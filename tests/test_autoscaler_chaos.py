"""Seeded autoscaler-chaos tier (core/autoscaler.py): the determinism and
crash-consistency half of the signal-driven gang autoscaler.

- 3-run byte-equal decision-log replay on fake clocks: the decision
  procedure is a pure function of (state, config), so the same scripted
  observation sequence must produce identical decision-log lines run
  over run — the core/policies.py contract, extended to the resize loop;
- chaos ``ScheduledCapacityRevocation`` mid-grow: the pool shrinks under
  a freshly-grown gang; the admission layer preempts to fit, the
  preempted job's ledger bump opens the autoscaler's cooldown window,
  and the fleet must settle WITHOUT flapping (no resize lands inside a
  cooldown window — audited from the ledger by
  check_autoscaler_invariants);
- crash-point sweep over the resize write window: the operator dies
  immediately before and immediately after the spec patch; a cold-
  started autoscaler (all hysteresis memory lost) must converge to the
  same target with EXACTLY ONE applied spec patch — idempotence of
  decide-over-current-spec is the exactly-once mechanism, not any
  durable intent record.
"""

import pytest

from tf_operator_tpu.cluster.chaos import (
    ChaosCluster,
    ChaosSpec,
    ScheduledCapacityRevocation,
    SimulatedCrash,
)
from tf_operator_tpu.cluster.memory import InMemoryCluster
from tf_operator_tpu.controllers.jax import JAXController
from tf_operator_tpu.core.admission import AdmissionController
from tf_operator_tpu.core.autoscaler import AutoscalerConfig, GangAutoscaler
from tf_operator_tpu.core.job_controller import EngineOptions
from tf_operator_tpu.core.tracing import Tracer
from tf_operator_tpu.core.workqueue import WorkQueue
from tf_operator_tpu.metrics import Metrics
from tf_operator_tpu.testing.invariants import (
    assert_invariants,
    check_autoscaler_invariants,
)

from test_autoscaler import (
    FakeClock,
    beat,
    drive_running,
    elastic_manifest,
    job_slices,
    rigid_manifest,
    running_workers,
    settle,
)


def build(capacity, clk, chaos_spec=None, seed=0):
    inner = InMemoryCluster(clock=clk)
    cluster = inner
    if chaos_spec is not None:
        cluster = ChaosCluster(inner, chaos_spec)
    metrics = Metrics()
    tracer = Tracer()
    adm = AdmissionController(
        capacity=capacity, clock=clk, metrics=metrics,
        capacity_fn=inner.schedulable_capacity,
    )
    controller = JAXController(
        cluster, queue=WorkQueue(clock=clk), options=EngineOptions(),
        clock=clk, metrics=metrics, tracer=tracer, admission=adm,
    )
    scaler = GangAutoscaler(
        cluster, adm,
        AutoscalerConfig(watermark_pods=1.0, hold_seconds=2.0,
                         dwell_seconds=4.0, cooldown_seconds=6.0,
                         seed=seed),
        clock=clk, metrics=metrics,
    )
    return inner, cluster, controller, adm, scaler, tracer


# ----------------------------------------------------- byte-equal replay


def scripted_run(seed):
    """One fully-scripted elasticity scenario on a fake clock: grow into
    surplus, a mid-run capacity revocation with queue pressure, a
    checkpoint-gated shrink, recovery. Returns the decision-log lines —
    the byte-equality artifact."""
    clk = FakeClock()
    inner, cluster, controller, adm, scaler, tracer = build(
        {"pods": "12"}, clk, seed=seed)
    inner.create_job(elastic_manifest("e0", slices=2, hosts=2,
                                      max_slices=5))
    inner.create_job(elastic_manifest("e1", slices=1, hosts=2,
                                      max_slices=5))
    settle(controller, clk, ["e0", "e1"])

    def step(seconds=1.0, ticks=1):
        for _ in range(ticks):
            clk.advance(seconds)
            scaler.tick()
            settle(controller, clk, ["e0", "e1"], rounds=4)

    step(seconds=2.5, ticks=3)   # surplus held: grows fire
    # Workloads report; e1 checkpoints.
    for name in ("e0", "e1"):
        for pod_name in running_workers(inner, name):
            beat(inner, pod_name, step=50, tps=400.0, ckpt=40)
    # Queue pressure arrives: a rigid job that cannot fit.
    inner.create_job(rigid_manifest("r0", workers=4))
    settle(controller, clk, ["e0", "e1", "r0"], rounds=4)
    step(seconds=1.0, ticks=2)   # propose shrink; blocked until fresh ckpt
    for name in ("e0", "e1"):
        for pod_name in running_workers(inner, name):
            beat(inner, pod_name, step=90, tps=400.0, ckpt=80)
    step(seconds=5.0, ticks=4)   # shrink applies (dwell-paced), r0 admits
    # Capacity churn: the seeded revocation effect, then restore.
    inner.set_schedulable_capacity({"pods": "6"})
    settle(controller, clk, ["e0", "e1", "r0"], rounds=6)
    step(seconds=1.0, ticks=2)
    inner.set_schedulable_capacity(None)
    step(seconds=3.0, ticks=4)
    violations = check_autoscaler_invariants(
        scaler, cluster=inner, kinds=("JAXJob",))
    assert violations == [], violations
    return scaler.decision_log_lines()


class TestDecisionLogReplay:
    def test_three_runs_byte_equal(self):
        runs = [scripted_run(seed=7) for _ in range(3)]
        assert runs[0], "scenario produced no decisions at all"
        assert runs[0] == runs[1] == runs[2]

    def test_seed_is_threaded_into_the_log(self):
        lines = scripted_run(seed=13)
        assert all('"seed":13' in line for line in lines)


# ---------------------------------------------- revocation mid-grow


class TestRevocationMidGrow:
    def test_scheduled_revocation_opens_cooldown_no_flap(self):
        """The chaos ScheduledCapacityRevocation fires on the write
        clock right after the autoscaler's grow lands: the pool shrinks
        under the freshly-grown gang, admission preempts to fit, and the
        disruption must open the cooldown window — the ledger shows no
        resize inside it (anti-flap), and the fleet converges."""
        clk = FakeClock()
        spec = ChaosSpec(
            seed=11,
            capacity_revocations=(
                # Fires once the write clock passes the grown world's
                # recreation — i.e. mid-grow, the worst moment.
                ScheduledCapacityRevocation(
                    after_writes=40, capacity={"pods": "4"}),
            ),
        )
        inner, cluster, controller, adm, scaler, tracer = build(
            {"pods": "8"}, clk, chaos_spec=spec)
        inner.create_job(elastic_manifest("e0", slices=2, hosts=2,
                                          max_slices=4))
        settle(controller, clk, ["e0"])
        assert len(running_workers(inner, "e0")) == 4

        grew = revoked = False
        for _ in range(40):
            clk.advance(1.0)
            scaler.tick()
            settle(controller, clk, ["e0"], rounds=4)
            grew = grew or job_slices(inner, "e0") > 2
            revoked = revoked or any(
                "capacity-revoke" in f for f in cluster.fault_log
            )
            if grew and revoked:
                break
        assert grew, "the autoscaler never grew into the surplus"
        assert revoked, "the scheduled revocation never fired"
        # Let the preempt-to-fit and cooldown play out.
        for _ in range(12):
            clk.advance(1.0)
            scaler.tick()
            settle(controller, clk, ["e0"], rounds=4)
        status = (
            inner.get_job("JAXJob", "default", "e0").get("status") or {}
        )
        assert sum((status.get("disruptionCounts") or {}).values()) >= 1
        violations = check_autoscaler_invariants(
            scaler, cluster=inner, kinds=("JAXJob",))
        assert violations == [], violations
        assert_invariants(inner, kinds=("JAXJob",), tracer=tracer,
                          admission=adm, autoscaler=scaler,
                          label="autoscaler_revocation")


# ------------------------------------------------- crash-point sweep


class ResizeCrashProxy:
    """Wraps the autoscaler's cluster seam and plants one SimulatedCrash
    in the resize write window: variant 'before' dies with the spec
    patch unwritten, 'after' dies with it durable. Counts the spec
    patches that actually landed — the exactly-once artifact."""

    def __init__(self, inner, variant):
        self._inner = inner
        self._variant = variant
        self._armed = True
        self.applied = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def update_job(self, job_dict):
        if self._armed:
            self._armed = False
            if self._variant == "before":
                raise SimulatedCrash("crash before resize write")
            out = self._inner.update_job(job_dict)
            self.applied += 1
            raise SimulatedCrash("crash after resize write")
        out = self._inner.update_job(job_dict)
        self.applied += 1
        return out


class TestResizeCrashWindow:
    @pytest.mark.parametrize("variant", ["before", "after"])
    def test_exactly_once_spec_patch_across_crash(self, variant):
        clk = FakeClock()
        inner = InMemoryCluster(clock=clk)
        metrics = Metrics()
        adm = AdmissionController(
            capacity={"pods": "8"}, clock=clk, metrics=metrics,
            capacity_fn=inner.schedulable_capacity,
        )
        controller = JAXController(
            inner, queue=WorkQueue(clock=clk), options=EngineOptions(),
            clock=clk, metrics=metrics, tracer=Tracer(), admission=adm,
        )
        proxy = ResizeCrashProxy(inner, variant)
        config = AutoscalerConfig(watermark_pods=1.0, hold_seconds=2.0,
                                  dwell_seconds=4.0, cooldown_seconds=6.0)
        scaler = GangAutoscaler(proxy, adm, config, clock=clk,
                                metrics=metrics)
        inner.create_job(elastic_manifest("e0", slices=2, hosts=2,
                                          max_slices=3))
        settle(controller, clk, ["e0"])
        assert len(running_workers(inner, "e0")) == 4

        scaler.tick()  # arms the surplus hold clock
        clk.advance(2.5)
        with pytest.raises(SimulatedCrash):
            scaler.tick()  # the operator dies in the resize write window
        # Cold start: a fresh autoscaler instance, all memory lost.
        scaler = GangAutoscaler(proxy, adm, config, clock=clk,
                                metrics=metrics)
        for _ in range(4):
            clk.advance(2.5)
            scaler.tick()
            settle(controller, clk, ["e0"], rounds=4)
        # Exactly one spec patch landed and the target was reached —
        # never zero (lost resize), never two (doubled resize).
        assert job_slices(inner, "e0") == 3
        assert proxy.applied == 1
        settle(controller, clk, ["e0"])
        assert len(running_workers(inner, "e0")) == 6
        violations = check_autoscaler_invariants(
            scaler, cluster=inner, kinds=("JAXJob",))
        assert violations == [], violations
