"""Validation tests, modeled on the reference's pkg/apis/*/validation tests."""

import pytest

from tf_operator_tpu.api import common, jaxjob, mxjob, pytorchjob, tfjob, xgboostjob
from tf_operator_tpu.api.defaulting import ValidationError
from tf_operator_tpu.api.k8s import Container, PodSpec, PodTemplateSpec


def replica(container_name, image="img", replicas=1):
    return common.ReplicaSpec(
        replicas=replicas,
        template=PodTemplateSpec(
            spec=PodSpec(containers=[Container(name=container_name, image=image)])
        ),
    )


class TestTFJobValidation:
    def test_nil_specs_invalid(self):
        with pytest.raises(ValidationError):
            tfjob.validate(tfjob.TFJobSpec())

    def test_valid_spec(self):
        spec = tfjob.TFJobSpec(
            tf_replica_specs={
                tfjob.REPLICA_TYPE_WORKER: replica("tensorflow"),
                tfjob.REPLICA_TYPE_PS: replica("tensorflow"),
            }
        )
        tfjob.validate(spec)

    def test_missing_image_invalid(self):
        spec = tfjob.TFJobSpec(
            tf_replica_specs={tfjob.REPLICA_TYPE_WORKER: replica("tensorflow", image="")}
        )
        with pytest.raises(ValidationError, match="Image is undefined"):
            tfjob.validate(spec)

    def test_wrong_container_name_invalid(self):
        spec = tfjob.TFJobSpec(
            tf_replica_specs={tfjob.REPLICA_TYPE_WORKER: replica("not-tensorflow")}
        )
        with pytest.raises(ValidationError, match="no container named tensorflow"):
            tfjob.validate(spec)

    def test_two_chiefs_invalid(self):
        spec = tfjob.TFJobSpec(
            tf_replica_specs={
                tfjob.REPLICA_TYPE_CHIEF: replica("tensorflow"),
                tfjob.REPLICA_TYPE_MASTER: replica("tensorflow"),
            }
        )
        with pytest.raises(ValidationError, match="more than 1 chief/master"):
            tfjob.validate(spec)

    def test_no_containers_invalid(self):
        spec = tfjob.TFJobSpec(
            tf_replica_specs={
                tfjob.REPLICA_TYPE_WORKER: common.ReplicaSpec(template=PodTemplateSpec())
            }
        )
        with pytest.raises(ValidationError, match="containers definition expected"):
            tfjob.validate(spec)


class TestPyTorchJobValidation:
    def test_master_required(self):
        spec = pytorchjob.PyTorchJobSpec(
            pytorch_replica_specs={pytorchjob.REPLICA_TYPE_WORKER: replica("pytorch")}
        )
        with pytest.raises(ValidationError, match="Master ReplicaSpec must be present"):
            pytorchjob.validate(spec)

    def test_single_master_enforced(self):
        spec = pytorchjob.PyTorchJobSpec(
            pytorch_replica_specs={
                pytorchjob.REPLICA_TYPE_MASTER: replica("pytorch", replicas=2)
            }
        )
        with pytest.raises(ValidationError, match="only 1 master"):
            pytorchjob.validate(spec)

    def test_invalid_replica_type(self):
        spec = pytorchjob.PyTorchJobSpec(
            pytorch_replica_specs={
                pytorchjob.REPLICA_TYPE_MASTER: replica("pytorch"),
                "Chief": replica("pytorch"),
            }
        )
        with pytest.raises(ValidationError, match="must be one of"):
            pytorchjob.validate(spec)

    def test_valid(self):
        spec = pytorchjob.PyTorchJobSpec(
            pytorch_replica_specs={
                pytorchjob.REPLICA_TYPE_MASTER: replica("pytorch"),
                pytorchjob.REPLICA_TYPE_WORKER: replica("pytorch", replicas=3),
            }
        )
        pytorchjob.validate(spec)


class TestMXJobValidation:
    def test_two_schedulers_invalid(self):
        spec = mxjob.MXJobSpec(
            mx_replica_specs={
                mxjob.REPLICA_TYPE_SCHEDULER: replica("mxnet"),
            }
        )
        mxjob.validate(spec)  # one scheduler fine

    def test_container_name(self):
        spec = mxjob.MXJobSpec(mx_replica_specs={mxjob.REPLICA_TYPE_WORKER: replica("bad")})
        with pytest.raises(ValidationError):
            mxjob.validate(spec)


class TestXGBoostJobValidation:
    def test_master_required(self):
        spec = xgboostjob.XGBoostJobSpec(
            xgb_replica_specs={xgboostjob.REPLICA_TYPE_WORKER: replica("xgboost")}
        )
        with pytest.raises(ValidationError, match="Master ReplicaSpec must be present"):
            xgboostjob.validate(spec)

    def test_valid(self):
        spec = xgboostjob.XGBoostJobSpec(
            xgb_replica_specs={
                xgboostjob.REPLICA_TYPE_MASTER: replica("xgboost"),
                xgboostjob.REPLICA_TYPE_WORKER: replica("xgboost", replicas=2),
            }
        )
        xgboostjob.validate(spec)


class TestJAXJobValidation:
    def test_valid(self):
        spec = jaxjob.JAXJobSpec(
            jax_replica_specs={jaxjob.REPLICA_TYPE_WORKER: replica("jax", replicas=8)},
            tpu=jaxjob.TPUSpec(accelerator_type="v5e-32"),
        )
        jaxjob.validate(spec)

    def test_unknown_accelerator(self):
        spec = jaxjob.JAXJobSpec(
            jax_replica_specs={jaxjob.REPLICA_TYPE_WORKER: replica("jax")},
            tpu=jaxjob.TPUSpec(accelerator_type="v99-1"),
        )
        with pytest.raises(ValidationError, match="unknown TPU accelerator"):
            jaxjob.validate(spec)

    def test_replica_topology_mismatch(self):
        spec = jaxjob.JAXJobSpec(
            jax_replica_specs={jaxjob.REPLICA_TYPE_WORKER: replica("jax", replicas=3)},
            tpu=jaxjob.TPUSpec(accelerator_type="v5e-32"),  # needs 8 hosts
        )
        with pytest.raises(ValidationError, match="requires 8 workers"):
            jaxjob.validate(spec)

    def test_mesh_chip_count_mismatch(self):
        spec = jaxjob.JAXJobSpec(
            jax_replica_specs={jaxjob.REPLICA_TYPE_WORKER: replica("jax", replicas=8)},
            tpu=jaxjob.TPUSpec(accelerator_type="v5e-32"),
            mesh={"fsdp": 8, "tp": 2},  # 16 != 32
        )
        with pytest.raises(ValidationError, match="mesh"):
            jaxjob.validate(spec)

    def test_mesh_matching_chips_valid(self):
        spec = jaxjob.JAXJobSpec(
            jax_replica_specs={jaxjob.REPLICA_TYPE_WORKER: replica("jax", replicas=8)},
            tpu=jaxjob.TPUSpec(accelerator_type="v5e-32"),
            mesh={"fsdp": 8, "tp": 4},
        )
        jaxjob.validate(spec)

    def test_min_slices_quorum_bounds(self):
        def spec(**kw):
            return jaxjob.JAXJobSpec(
                jax_replica_specs={
                    jaxjob.REPLICA_TYPE_WORKER: replica("jax", replicas=8)
                },
                num_slices=4,
                **kw,
            )

        jaxjob.validate(spec(min_slices=2))
        with pytest.raises(ValidationError, match="minSlices must be >= 1"):
            jaxjob.validate(spec(min_slices=0))
        with pytest.raises(ValidationError, match="exceeds numSlices"):
            jaxjob.validate(spec(min_slices=5))

    def test_elastic_below_quorum_rejected(self):
        """elastic.minSlices < minSlices would let a perfectly legal
        scale() produce a spec validation must reject — bricking the
        live job at its next sync. The inconsistent declaration is
        refused up front instead."""
        spec = jaxjob.JAXJobSpec(
            jax_replica_specs={
                jaxjob.REPLICA_TYPE_WORKER: replica("jax", replicas=8)
            },
            num_slices=4,
            min_slices=2,
            elastic=jaxjob.ElasticPolicy(min_slices=1),
        )
        with pytest.raises(ValidationError, match="below the restart quorum"):
            jaxjob.validate(spec)
        spec.elastic = jaxjob.ElasticPolicy(min_slices=2)
        jaxjob.validate(spec)

    def test_exit_code_retry_taxonomy(self):
        # 1-127 permanent, 128+ retryable (reference design doc :84).
        assert not common.is_retryable_exit_code(1)
        assert not common.is_retryable_exit_code(127)
        assert common.is_retryable_exit_code(128)
        assert common.is_retryable_exit_code(137)


class TestRunPolicyLivenessValidation:
    """Gang-liveness deadline admission rules (docs/design/failure_modes.md
    §8): positive ints only, rendezvous requires progress, both default
    unset so heartbeat-less jobs can never stall-restart."""

    def _spec(self, **rp):
        return tfjob.TFJobSpec(
            run_policy=common.RunPolicy(**rp),
            tf_replica_specs={tfjob.REPLICA_TYPE_WORKER: replica("tensorflow")},
        )

    def test_valid_deadlines(self):
        tfjob.validate(self._spec(progress_deadline_seconds=300))
        tfjob.validate(
            self._spec(progress_deadline_seconds=300,
                       rendezvous_deadline_seconds=600)
        )

    @pytest.mark.parametrize("bad", [0, -1, 1.5, "soon", True])
    def test_progress_deadline_must_be_positive_int(self, bad):
        with pytest.raises(ValidationError, match="progressDeadlineSeconds"):
            tfjob.validate(self._spec(progress_deadline_seconds=bad))

    @pytest.mark.parametrize("bad", [0, -30, "fast", False])
    def test_rendezvous_deadline_must_be_positive_int(self, bad):
        with pytest.raises(ValidationError, match="rendezvousDeadlineSeconds"):
            tfjob.validate(
                self._spec(progress_deadline_seconds=60,
                           rendezvous_deadline_seconds=bad)
            )

    def test_rendezvous_requires_progress_opt_in(self):
        with pytest.raises(ValidationError, match="requires runPolicy.progressDeadlineSeconds"):
            tfjob.validate(self._spec(rendezvous_deadline_seconds=60))

    def test_every_kind_validates_run_policy(self):
        rp = common.RunPolicy(rendezvous_deadline_seconds=60)
        cases = [
            (pytorchjob, pytorchjob.PyTorchJobSpec(
                run_policy=rp,
                pytorch_replica_specs={"Master": replica("pytorch")})),
            (mxjob, mxjob.MXJobSpec(
                run_policy=rp,
                mx_replica_specs={"Worker": replica("mxnet")})),
            (xgboostjob, xgboostjob.XGBoostJobSpec(
                run_policy=rp,
                xgb_replica_specs={"Master": replica("xgboost")})),
            (jaxjob, jaxjob.JAXJobSpec(
                run_policy=rp,
                jax_replica_specs={"Worker": replica("jax")})),
        ]
        for module, spec in cases:
            with pytest.raises(ValidationError, match="rendezvousDeadlineSeconds"):
                module.validate(spec)
