"""CI DAG runner: ordering, retries, skip-on-failure, junit output."""

import pathlib

import pytest

from ci.dag import CycleError, Step, default_dag, run_dag


def fake_runner(script):
    """script: {step_name: list of return codes per attempt}"""
    calls = []

    def run(step):
        codes = script[step.name]
        idx = min(len(calls_for(step.name)), len(codes) - 1)
        calls.append(step.name)
        return codes[idx], f"log:{step.name}"

    def calls_for(name):
        return [c for c in calls if c == name]

    run.calls = calls
    return run


class TestRunDag:
    def test_dependency_order_and_success(self):
        runner = fake_runner({"a": [0], "b": [0], "c": [0]})
        steps = [Step("a", ["x"]), Step("b", ["x"], deps=["a"]), Step("c", ["x"], deps=["b"])]
        run = run_dag(steps, log=lambda *a: None, runner=runner)
        assert run.ok
        assert runner.calls == ["a", "b", "c"]

    def test_failure_skips_dependents_but_not_siblings(self):
        runner = fake_runner({"a": [0], "bad": [1], "child": [0], "sib": [0]})
        steps = [
            Step("a", ["x"]),
            Step("bad", ["x"], deps=["a"]),
            Step("child", ["x"], deps=["bad"]),
            Step("sib", ["x"], deps=["a"]),
        ]
        run = run_dag(steps, log=lambda *a: None, runner=runner)
        assert not run.ok
        assert run.results["bad"].status == "failed"
        assert run.results["child"].status == "skipped"
        assert run.results["sib"].status == "passed"
        assert "child" not in runner.calls

    def test_retries_until_pass(self):
        runner = fake_runner({"flaky": [1, 0]})
        run = run_dag([Step("flaky", ["x"], retries=3)], log=lambda *a: None, runner=runner)
        assert run.ok
        assert run.results["flaky"].attempts == 2

    def test_cycle_rejected(self):
        with pytest.raises(CycleError):
            run_dag(
                [Step("a", ["x"], deps=["b"]), Step("b", ["x"], deps=["a"])],
                log=lambda *a: None,
                runner=fake_runner({}),
            )

    def test_unknown_dep_rejected(self):
        with pytest.raises(ValueError):
            run_dag([Step("a", ["x"], deps=["ghost"])], log=lambda *a: None,
                    runner=fake_runner({}))

    def test_junit_xml(self):
        runner = fake_runner({"a": [0], "b": [1]})
        run = run_dag([Step("a", ["x"]), Step("b", ["x"])], log=lambda *a: None, runner=runner)
        xml = run.junit_xml()
        assert 'tests="2"' in xml and 'failures="1"' in xml and "<failure" in xml


class TestDefaultDag:
    def test_acyclic_and_files_exist(self):
        steps = default_dag()
        # _validate runs inside run_dag; here just check referenced paths.
        from ci.dag import _validate

        _validate(steps)
        repo = pathlib.Path(__file__).resolve().parent.parent
        for s in steps:
            for arg in s.command:
                if str(arg).startswith("tests/"):
                    assert (repo / arg).exists(), f"{s.name}: missing {arg}"

    def test_real_subprocess_step(self):
        import sys

        run = run_dag(
            [Step("echo", [sys.executable, "-c", "print('hi')"])],
            log=lambda *a: None,
        )
        assert run.ok

    def test_missing_binary_records_failure_not_hang(self):
        # A crashed subprocess launch must surface as a failed StepResult so
        # dependents are skipped and the run reports red (not green/hang).
        run = run_dag(
            [
                Step("ghost", ["definitely-not-a-binary-xyz"]),
                Step("child", ["x"], deps=["ghost"]),
            ],
            log=lambda *a: None,
        )
        assert not run.ok
        assert run.results["ghost"].status == "failed"
        assert "FileNotFoundError" in run.results["ghost"].log
        assert run.results["child"].status == "skipped"

    def test_junit_escapes_quotes_in_names(self):
        runner = fake_runner({'run "fast"': [0]})
        run = run_dag([Step('run "fast"', ["x"])], log=lambda *a: None, runner=runner)
        import xml.dom.minidom

        xml.dom.minidom.parseString(run.junit_xml())  # must be well-formed

    def test_cli_only_unknown_step(self, capsys):
        from ci.__main__ import main

        assert main(["--only", "no-such-step"]) == 2
        assert "available" in capsys.readouterr().err
