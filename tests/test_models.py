"""Model zoo: the non-flagship BASELINE configs (mnist/resnet/bert).

The reference ships workloads as examples with e2e assertions only; here
each model family gets direct numerics tests (forward shape, gradient flow,
loss decrease) at CI-sized configs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tf_operator_tpu.models import bert, mnist, resnet


class TestMnist:
    def test_forward_shape(self):
        model = mnist.make_model()
        params = mnist.init_params(model, jax.random.PRNGKey(0), batch=2)
        logits = model.apply({"params": params}, jnp.zeros((2, 28, 28, 1)))
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32

    def test_learns_synthetic_task(self):
        model = mnist.make_model()
        params = mnist.init_params(model, jax.random.PRNGKey(0), batch=1)
        tx = optax.sgd(0.05, momentum=0.9)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, images, labels):
            (loss, acc), grads = jax.value_and_grad(
                lambda p: mnist.loss_and_accuracy(model, p, images, labels),
                has_aux=True,
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss, acc

        data = mnist.SyntheticMnist(32, seed=0)
        first_loss = None
        for i, (images, labels) in zip(range(60), data):
            params, opt_state, loss, acc = step(params, opt_state, images, labels)
            if first_loss is None:
                first_loss = float(loss)
        assert float(loss) < first_loss * 0.5
        assert float(acc) > 0.8


class TestResNet:
    def test_forward_and_batchstats(self):
        model = resnet.make_model("resnet-tiny")
        variables = resnet.init_variables(model, jax.random.PRNGKey(0), batch=2, image_size=32)
        assert "batch_stats" in variables
        logits, mutated = model.apply(
            variables, jnp.ones((2, 32, 32, 3)), train=True, mutable=["batch_stats"]
        )
        assert logits.shape == (2, 8)
        # Running statistics must move under train=True.
        before = jax.tree.leaves(variables["batch_stats"])
        after = jax.tree.leaves(mutated["batch_stats"])
        assert any(not np.allclose(b, a) for b, a in zip(before, after))

    def test_eval_deterministic(self):
        model = resnet.make_model("resnet-tiny")
        variables = resnet.init_variables(model, jax.random.PRNGKey(0), batch=1, image_size=32)
        x = jnp.ones((1, 32, 32, 3))
        a = model.apply(variables, x, train=False)
        b = model.apply(variables, x, train=False)
        assert np.allclose(a, b)

    def test_resnet50_config_is_bottleneck_50_layer(self):
        cfg = resnet.CONFIGS["resnet50"]
        assert cfg.bottleneck
        # 3+4+6+3 bottleneck blocks x3 convs + stem + fc = 50
        assert sum(cfg.stage_sizes) * 3 + 2 == 50


class TestBert:
    def test_forward_shape_and_mask(self):
        model = bert.make_model("bert-tiny")
        params = bert.init_params(model, jax.random.PRNGKey(0), batch=2, seq=16)
        ids = jnp.ones((2, 16), jnp.int32)
        mask = jnp.ones((2, 16), bool).at[:, 8:].set(False)
        logits = model.apply({"params": params}, ids, attention_mask=mask)
        assert logits.shape == (2, 16, model.config.vocab_size)

    def test_padding_does_not_leak(self):
        """Masked-out positions must not influence visible positions."""
        model = bert.make_model("bert-tiny")
        params = bert.init_params(model, jax.random.PRNGKey(0), batch=1, seq=8)
        mask = jnp.ones((1, 8), bool).at[:, 4:].set(False)
        a = jnp.array([[5, 6, 7, 8, 9, 9, 9, 9]], jnp.int32)
        b = jnp.array([[5, 6, 7, 8, 100, 101, 102, 103]], jnp.int32)
        la = model.apply({"params": params}, a, attention_mask=mask)
        lb = model.apply({"params": params}, b, attention_mask=mask)
        assert np.allclose(la[:, :4], lb[:, :4], atol=1e-5)

    def test_base_param_count_matches_published(self):
        # BERT-base is ~110M parameters.
        assert 105e6 < bert.CONFIGS["bert-base"].param_count() < 115e6

    def test_gradients_flow(self):
        model = bert.make_model("bert-tiny")
        params = bert.init_params(model, jax.random.PRNGKey(0), batch=1, seq=8)
        ids = jnp.ones((1, 8), jnp.int32)

        def loss_fn(p):
            return model.apply({"params": p}, ids).astype(jnp.float32).mean()

        grads = jax.grad(loss_fn)(params)
        norms = [float(jnp.abs(g).max()) for g in jax.tree.leaves(grads)]
        assert any(n > 0 for n in norms)
