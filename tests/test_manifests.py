"""Manifest generation (L6): schema shape, spec round-trip, RBAC/deployment
completeness. Reference: manifests/base/** (SURVEY.md §2.8)."""

import pytest

from tf_operator_tpu.api import jaxjob, tfjob
from tf_operator_tpu.manifests import generate_all, generate_crd, operator_manifests


def schema_accepts(schema: dict, value) -> bool:
    """Tiny structural-schema checker: enough to prove generated schemas
    describe what the API layer serializes."""
    if "x-kubernetes-preserve-unknown-fields" in schema and "type" not in schema:
        return True
    t = schema.get("type")
    if t == "object":
        if not isinstance(value, dict):
            return False
        props = schema.get("properties", {})
        additional = schema.get("additionalProperties")
        for key, sub in value.items():
            if key in props:
                if not schema_accepts(props[key], sub):
                    return False
            elif additional is not None:
                if not schema_accepts(additional, sub):
                    return False
            elif not schema.get("x-kubernetes-preserve-unknown-fields"):
                return False
        return True
    if t == "array":
        return isinstance(value, list) and all(
            schema_accepts(schema.get("items", {}), v) for v in value
        )
    if t == "string":
        return isinstance(value, str)
    if t == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if t == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if t == "boolean":
        return isinstance(value, bool)
    return True


def crd_spec_schema(module) -> dict:
    crd = generate_crd(module)
    version = crd["spec"]["versions"][0]
    return version["schema"]["openAPIV3Schema"]["properties"]["spec"]


class TestCRDGeneration:
    def test_all_five_kinds_generated(self):
        docs = generate_all()
        crds = [k for k in docs if k.startswith("crds/")]
        assert len(crds) == 5
        assert "crds/kubeflow.org_jaxjobs" in docs

    def test_crd_identity_fields(self):
        crd = generate_crd(tfjob)
        assert crd["metadata"]["name"] == "tfjobs.kubeflow.org"
        assert crd["spec"]["names"]["kind"] == "TFJob"
        version = crd["spec"]["versions"][0]
        assert version["subresources"] == {"status": {}}
        assert version["served"] and version["storage"]

    def test_tfjob_schema_has_framework_fields(self):
        spec = crd_spec_schema(tfjob)["properties"]
        assert "tfReplicaSpecs" in spec
        assert "successPolicy" in spec
        assert "enableDynamicWorker" in spec
        run_policy = spec["runPolicy"]["properties"]
        assert {"cleanPodPolicy", "backoffLimit", "activeDeadlineSeconds",
                "ttlSecondsAfterFinished", "schedulingPolicy"} <= set(run_policy)

    def test_jaxjob_schema_has_tpu_fields(self):
        spec = crd_spec_schema(jaxjob)["properties"]
        assert {"tpu", "numSlices", "mesh"} <= set(spec)
        tpu = spec["tpu"]["properties"]
        assert {"acceleratorType", "topology", "chipsPerHost"} <= set(tpu)
        assert spec["mesh"]["additionalProperties"]["type"] == "integer"

    def test_schema_accepts_serialized_job(self):
        from tf_operator_tpu.api import parse_job
        from tf_operator_tpu.api.jaxjob import set_defaults

        job = parse_job(
            {
                "apiVersion": "kubeflow.org/v1",
                "kind": "JAXJob",
                "metadata": {"name": "j", "namespace": "n"},
                "spec": {
                    "tpu": {"acceleratorType": "v5e-32", "topology": "4x8"},
                    "numSlices": 2,
                    "mesh": {"slice": 2, "fsdp": 8, "tp": 4},
                    "jaxReplicaSpecs": {
                        "Worker": {
                            "template": {"spec": {"containers": [{"name": "jax", "image": "i"}]}}
                        }
                    },
                },
            }
        )
        set_defaults(job)
        serialized = job.to_dict()["spec"]
        assert schema_accepts(crd_spec_schema(jaxjob), serialized), serialized

    def test_schema_rejects_wrong_types(self):
        schema = crd_spec_schema(jaxjob)
        assert not schema_accepts(schema, {"numSlices": "two"})
        assert not schema_accepts(schema, {"unknownField": 1})


class TestOperatorManifests:
    def test_rbac_covers_all_plurals_and_status(self):
        docs = operator_manifests()
        role = next(d for d in docs if d["kind"] == "ClusterRole")
        crd_rule = role["rules"][0]
        for plural in ("tfjobs", "pytorchjobs", "mxjobs", "xgboostjobs", "jaxjobs"):
            assert plural in crd_rule["resources"]
            assert f"{plural}/status" in crd_rule["resources"]
        core_rule = role["rules"][1]
        assert {"pods", "services", "events"} <= set(core_rule["resources"])

    def test_deployment_probes_and_entrypoint(self):
        docs = operator_manifests()
        deploy = next(d for d in docs if d["kind"] == "Deployment")
        container = deploy["spec"]["template"]["spec"]["containers"][0]
        assert container["command"] == [
            "python", "-m", "tf_operator_tpu", "--kube", "--leader-elect",
        ]  # in-cluster: real apiserver + Lease election (2 replicas)
        assert container["livenessProbe"]["httpGet"]["path"] == "/healthz"
        assert container["readinessProbe"]["httpGet"]["path"] == "/readyz"

    def test_yaml_round_trip(self, tmp_path):
        import yaml

        from tf_operator_tpu.manifests import write_manifests

        paths = write_manifests(str(tmp_path))
        assert len(paths) == 6
        for path in paths:
            docs = list(yaml.safe_load_all(open(path)))
            assert docs and all(isinstance(d, dict) for d in docs)
