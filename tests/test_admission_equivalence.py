"""Schedule-equivalence property tier for the admissibility index
(docs/design/gang_admission.md, "Admissibility index"): the indexed
arbiter is a pure PRUNING filter over ``policy.decide`` — for any call
sequence it must produce the byte-identical decision log, the same
admitted/waiting/preempting sets, the same queue positions, and the
same blocked verdicts as the full-scan arbiter.

Two layers of evidence:

- A seeded randomized PAIRED DRIVER: the same operation trace (new
  gangs, steady-state re-asks, elastic demand changes, releases,
  engine-style preemption acks, clock advances) is fed to a full-scan
  controller and an indexed controller in lockstep, and the complete
  observable state is compared after EVERY operation — a divergence
  fails at the exact step that introduced it, with the trace seed in
  the test id for replay.
- FleetSim digest equality per policy: a whole storm scenario (arrival
  trace + decision logs + fault log + terminal states, hashed) must
  not move by one byte when the flag flips.

Runs in the admission-chaos CI tier (ci/dag.py) beside the seeded
admission scenarios.
"""

import random
from fractions import Fraction

import pytest

from tf_operator_tpu.core.admission import AdmissionController
from tf_operator_tpu.metrics import Metrics

NAMESPACES = ("tenant-a", "tenant-b", "tenant-c")
BANDS = ("low", "", "default", "high", "critical")


class FakeClock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


def make_pair(policy, quotas=None, generations=None, weights=None, seed=0):
    """(full-scan, indexed) controllers over identical configuration.
    Only the index flag differs — that flag is the thing under test."""
    pair = []
    for index in (False, True):
        clock = FakeClock()
        adm = AdmissionController(
            capacity={"pods": "16"} if generations is None else None,
            quotas=quotas, generations=generations, tenant_weights=weights,
            policy=policy, seed=seed, clock=clock, metrics=Metrics(),
            aging_seconds=120.0, backfill_max_members=4,
            admission_index=index,
        )
        pair.append((adm, clock))
    return pair


def observable(adm):
    """Everything the engine (and the determinism audit) can see."""
    snap = adm.snapshot()
    return {
        "admitted": sorted(g["key"] for g in snap["admitted"]),
        "waiting": [
            (w["key"], w["band"], w["position"], w["blocked_on"])
            for w in snap["waiting"]
        ],
        "preempting": snap["preempting"],
        "usage": snap["usage"],
        "namespace_usage": snap["namespace_usage"],
        "dominant_shares": snap["dominant_shares"],
        "log": adm.decision_log_lines(),
    }


def assert_equivalent(pair, context):
    full, indexed = observable(pair[0][0]), observable(pair[1][0])
    assert indexed == full, f"diverged after {context}"


class PairedDriver:
    """Feeds one randomized operation trace to both controllers and
    checks full observable equality after every single operation."""

    def __init__(self, policy, seed, quotas=None, generations=None,
                 weights=None):
        self.rng = random.Random(seed)
        self.generations = generations
        self.pair = make_pair(
            policy, quotas=quotas, generations=generations,
            weights=weights, seed=seed)
        self.specs = {}  # key -> ask kwargs (kept identical across asks)
        self.counter = 0

    def ask_both(self, key, has_pods=False):
        spec = self.specs[key]
        for adm, _ in self.pair:
            adm.try_admit(key=key, has_pods=has_pods, **spec)

    def op_new(self):
        self.counter += 1
        ns = self.rng.choice(NAMESPACES)
        name = f"job-{self.counter:03d}"
        pods = self.rng.randint(1, 6)
        ratios = None
        if self.generations and self.rng.random() < 0.5:
            ratios = {
                gen: self.rng.choice((0.4, 0.7, 1.0))
                for gen in self.generations
            }
        key = f"JAXJob:{ns}/{name}"
        self.specs[key] = dict(
            kind="JAXJob", namespace=ns, name=name, uid=f"uid-{name}",
            priority_class=self.rng.choice(BANDS),
            demand={"pods": Fraction(pods)}, members=pods,
            throughput_ratios=ratios,
        )
        self.ask_both(key, has_pods=self.rng.random() < 0.1)
        return f"new {key}"

    def op_reask(self):
        if not self.specs:
            return self.op_new()
        key = self.rng.choice(sorted(self.specs))
        if self.rng.random() < 0.25:  # elastic resize: decide-relevant
            pods = self.rng.randint(1, 6)
            self.specs[key]["demand"] = {"pods": Fraction(pods)}
            self.specs[key]["members"] = pods
        self.ask_both(key)
        return f"reask {key}"

    def op_release(self):
        if not self.specs:
            return self.op_new()
        key = self.rng.choice(sorted(self.specs))
        del self.specs[key]
        for adm, _ in self.pair:
            adm.release(key)
        return f"release {key}"

    def op_ack(self):
        pending = sorted(self.pair[0][0].snapshot()["preempting"])
        if not pending:
            return self.op_tick()
        key = pending[0]
        uid = self.specs.get(key, {}).get("uid", "uid-gone")
        for adm, _ in self.pair:
            adm.note_preempted(key, uid)
        return f"ack {key}"

    def op_tick(self):
        seconds = self.rng.choice((5.0, 30.0, 90.0, 200.0))
        for _, clock in self.pair:
            clock.advance(seconds)
        return f"tick {seconds}"

    def run(self, steps=120):
        ops = (
            (self.op_new, 4), (self.op_reask, 4), (self.op_release, 2),
            (self.op_ack, 2), (self.op_tick, 2),
        )
        table = [op for op, weight in ops for _ in range(weight)]
        for step in range(steps):
            context = f"step {step}: {self.rng.choice(table)()}"
            assert_equivalent(self.pair, context)
        # Drain: ack every pending preemption, then release everything,
        # still in lockstep — the tail (emptying queues, watermark
        # teardown) is where removal bookkeeping bugs hide.
        while True:
            pending = sorted(self.pair[0][0].snapshot()["preempting"])
            if not pending:
                break
            for key in pending:
                uid = self.specs.get(key, {}).get("uid", "uid-gone")
                for adm, _ in self.pair:
                    adm.note_preempted(key, uid)
                assert_equivalent(self.pair, f"drain ack {key}")
        for key in sorted(self.specs):
            for adm, _ in self.pair:
                adm.release(key)
            assert_equivalent(self.pair, f"drain release {key}")
        indexed = self.pair[1][0]
        assert indexed._band_order == {}
        assert indexed._usage_idx == {}
        assert indexed._ns_usage_idx == {}


SEEDS = (1, 2, 3)


class TestPairedTraces:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_priority(self, seed):
        PairedDriver("priority", seed).run()

    @pytest.mark.parametrize("seed", SEEDS)
    def test_gavel_generations(self, seed):
        PairedDriver(
            "gavel", seed,
            generations={"v5lite": {"pods": "8"}, "v6": {"pods": "8"}},
        ).run()

    @pytest.mark.parametrize("seed", SEEDS)
    def test_drf_weighted(self, seed):
        # drf declines the prune contract: the indexed controller runs
        # decide over the full maintained state — still byte-equal.
        PairedDriver(
            "drf", seed,
            weights={"tenant-a": 2.0, "tenant-b": 1.0},
        ).run()

    @pytest.mark.parametrize("seed", SEEDS)
    def test_priority_quota_fallback(self, seed):
        # Quotas also decline the prune (head-of-line selection is
        # quota-aware); only the no-op short-circuit remains active.
        PairedDriver(
            "priority", seed,
            quotas={"tenant-a": {"pods": "6"}, "tenant-b": {"pods": "6"}},
        ).run()

    @pytest.mark.parametrize("seed", SEEDS)
    def test_drf_every_tenant_weighted(self, seed):
        # ROADMAP soak toward the index default flip: uneven weights on
        # EVERY tenant, so no ask ever hits the unweighted fast path and
        # each admission re-sorts the full share order.
        PairedDriver(
            "drf", seed,
            weights={"tenant-a": 3.0, "tenant-b": 1.5, "tenant-c": 0.5},
        ).run()

    @pytest.mark.parametrize("seed", SEEDS)
    def test_gavel_generations_with_quotas(self, seed):
        # Generations AND namespace quotas together: gavel's per-
        # generation placement composes with the quota decline, so both
        # prune-decline paths are exercised in one trace.
        PairedDriver(
            "gavel", seed,
            generations={"v5lite": {"pods": "8"}, "v6": {"pods": "8"}},
            quotas={"tenant-a": {"pods": "7"}, "tenant-c": {"pods": "5"}},
        ).run()


class TestFleetSimDigest:
    @pytest.mark.parametrize("policy", ["priority", "gavel", "drf"])
    def test_digest_unmoved_by_the_flag(self, policy):
        import dataclasses

        from tf_operator_tpu.testing.fleetsim import (
            FleetSim, Scenario, StormEvent,
        )

        scenario = Scenario(
            name=f"index-eq-{policy}", seed=71, profile="bursty",
            jobs=120, tenants=6, horizon=1800.0, capacity_pods=24,
            policy=policy, aging_seconds=300.0,
            storm=[
                StormEvent(t=300.0, kind="revoke-capacity",
                           capacity={"pods": "12"}),
                StormEvent(t=900.0, kind="revoke-capacity",
                           capacity={"pods": "24"}),
            ],
        )
        full = FleetSim(scenario).run()
        indexed = FleetSim(
            dataclasses.replace(scenario, admission_index=True)).run()
        assert indexed["digest"] == full["digest"]
        assert indexed["completed"] == full["completed"] == full["jobs"]
        assert indexed["invariant_violations"] == []
