"""Slow-start fan-out tier (ISSUE 4): the parallel replica write path and
everything it must NOT break.

- slow_start_batch semantics: doubling waves, bounded pool, first-error
  abort with an exact success count (a broken template costs one call);
- TokenBucket FIFO fairness: parallel fan-out makes contention on the
  shared --qps/--burst budget the common case — tokens are granted in
  arrival order and N contending threads drain in bounded time;
- expectation accounting around batches: whole-batch expect up front,
  rollback of exactly the failed remainder; service deletions now ride
  the same expectation protocol as pod deletions (the old asymmetry let
  a slow service delete race the next sync);
- the hard design constraint: chaos-tier determinism with fan-out
  enabled. The chaos seam declares supports_concurrent_writes=False, the
  engine serializes its batches there, and the same seed replays the
  same fault schedule byte-for-byte — plus a crash-point sweep across
  the batch-create window (crash at the k-th create, failover, converge,
  invariants green).
"""

import dataclasses
import threading
import time

from tf_operator_tpu.api.k8s import POD_PENDING, POD_RUNNING
from tf_operator_tpu.cluster.chaos import (
    ChaosCluster,
    ChaosSpec,
    CrashPoint,
    ScheduledPreemption,
)
from tf_operator_tpu.cluster.memory import InMemoryCluster
from tf_operator_tpu.cluster.throttled import LatencyCluster
from tf_operator_tpu.controllers.jax import JAXController
from tf_operator_tpu.controllers.tensorflow import TFController
from tf_operator_tpu.core.control import TokenBucket, slow_start_batch
from tf_operator_tpu.core.workqueue import WorkQueue
from tf_operator_tpu.metrics import Metrics
from tf_operator_tpu.testing.failover import FailoverDriver
from tf_operator_tpu.testing.invariants import assert_invariants


def container(name):
    return {"name": name, "image": "test:1"}


def jax_manifest(name="llama", workers=8, run_policy=None):
    spec = {
        "jaxReplicaSpecs": {
            "Worker": {
                "replicas": workers,
                "template": {"spec": {"containers": [container("jax")]}},
            }
        },
    }
    if run_policy:
        spec["runPolicy"] = run_policy
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


def tfjob_manifest(name="tj", workers=2, clean_pod_policy=None):
    spec = {
        "tfReplicaSpecs": {
            "Worker": {
                "replicas": workers,
                "restartPolicy": "ExitCode",
                "template": {
                    "spec": {"containers": [container("tensorflow")]}
                },
            }
        }
    }
    if clean_pod_policy:
        spec["runPolicy"] = {"cleanPodPolicy": clean_pod_policy}
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TFJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


def conds_of(cluster, kind, name):
    job = cluster.get_job(kind, "default", name)
    return {c["type"]: c for c in (job.get("status") or {}).get("conditions") or []}


# ------------------------------------------------------------ slow start

class TestSlowStartBatch:
    def test_waves_double_and_saturate(self):
        waves = []
        calls = []
        successes, err = slow_start_batch(
            32, calls.append, parallel=True, on_batch=waves.append,
        )
        assert err is None and successes == 32
        assert waves == [1, 2, 4, 8, 16, 1]
        assert sorted(calls) == list(range(32))

    def test_first_error_aborts_remainder(self):
        attempted = []

        def fn(i):
            attempted.append(i)
            if i == 3:
                raise RuntimeError("broken template")

        successes, err = slow_start_batch(64, fn, parallel=True)
        assert isinstance(err, RuntimeError)
        # Waves 1+2+4 ran; the failing wave (indices 3..6) completed; the
        # remaining 57 were never attempted — the slow-start property.
        assert successes == len(attempted) - 1
        assert len(attempted) <= 7
        assert max(attempted) <= 6

    def test_serial_mode_is_ordered_and_stops_at_first_error(self):
        calls = []

        def fn(i):
            calls.append(i)
            if i == 5:
                raise RuntimeError("boom")

        waves = []
        successes, err = slow_start_batch(
            32, fn, parallel=False, on_batch=waves.append,
        )
        assert isinstance(err, RuntimeError)
        # Strict work-list order (the chaos-determinism contract) and an
        # immediate stop: the serial fallback never overshoots the error.
        assert calls == [0, 1, 2, 3, 4, 5]
        assert successes == 5
        assert waves == [32]

    def test_empty_batch_is_a_noop(self):
        assert slow_start_batch(0, lambda i: 1 / 0) == (0, None)


# ------------------------------------------------------- bucket fairness

class TestTokenBucketFairness:
    def test_tokens_granted_in_arrival_order(self):
        bucket = TokenBucket(qps=25.0, burst=1)
        bucket.acquire()  # drain the burst
        order = []

        def taker(tag, delay):
            time.sleep(delay)
            bucket.acquire()
            order.append(tag)

        threads = [
            threading.Thread(target=taker, args=(tag, delay))
            for tag, delay in (("first", 0.0), ("second", 0.1), ("third", 0.2))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # Arrivals are 100ms apart (>> scheduling jitter); a fair bucket
        # must serve them in that order — the old spin-lock acquire could
        # hand "first"'s token to "third" on an unlucky wakeup.
        assert order == ["first", "second", "third"]

    def test_n_contenders_drain_in_bounded_time(self):
        bucket = TokenBucket(qps=500.0, burst=1)
        bucket.acquire()
        done = []

        def taker():
            bucket.acquire()
            done.append(1)

        threads = [threading.Thread(target=taker) for _ in range(30)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        elapsed = time.monotonic() - t0
        assert len(done) == 30, "a waiter starved (lost wakeup)"
        # Theoretical drain is 30/500 = 60ms; 5s is the generous bound
        # that still catches a thundering-herd livelock or a lost baton.
        assert elapsed < 5.0, f"drain took {elapsed:.2f}s"

    def test_disabled_bucket_is_free(self):
        bucket = TokenBucket(qps=0.0)
        t0 = time.monotonic()
        for _ in range(1000):
            bucket.acquire()
        assert time.monotonic() - t0 < 0.5


# ------------------------------------- expectation accounting of batches

class TestBatchExpectations:
    def test_failed_create_batch_rolls_back_exact_remainder(self):
        """expect-creations covers the whole batch up front; a mid-batch
        create error must leave outstanding adds == successful creates
        (their watch events are still due) and nothing more."""
        cluster = InMemoryCluster()
        controller = TFController(cluster, metrics=Metrics())
        cluster.create_job(tfjob_manifest("tj", workers=8))
        engine = controller.engine

        fails = {"after": 3}
        real_create = engine.pod_control.create_pod

        def flaky_create(namespace, pod, job, **kwargs):
            if fails["after"] <= 0:
                raise RuntimeError("chaos template")
            fails["after"] -= 1
            return real_create(namespace, pod, job, **kwargs)

        engine.pod_control.create_pod = flaky_create
        # Serialize so exactly 3 creates land before the failure (the
        # accounting must hold either way; serial makes it exact).
        engine.options.parallel_fanout = False
        controller.run_until_idle()

        created = len(cluster.list_pods("default"))
        assert created == 3
        # ADDED events already observed their share: outstanding adds
        # must be 0 (3 expected - 3 observed), with the 5-pod failed
        # remainder rolled back rather than wedging the gate for 5 min.
        outstanding = controller.expectations.get("default/tj", "pods")
        assert outstanding is None or outstanding[0] == 0, outstanding

    def test_service_deletions_ride_the_expectation_protocol(self):
        """Regression for the pod/service asymmetry: cleanup-path service
        deletions must register expect_deletions exactly like pod
        deletions, and a failed delete must roll its expectation back."""
        cluster = InMemoryCluster()
        controller = TFController(cluster, metrics=Metrics())
        cluster.create_job(
            tfjob_manifest("tj", workers=2, clean_pod_policy="All"))
        controller.run_until_idle()
        assert len(cluster.list_services("default")) == 2

        registered = []
        real_expect = controller.expectations.expect_deletions

        def spying_expect(key, kind, count):
            registered.append((kind, count))
            return real_expect(key, kind, count)

        controller.expectations.expect_deletions = spying_expect
        # Drive the job terminal: cleanPodPolicy All tears services down.
        for p in cluster.list_pods("default"):
            cluster.set_pod_phase("default", p.metadata.name, POD_RUNNING)
        controller.run_until_idle()
        cluster.set_pod_phase(
            "default", "tj-worker-0", "Succeeded", exit_code=0)
        controller.run_until_idle()

        assert conds_of(cluster, "TFJob", "tj").get(
            "Succeeded", {}).get("status") == "True"
        assert cluster.list_services("default") == []
        svc_expected = sum(c for k, c in registered if k == "services")
        assert svc_expected == 2, registered
        # The watch observed both DELETED events: the gate must be clean.
        assert controller.expectations.satisfied("default/tj", "services")

    def test_failed_service_delete_rolls_back_its_expectation(self):
        cluster = InMemoryCluster()
        controller = TFController(cluster, metrics=Metrics())
        cluster.create_job(
            tfjob_manifest("tj", workers=1, clean_pod_policy="All"))
        controller.run_until_idle()
        engine = controller.engine

        def failing_delete(namespace, name, job, **kwargs):
            raise RuntimeError("injected delete failure")

        engine.service_control.delete_service = failing_delete
        job = controller.parse_job(cluster.get_job("TFJob", "default", "tj"))
        try:
            engine._delete_service(job, cluster.list_services("default")[0])
        except RuntimeError:
            pass
        else:
            raise AssertionError("delete failure must propagate")
        # Rolled back: the gate must NOT wait on a delete that never
        # happened.
        assert controller.expectations.satisfied("default/tj", "services")


# ------------------------------------------- determinism under the chaos seam

def run_chaotic_gang_lifecycle(seed):
    """A full 8-worker gang lifecycle under write conflicts/errors + a
    mid-training slice preemption, with fan-out ENABLED (engine default).
    The chaos seam's supports_concurrent_writes=False must serialize the
    batches, keeping the whole schedule a pure function of the seed."""
    inner = InMemoryCluster()
    chaos = ChaosCluster(inner, ChaosSpec(
        seed=seed,
        conflict_rate=0.08,
        error_rate=0.05,
        preemptions=(
            ScheduledPreemption(
                after_writes=24,
                namespace="default",
                labels={"job-name": "llama", "replica-type": "worker"},
            ),
        ),
    ))
    metrics = Metrics()
    controller = JAXController(chaos, metrics=metrics)
    assert controller.engine.options.parallel_fanout, "fan-out must be ON"
    inner.create_job(jax_manifest(workers=8, run_policy={"backoffLimit": 0}))

    state = {"preempted": False, "finished": False}

    def drive():
        pods = inner.list_pods("default")
        for p in pods:
            if p.status.phase == POD_PENDING:
                inner.set_pod_phase("default", p.metadata.name, POD_RUNNING)
        running = [p for p in inner.list_pods("default")
                   if p.status.phase == POD_RUNNING]
        if state["preempted"] and not state["finished"] and len(running) == 8:
            for p in running:
                inner.set_pod_phase(
                    "default", p.metadata.name, "Succeeded", exit_code=0)
            state["finished"] = True
        if any(f.startswith("preempt:") for f in chaos.fault_log):
            state["preempted"] = True

    for _ in range(400):
        controller.run_until_idle()
        if state["finished"] and conds_of(inner, "JAXJob", "llama").get(
            "Succeeded", {}
        ).get("status") == "True":
            break
        drive()
        controller.queue.add("JAXJob:default/llama")
        time.sleep(0.002)
    controller.run_until_idle()
    status = inner.get_job("JAXJob", "default", "llama").get("status") or {}
    return {
        "fault_log": list(chaos.fault_log),
        "status": status,
        "inner": inner,
        "fanout_waves": metrics.labeled_counter_value(
            "training_operator_fanout_batches_total", "JAXJob", "pods"),
    }


class TestFanoutChaosDeterminism:
    def test_same_seed_byte_identical_fault_log_with_fanout_enabled(self):
        """The acceptance-criteria determinism regression: fan-out on,
        chaos active through bring-up, teardown, and re-bring-up — two
        runs of the same seed must produce byte-identical fault logs."""
        a = run_chaotic_gang_lifecycle(seed=777)
        b = run_chaotic_gang_lifecycle(seed=777)
        assert a["fault_log"], "the seeded run must have injected faults"
        assert a["fault_log"] == b["fault_log"]
        assert a["status"].get("disruptionCounts") == {"Worker": 1}
        assert "restartCounts" not in a["status"]
        # The engine really went through the batch path (waves counted),
        # serialized by the seam's capability flag.
        assert a["fanout_waves"] >= 1
        assert_invariants(
            a["inner"], kinds=("JAXJob",),
            expect_ledgers={
                "disruptionCounts": {"Worker": 1},
                "restartCounts": {},
                "stallCounts": {},
            },
        )

    def test_parallel_capability_respected_per_seam(self):
        chaos = ChaosCluster(InMemoryCluster(), ChaosSpec(seed=1))
        assert chaos.supports_concurrent_writes is False
        assert InMemoryCluster().supports_concurrent_writes is True
        # Proxies inherit the inner seam's verdict.
        assert LatencyCluster(
            InMemoryCluster(), 0.0).supports_concurrent_writes is True
        assert LatencyCluster(
            chaos, 0.0).supports_concurrent_writes is False


class TestCrashSweepBatchCreateWindow:
    """Crash-point sweep across the batch-create window: the controller
    dies at the k-th create_pod of the gang fan-out (both write
    variants), a cold-started replacement converges, and the structural
    invariants hold — no orphans, no duplicate slots, no stuck
    expectations, no ledger double-counts."""

    def _run(self, call_index, before_write):
        inner = InMemoryCluster()
        chaos = ChaosCluster(inner, ChaosSpec(
            seed=11,
            crash_points=(
                CrashPoint(
                    method="create_pod", call_index=call_index,
                    before_write=before_write,
                ),
            ),
        ))
        driver = FailoverDriver(
            chaos,
            lambda cluster: JAXController(
                cluster, queue=WorkQueue(), metrics=Metrics()),
            kinds=("JAXJob",),
        )
        inner.create_job(jax_manifest(workers=8))
        for _ in range(8):
            driver.run_until_idle()
            for p in inner.list_pods("default"):
                if p.status.phase == POD_PENDING:
                    inner.set_pod_phase(
                        "default", p.metadata.name, POD_RUNNING)
            driver.controller.queue.add("JAXJob:default/llama")
        driver.run_until_idle()

        assert len(driver.crashes) == 1, driver.crashes
        pods = inner.list_pods("default")
        assert len(pods) == 8, (call_index, before_write,
                                [p.metadata.name for p in pods])
        assert all(p.status.phase == POD_RUNNING for p in pods)
        assert_invariants(
            inner, kinds=("JAXJob",),
            expect_ledgers={
                "disruptionCounts": {}, "restartCounts": {},
                "stallCounts": {},
            },
        )
        # The replacement's expectation gate must be clean: a crashed
        # batch's expectations died with the process, and the new
        # controller's own batch was fully observed.
        assert driver.controller.expectations.satisfied(
            "default/llama", "pods")
        assert driver.controller.expectations.satisfied(
            "default/llama", "services")

    def test_sweep_both_variants_across_the_window(self):
        for call_index in (0, 3, 7):
            for before_write in (True, False):
                self._run(call_index, before_write)


# ------------------------------------------------------ parallel speedup

class TestParallelFanoutWins:
    def test_batch_create_beats_serial_on_latency_charged_memory(self):
        """Direct engine-level speedup check (the full operator-loop
        version lives in test_concurrency_stress.py; the benchmark in
        scripts/measure_control_plane.py --mode scale): one sync's pod
        fan-out for a 32-gang on a 3ms-per-write seam must land well
        under the 32x serial lower bound."""
        latency = 0.003
        timings = {}
        for parallel in (True, False):
            cluster = LatencyCluster(InMemoryCluster(), latency)
            controller = TFController(cluster, metrics=Metrics())
            controller.engine.options.parallel_fanout = parallel
            cluster.create_job(tfjob_manifest("tj", workers=32))
            t0 = time.monotonic()
            controller.run_until_idle()
            timings[parallel] = time.monotonic() - t0
            assert len(cluster.list_pods("default")) == 32
            names = [p.metadata.name for p in cluster.list_pods("default")]
            assert len(set(names)) == 32, "duplicate pods under fan-out"
        # 32 pods + 32 services + events: serial pays >= 64 write round
        # trips sequentially; parallel pays ~log2(32) waves per resource.
        assert timings[True] < timings[False], timings
