"""Real-TPU JAXJob through the operator (VERDICT r4 #1 — the last
integration seam this environment permits).

Every other e2e pins the children to CPU; here the operator launches a pod
process that claims the LIVE chip: a `spec.tpu` v5e-1 JAXJob whose
operator-injected env (TPU_WORKER_ID, coordinator, JAX_MESH_SPEC,
TPU_ACCELERATOR_TYPE) is consumed by real jax-on-TPU llama-400m training
steps, then SIGKILL -> whole-gang restart -> orbax resume ON the chip, with
the restart MTTR landing in the histogram. This is the TPU-native analog of
the reference proving itself on real clusters
(/root/reference/test/workflows/components/workflows.libsonnet:218-300,
/root/reference/prow_config.yaml:5-43).

Gated skip-if-no-TPU so CI stays green off-chip. Single-tenant: the chip
fits one client — never run this file concurrently with bench.py or
another TPU job.
"""

import json
import os
import re
import subprocess
import sys
import time

import pytest

from tf_operator_tpu.cli import OperatorManager, OperatorOptions
from tf_operator_tpu.cluster.process import LocalProcessCluster
from tf_operator_tpu.metrics import Metrics

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# PYTHONPATH must APPEND the repo, not replace: on relay-plugin images the
# TPU backend registers from a sitecustomize on the ambient PYTHONPATH, and
# clobbering it leaves jax with the raw libtpu backend, which finds no
# local device ("No jellyfish device found").
_CHILD_PYTHONPATH = os.pathsep.join(
    p for p in (os.environ.get("PYTHONPATH", ""), REPO_ROOT) if p
)

# Children run on the REAL chip: the unit suite's JAX_PLATFORMS=cpu
# (tests/conftest.py sets it in this process's os.environ, which pods
# inherit) must be overridden — but the right value is image-specific.
# Relay-plugin images register the chip under their own platform name
# ("axon"; requesting "tpu" there makes jax REQUIRE the raw libtpu backend,
# which fails hard with no local device), while a plain TPU VM wants
# "tpu". The probe tries candidates in order and pins the first that
# yields a live TPU; tpu_init routes the value through jax.config so it
# sticks against sitecustomize pinning.
_PLATFORM_CANDIDATES = ("axon", "tpu")
_probe_result = None  # None = not probed; "" = no TPU; else the platform


def _tpu_platform():
    """Cached subprocess probe: which JAX_PLATFORMS value gives a fresh
    process (the same way a pod process will launch) a live TPU backend?
    A probe subprocess is the only honest check — this pytest process is
    pinned to CPU and must never claim the chip itself."""
    global _probe_result
    if _probe_result is None:
        _probe_result = ""
        for candidate in _PLATFORM_CANDIDATES:
            try:
                proc = subprocess.run(
                    [sys.executable, "-c",
                     "import jax, jax.numpy as jnp; "
                     "d = jax.devices(); "
                     "assert d[0].platform == 'tpu', d; "
                     "assert int(jnp.add(2, 2)) == 4; "
                     "print('tpu-ok')"],
                    env={**os.environ, "JAX_PLATFORMS": candidate,
                         "PYTHONPATH": _CHILD_PYTHONPATH},
                    capture_output=True, text=True, timeout=240,
                )
            except subprocess.TimeoutExpired:
                continue
            if proc.returncode == 0 and "tpu-ok" in proc.stdout:
                _probe_result = candidate
                break
    return _probe_result or None


def wait_for(predicate, timeout=30.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def job_condition(cluster, kind, name, ctype):
    try:
        job = cluster.get_job(kind, "default", name)
    except KeyError:
        return False
    conds = (job.get("status") or {}).get("conditions") or []
    return any(c["type"] == ctype and c["status"] == "True" for c in conds)


@pytest.fixture
def tpu_harness():
    platform = _tpu_platform()
    if platform is None:
        pytest.skip("no reachable TPU (probe subprocess failed)")
    metrics = Metrics()
    cluster = LocalProcessCluster(child_env={
        "JAX_PLATFORMS": platform, "PYTHONPATH": _CHILD_PYTHONPATH,
    })
    manager = OperatorManager(
        cluster,
        OperatorOptions(enabled_schemes=["JAXJob"], health_port=0,
                        metrics_port=0, resync_period=0.2),
        metrics=metrics,
    )
    manager.start()
    yield cluster, metrics
    manager.stop()
    cluster.shutdown()


class TestRealTPUJAXJobThroughOperator:
    def test_injected_env_trains_on_chip_kill_restart_resume(
        self, tpu_harness, tmp_path
    ):
        """End-to-end on the live chip: operator env -> libtpu ->
        jax-on-TPU llama-400m training -> SIGKILL -> gang restart -> orbax
        resume, MTTR recorded. Throughput is asserted at TPU scale
        (>5k tokens/sec/chip) — a silent CPU fallback would train ~1000x
        slower and fail loudly here rather than pass vacuously."""
        cluster, metrics = tpu_harness
        ckpt_dir = str(tmp_path / "ckpt")
        train_cmd = [
            sys.executable,
            os.path.join(REPO_ROOT, "examples", "jax", "llama", "llama_train.py"),
            "--model", "llama-400m", "--steps", "30", "--batch", "8",
            "--seq", "2048", "--checkpoint-every", "10", "--log-every", "5",
            "--checkpoint-dir", ckpt_dir,
        ]
        cluster.create_job({
            "apiVersion": "kubeflow.org/v1",
            "kind": "JAXJob",
            "metadata": {"name": "tpu1", "namespace": "default"},
            "spec": {
                # v5e-1: one host, one chip — exactly this environment.
                "tpu": {"acceleratorType": "v5e-1", "topology": "1x1"},
                "mesh": {"fsdp": 1},
                "jaxReplicaSpecs": {"Worker": {"template": {"spec": {
                    "containers": [
                        {"name": "jax", "image": "local", "command": train_cmd}
                    ]}}}},
            },
        })

        # The operator's side of the contract: slice env on the pod spec.
        assert wait_for(
            lambda: any(p.metadata.name == "tpu1-worker-0"
                        for p in cluster.list_pods()), timeout=30)
        pod = cluster.get_pod("default", "tpu1-worker-0")
        env = {e.name: e.value for e in pod.spec.containers[0].env}
        assert env["TPU_WORKER_ID"] == "0"
        assert env["TPU_ACCELERATOR_TYPE"] == "v5e-1"
        assert json.loads(env["JAX_MESH_SPEC"]) == {"fsdp": 1}
        assert env["JAX_NUM_PROCESSES"] == "1"

        # The workload's side: the injected mesh materialized on the chip
        # (first compile ~20-40s through the remote-compile tunnel).
        def booted():
            log = cluster.get_pod_log("default", "tpu1-worker-0")
            return "[llama] process 0/1 devices=1" in log and "step" in log

        assert wait_for(booted, timeout=300), (
            cluster.get_pod_log("default", "tpu1-worker-0")[-3000:])
        log = cluster.get_pod_log("default", "tpu1-worker-0")
        assert "mesh={'fsdp': 1}" in log, log[-2000:]

        # Preempt AFTER the first committed checkpoint.
        def committed_checkpoint():
            return os.path.isdir(ckpt_dir) and any(
                e.name.isdigit() for e in os.scandir(ckpt_dir))

        assert wait_for(committed_checkpoint, timeout=180), (
            "no committed checkpoint before the kill")
        first_start = cluster.get_pod("default", "tpu1-worker-0").status.start_time
        kill_t0 = time.monotonic()
        cluster.kill_pod("default", "tpu1-worker-0")

        def recreated():
            try:
                p = cluster.get_pod("default", "tpu1-worker-0")
            except KeyError:
                return False
            return (p.status.start_time is not None
                    and p.status.start_time > first_start)

        assert wait_for(recreated, timeout=90), "pod not recreated after kill"
        mttr = time.monotonic() - kill_t0
        print(f"[tpu-e2e] replacement Running {mttr:.2f}s after SIGKILL",
              flush=True)

        assert wait_for(
            lambda: job_condition(cluster, "JAXJob", "tpu1", "Succeeded"),
            timeout=420,
        ), cluster.get_pod_log("default", "tpu1-worker-0")[-3000:]
        log = cluster.get_pod_log("default", "tpu1-worker-0")
        assert "resumed from step" in log, log[-2000:]
        assert "[llama] done" in log, log[-2000:]
        assert not job_condition(cluster, "JAXJob", "tpu1", "Failed")

        # TPU-scale throughput or bust: the logged rates are wall-clock
        # averages polluted by the first compile (~30 s through the
        # remote-compile tunnel) and by orbax saves streaming the full
        # state off-chip (~20 s each here), so they sit far below
        # bench.py's 45.2k steady-state — but a CPU at seq 2048 trains
        # llama-400m at <100 tokens/sec, so 1,000+ still proves the chip
        # (measured run: min-window 1.8k, best-window 11.4k).
        rates = [float(m.replace(",", ""))
                 for m in re.findall(r"\(([\d,]+)/chip\)", log)]
        assert rates and max(rates) > 1000, f"not TPU-speed: {rates}"
        print(f"[tpu-e2e] per-chip tokens/sec across logs: "
              f"min={min(rates):,.0f} max={max(rates):,.0f}", flush=True)

        # Restart accounting: one world restart, MTTR in the histogram.
        job = cluster.get_job("JAXJob", "default", "tpu1")
        counts = job["status"]
        total = (sum(counts.get("restartCounts", {}).values())
                 + sum(counts.get("disruptionCounts", {}).values()))
        assert total == 1, counts
        hist = metrics._histograms["training_operator_job_restart_seconds"][
            ("default", "JAXJob")]
        assert hist.count >= 1, "restart MTTR missing from the histogram"
