"""Shard-scoped shared watch cache (cluster/watchcache.py + the scoped
WatchCacheCluster serving rules).

The 10k-fleet property under test: a replica's delta-fed store holds (and
pays maintenance for) ONLY its owned shards' objects — out-of-shard
deltas are dropped at the cache boundary (the served/filtered counter
pair), a claim primes the new shard's slice BEFORE any sync needs it,
and a release tears the slice down. Scoped reads that cannot be
attributed to an owned job key fall through to the inner chain: a scoped
store is a subset of the world and must never masquerade as all of it.
"""

import threading

from tf_operator_tpu.api.k8s import ObjectMeta, Pod
from tf_operator_tpu.cli import OperatorManager, OperatorOptions
from tf_operator_tpu.cluster.base import NotFound
from tf_operator_tpu.cluster.memory import InMemoryCluster
from tf_operator_tpu.cluster.watchcache import SharedWatchCache, WatchCacheCluster
from tf_operator_tpu.core.sharding import shard_for_key
from tf_operator_tpu.core.tracing import Tracer
from tf_operator_tpu.metrics import Metrics

REQS = "training_operator_apiserver_requests_total"


class FakeScope:
    """Stand-in for a ShardCoordinator: a fixed ring with a mutable
    owned set (tests flip ownership to simulate claims/releases)."""

    def __init__(self, shards=4, owned=()):
        self.shards = shards
        self.owned_set = set(owned)

    def shard_of(self, namespace, name):
        return shard_for_key(namespace, name, self.shards)

    def owns(self, shard):
        return shard in self.owned_set


def job_dict(name, namespace="default", rv="1"):
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TFJob",
        "metadata": {"name": name, "namespace": namespace,
                     "resourceVersion": rv},
        "spec": {},
    }


def pod_for(job, podname, namespace="default"):
    return Pod(metadata=ObjectMeta(
        name=podname, namespace=namespace, labels={"job-name": job}))


def keys_in_shard(scope, shard, count=5, namespace="default"):
    out = []
    i = 0
    while len(out) < count:
        name = f"job-{i}"
        if scope.shard_of(namespace, name) == shard:
            out.append(name)
        i += 1
    return out


class TestScopedStore:
    def test_out_of_shard_deltas_filtered_and_counted(self):
        mem = InMemoryCluster()
        scope = FakeScope(shards=4, owned={0})
        metrics = Metrics()
        cache = SharedWatchCache(mem, metrics=metrics, scope=scope)
        owned = keys_in_shard(scope, 0, count=2)
        foreign = keys_in_shard(scope, 1, count=2)
        for job in owned + foreign:
            mem.create_pod(pod_for(job, f"{job}-worker-0"))
        with cache._lock:
            stored = {name for _, name in cache._stores["pods"]}
        assert stored == {f"{j}-worker-0" for j in owned}, stored
        served, filtered = metrics.watch_cache_totals()
        assert served == 2 and filtered == 2

    def test_unattributable_objects_not_stored_under_scope(self):
        mem = InMemoryCluster()
        cache = SharedWatchCache(mem, scope=FakeScope(shards=4, owned={0, 1, 2, 3}))
        mem.create_pod(Pod(metadata=ObjectMeta(name="naked", namespace="default")))
        with cache._lock:
            assert not cache._stores["pods"]

    def test_prime_shard_merges_only_the_claimed_slice(self):
        mem = InMemoryCluster()
        scope = FakeScope(shards=4, owned=set())
        cache = SharedWatchCache(mem, scope=scope)
        cache.register_kind("TFJob")
        in_zero = keys_in_shard(scope, 0, count=3)
        in_one = keys_in_shard(scope, 1, count=3)
        for job in in_zero + in_one:
            mem.create_job(job_dict(job))
            mem.create_pod(pod_for(job, f"{job}-worker-0"))
        with cache._lock:  # nothing owned: nothing stored
            assert not cache._stores["TFJob"] and not cache._stores["pods"]
        # Claim shard 0: ownership flips, THEN the prime (cli ordering).
        scope.owned_set.add(0)
        cache.prime_shard(0)
        with cache._lock:
            jobs = {name for _, name in cache._stores["TFJob"]}
            pods = {name for _, name in cache._stores["pods"]}
        assert jobs == set(in_zero)
        assert pods == {f"{j}-worker-0" for j in in_zero}

    def test_drop_shard_tears_down_the_released_slice(self):
        mem = InMemoryCluster()
        scope = FakeScope(shards=4, owned={0, 1})
        cache = SharedWatchCache(mem, scope=scope)
        cache.register_kind("TFJob")
        in_zero = keys_in_shard(scope, 0, count=2)
        in_one = keys_in_shard(scope, 1, count=2)
        for job in in_zero + in_one:
            mem.create_job(job_dict(job))
            mem.create_pod(pod_for(job, f"{job}-worker-0"))
        scope.owned_set.discard(1)
        cache.drop_shard(1)
        with cache._lock:
            jobs = {name for _, name in cache._stores["TFJob"]}
            pods = {name for _, name in cache._stores["pods"]}
        assert jobs == set(in_zero)
        assert pods == {f"{j}-worker-0" for j in in_zero}

    def test_deletion_racing_a_shard_prime_never_resurrects(self):
        """The tombstone rule during prime_shard: a DELETED delta landing
        between the LIST snapshot and the merge must win."""
        mem = InMemoryCluster()
        scope = FakeScope(shards=4, owned=set())
        cache = SharedWatchCache(mem, scope=scope)
        cache.register_kind("TFJob")
        job = keys_in_shard(scope, 0, count=1)[0]
        mem.create_job(job_dict(job))
        scope.owned_set.add(0)
        # Simulate the race: list first (the prime's snapshot), delete,
        # then merge the stale snapshot through the tombstone guard.
        listed = mem.list_jobs("TFJob", None)
        real_list = cache._list_backend

        def stale_list(resource):
            if resource == "TFJob":
                mem.delete_job("TFJob", "default", job)  # DELETED delta
                return listed
            return real_list(resource)

        cache._list_backend = stale_list
        cache.prime_shard(0)
        assert cache.get_object_or_none("TFJob", "default", job) is None


class TestScopedProxyReads:
    def _setup(self, owned):
        mem = InMemoryCluster()
        scope = FakeScope(shards=4, owned=set(owned))
        metrics = Metrics()
        cache = SharedWatchCache(mem, metrics=metrics, scope=scope)
        from tf_operator_tpu.cluster.accounting import AccountingCluster

        acct = AccountingCluster(mem, metrics=metrics, tracer=Tracer())
        proxy = WatchCacheCluster(acct, cache, "TFJob")
        return mem, scope, metrics, cache, proxy

    def test_attributed_list_serves_from_cache(self):
        mem, scope, metrics, cache, proxy = self._setup(owned={0, 1, 2, 3})
        job = keys_in_shard(scope, 0, count=1)[0]
        mem.create_pod(pod_for(job, f"{job}-worker-0"))
        out = proxy.list_pods(namespace="default", labels={"job-name": job})
        assert [p.metadata.name for p in out] == [f"{job}-worker-0"]
        assert metrics.labeled_counter_value(REQS, "list", "pods", "200") == 0

    def test_unattributed_list_delegates(self):
        mem, scope, metrics, cache, proxy = self._setup(owned={0, 1, 2, 3})
        job = keys_in_shard(scope, 0, count=1)[0]
        mem.create_pod(pod_for(job, f"{job}-worker-0"))
        out = proxy.list_pods(namespace="default")  # no job-name selector
        assert len(out) == 1
        assert metrics.labeled_counter_value(REQS, "list", "pods", "200") == 1

    def test_out_of_shard_reads_delegate(self):
        mem, scope, metrics, cache, proxy = self._setup(owned={0})
        foreign = keys_in_shard(scope, 1, count=1)[0]
        mem.create_job(job_dict(foreign))
        got = proxy.get_job("TFJob", "default", foreign)
        assert got["metadata"]["name"] == foreign
        assert metrics.labeled_counter_value(REQS, "get", "jobs", "200") == 1

    def test_scoped_get_miss_falls_through_not_notfound(self):
        """A scoped store's miss is ambiguous (deleted vs out of scope):
        the proxy must consult the inner chain, not synthesize 404."""
        mem, scope, metrics, cache, proxy = self._setup(owned={0})
        job = keys_in_shard(scope, 0, count=1)[0]
        # Object exists on the server but the store is cold (created
        # before any prime covered it; force by clearing the store).
        mem.create_pod(pod_for(job, f"{job}-worker-0"))
        with cache._lock:
            cache._stores["pods"].clear()
        pod = proxy.get_pod("default", f"{job}-worker-0")
        assert pod.metadata.name == f"{job}-worker-0"
        # And a genuinely missing object still raises through the inner.
        try:
            proxy.get_pod("default", "never-existed")
        except NotFound:
            pass
        else:
            raise AssertionError("missing pod must raise NotFound")

    def test_scoped_list_jobs_always_delegates(self):
        mem, scope, metrics, cache, proxy = self._setup(owned={0, 1, 2, 3})
        mem.create_job(job_dict("j0"))
        assert len(proxy.list_jobs("TFJob", None)) == 1
        assert metrics.labeled_counter_value(REQS, "list", "jobs", "200") == 1


class TestScopedManagers:
    """Two sharded OperatorManagers over one cluster: each replica's
    cache indexes only its shards' objects, and the served/filtered
    split partitions the fleet's watch traffic."""

    def _opts(self, rid):
        return OperatorOptions(
            enabled_schemes=["TFJob"], shards=2, replica_id=rid,
            lease_duration=1.0, health_port=0, metrics_port=0,
            resync_period=0.5,
        )

    def test_two_replicas_partition_cache_maintenance(self):
        import time

        def tfjob(name, workers=1):
            return {
                "apiVersion": "kubeflow.org/v1",
                "kind": "TFJob",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"tfReplicaSpecs": {"Worker": {
                    "replicas": workers,
                    "template": {"spec": {"containers": [
                        {"name": "tensorflow", "image": "tf:1"}]}},
                }}},
            }

        mem = InMemoryCluster()
        m1 = OperatorManager(mem, self._opts("r0"), metrics=Metrics(),
                             tracer=Tracer())
        m2 = OperatorManager(mem, self._opts("r1"), metrics=Metrics(),
                             tracer=Tracer())
        m1.start()
        m2.start()
        try:
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if (m1.coordinator.owned_shards() == [0]
                        and m2.coordinator.owned_shards() == [1]):
                    break
                time.sleep(0.02)
            for i in range(8):
                mem.create_job(tfjob(f"j{i}"))
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if len(mem.list_pods("default")) == 8:
                    break
                time.sleep(0.02)
            assert len(mem.list_pods("default")) == 8
            by_shard = {0: set(), 1: set()}
            for i in range(8):
                by_shard[shard_for_key("default", f"j{i}", 2)].add(f"j{i}")
            with m1.watch_cache._lock:
                r0_jobs = {n for _, n in m1.watch_cache._stores["TFJob"]}
            with m2.watch_cache._lock:
                r1_jobs = {n for _, n in m2.watch_cache._stores["TFJob"]}
            assert r0_jobs == by_shard[0], (r0_jobs, by_shard)
            assert r1_jobs == by_shard[1], (r1_jobs, by_shard)
            s1, f1 = m1.metrics.watch_cache_totals()
            s2, f2 = m2.metrics.watch_cache_totals()
            # Both replicas saw the same stream; each applied only its
            # share and filtered the rest.
            assert s1 > 0 and s2 > 0 and f1 > 0 and f2 > 0
        finally:
            m1.stop()
            m2.stop()
