"""Unit tier for the bench harness's non-measuring parts: the multi-config
floor check (`bench.py --check` against ci/bench_floors.json), the
peak-TFLOPs fallthrough contract (unknown chips are ASSUMED loudly, never
silently scored), and the expert-axis sharding resolution the MoE perf
work rides (parallel/sharding.py).
"""

from __future__ import annotations

import json

import pytest

import bench


class FakeDevice:
    def __init__(self, device_kind, platform):
        self.device_kind = device_kind
        self.platform = platform


class TestPeakTflops:
    def test_known_kinds_have_no_assumption(self):
        peak, assumed = bench.peak_tflops_for(FakeDevice("TPU v5 lite", "tpu"))
        assert peak == 197.0 and assumed is None
        peak, assumed = bench.peak_tflops_for(FakeDevice("cpu", "cpu"))
        assert peak == 1.0 and assumed is None

    def test_unknown_tpu_kind_assumes_v5e_and_says_so(self, capsys):
        peak, assumed = bench.peak_tflops_for(FakeDevice("TPU v9 mega", "tpu"))
        assert peak == 197.0
        assert assumed == "tpu v5 lite"
        assert "WARNING" in capsys.readouterr().err

    def test_unknown_non_tpu_assumes_cpu(self, capsys):
        peak, assumed = bench.peak_tflops_for(FakeDevice("H100", "gpu"))
        assert peak == 1.0 and assumed == "cpu"
        assert "WARNING" in capsys.readouterr().err


class TestCheckFloors:
    def _floors(self, tmp_path, table):
        path = tmp_path / "floors.json"
        path.write_text(json.dumps(table))
        return str(path)

    def test_all_floors_held_passes(self, tmp_path):
        path = self._floors(tmp_path, {
            "tpu v5 lite": {"llama-400m": 0.64, "moe-125m": 0.38},
        })
        rc = bench._check_floors(
            path, "llama-400m", {"mfu": 0.70},
            {"moe-125m": {"mfu": 0.52}},
            FakeDevice("TPU v5 lite", "tpu"),
        )
        assert rc == 0

    def test_secondary_regression_fails_not_just_headline(self, tmp_path):
        path = self._floors(tmp_path, {
            "tpu v5 lite": {"llama-400m": 0.64, "moe-125m": 0.38},
        })
        rc = bench._check_floors(
            path, "llama-400m", {"mfu": 0.70},
            {"moe-125m": {"mfu": 0.30}},  # headline fine, secondary not
            FakeDevice("TPU v5 lite", "tpu"),
        )
        assert rc == 3

    def test_missing_floored_config_fails(self, tmp_path):
        """A secondary silently dropped from the suite is a check failure —
        the ratchet gates presence, not just values."""
        path = self._floors(tmp_path, {
            "tpu v5 lite": {"llama-400m": 0.64, "moe-125m": 0.38},
        })
        rc = bench._check_floors(
            path, "llama-400m", {"mfu": 0.70}, {},
            FakeDevice("TPU v5 lite", "tpu"),
        )
        assert rc == 3

    def test_errored_config_fails_even_unfloored(self, tmp_path):
        path = self._floors(tmp_path, {"cpu": {"llama-tiny": 0.0}})
        rc = bench._check_floors(
            path, "llama-tiny", {"mfu": 0.1},
            {"bert-tiny": {"error": "ValueError: boom"}},
            FakeDevice("cpu", "cpu"),
        )
        assert rc == 3

    def test_unknown_platform_is_report_only(self, tmp_path):
        path = self._floors(tmp_path, {"tpu v5 lite": {"llama-400m": 0.64}})
        rc = bench._check_floors(
            path, "llama-400m", {"mfu": 0.01}, {},
            FakeDevice("TPU v9 mega", "tpu"),
        )
        assert rc == 0

    def test_longest_platform_prefix_wins(self, tmp_path):
        """'tpu v5 lite' must match its own table, not the shorter
        'tpu v5' (v5p) prefix."""
        path = self._floors(tmp_path, {
            "tpu v5": {"llama-400m": 0.99},
            "tpu v5 lite": {"llama-400m": 0.60},
        })
        rc = bench._check_floors(
            path, "llama-400m", {"mfu": 0.65}, {},
            FakeDevice("TPU v5 lite", "tpu"),
        )
        assert rc == 0

    def test_committed_floors_parse_and_cover_the_r05_suite(self):
        with open(bench.os.path.join(
                bench.os.path.dirname(bench.os.path.abspath(bench.__file__)),
                "ci", "bench_floors.json")) as fh:
            floors = json.load(fh)
        tpu = floors["tpu v5 lite"]
        for name in ("llama-400m", "llama-400m+native-loader", "moe-125m",
                     "bert-base", "llama-1b"):
            assert name in tpu and 0.0 < tpu[name] < 1.0
        assert set(floors["cpu"]) == {
            "llama-400m", "llama-400m+native-loader", "moe-tiny", "bert-tiny",
        }


class TestExpertShardingResolution:
    """parallel/sharding.py: where MoE expert weights land per mesh."""

    def _mesh(self, **axes):
        import numpy as np

        jax = pytest.importorskip("jax")
        total = 1
        for v in axes.values():
            total *= v
        if total > len(jax.devices()):
            pytest.skip("not enough host devices")
        arr = np.array(jax.devices()[:total]).reshape(tuple(axes.values()))
        return jax.sharding.Mesh(arr, tuple(axes))

    def test_ep_mesh_keeps_ep(self):
        from tf_operator_tpu.parallel.sharding import moe_expert_axes

        mesh = self._mesh(fsdp=4, ep=2)
        ax, batch = moe_expert_axes(mesh, 8)
        assert ax == "ep" and "ep" not in batch and "fsdp" in batch

    def test_epless_mesh_rides_fsdp_when_divisible(self):
        from tf_operator_tpu.parallel.sharding import (
            moe_expert_axes,
            spec_for_param,
        )

        mesh = self._mesh(fsdp=4)
        ax, batch = moe_expert_axes(mesh, 8)
        assert ax == "fsdp" and "fsdp" not in batch
        # Weight rule: scanned stack [layers, e, d, f] -> experts over
        # fsdp, d UNsharded (the axis cannot be used twice).
        spec = spec_for_param("params/layers/feed_forward/experts_w1",
                              4, mesh, shape=(12, 8, 768, 2048))
        assert tuple(spec) == (None, "fsdp", None, None)

    def test_epless_mesh_replicates_when_not_divisible(self):
        from tf_operator_tpu.parallel.sharding import (
            moe_expert_axes,
            spec_for_param,
        )

        mesh = self._mesh(fsdp=4)
        ax, batch = moe_expert_axes(mesh, 6)  # 6 % 4 != 0
        assert ax is None and "fsdp" in batch
        # Weights fall back to the old layout: d over fsdp.
        spec = spec_for_param("params/layers/feed_forward/experts_w1",
                              4, mesh, shape=(12, 6, 768, 2048))
        assert tuple(spec) == (None, None, "fsdp", None)

    def test_shape_blind_call_preserves_legacy_layout(self):
        from tf_operator_tpu.parallel.sharding import spec_for_param

        mesh = self._mesh(fsdp=4)
        spec = spec_for_param("params/layers/feed_forward/experts_w2", 4, mesh)
        assert tuple(spec) == (None, None, None, "fsdp")
