"""Tracing tier: job-lifecycle span timelines + apiserver request
accounting (docs/design/tracing.md).

What this tier holds:

- Tracer core semantics: deterministic counter-derived ids (no wall
  clock, no randomness), one trace per job incarnation (UID-keyed),
  bounded per-trace ring buffer + bounded LRU trace map, thread-local
  context with explicit cross-thread propagation.
- Request accounting (cluster/accounting.py): every cluster call counted
  into `training_operator_apiserver_requests_total{verb,resource,code}`
  and attributed to the active job's trace; write verbs become api.*
  child spans; 1:1 pass-through (exceptions — SimulatedCrash included —
  re-raised unchanged).
- Controller integration: sync spans parented to the measured queue
  wait, per-job write attribution, the gang restart's
  count-before-teardown ordering auditable from the trace alone
  (testing/invariants.py check_span_invariants).
- Determinism (the acceptance criterion): a seeded chaos run driven on
  fake clocks replays BOTH the fault log and the span SEQUENCE
  byte-identically — tracing adds zero nondeterminism.
- The /tracez handler, /readyz state reflection, and the --log-format
  json trace stamping.
"""

import json
import logging
import threading
import urllib.error
import urllib.request

import pytest

from tf_operator_tpu.api.k8s import POD_FAILED, POD_PENDING, POD_RUNNING
from tf_operator_tpu.cli import (
    OperatorManager,
    OperatorOptions,
    json_log_formatter,
)
from tf_operator_tpu.cluster.accounting import AccountingCluster, code_of
from tf_operator_tpu.cluster.base import Conflict, Gone, NotFound, ServerError
from tf_operator_tpu.cluster.chaos import ChaosCluster, ChaosSpec, SimulatedCrash
from tf_operator_tpu.cluster.memory import InMemoryCluster
from tf_operator_tpu.controllers.jax import JAXController
from tf_operator_tpu.controllers.tensorflow import TFController
from tf_operator_tpu.core.tracing import NOOP_TRACER, Tracer
from tf_operator_tpu.core.workqueue import WorkQueue
from tf_operator_tpu.metrics import Metrics
from tf_operator_tpu.testing.invariants import (
    check_span_invariants,
    dump_trace,
)

JOB = ("TFJob", "default", "tj", "uid-1")


def container(name):
    return {"name": name, "image": "test:1"}


def tf_manifest(name="tj", workers=2):
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TFJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "tfReplicaSpecs": {
                "Worker": {
                    "replicas": workers,
                    "restartPolicy": "ExitCode",
                    "template": {
                        "spec": {"containers": [container("tensorflow")]}
                    },
                }
            }
        },
    }


def jax_manifest(name="llama", workers=4):
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "jaxReplicaSpecs": {
                "Worker": {
                    "replicas": workers,
                    "template": {"spec": {"containers": [container("jax")]}},
                }
            },
        },
    }


class TestTracerCore:
    def test_deterministic_ids_and_nesting(self):
        tracer = Tracer()
        with tracer.span("sync", job=JOB) as outer:
            with tracer.span("inner", attrs={"k": "v"}) as inner:
                assert inner.parent_id == outer.span_id
        traces = tracer.export()
        assert len(traces) == 1
        trace = traces[0]
        assert trace["trace_id"] == "trace-000001"
        assert [s["id"] for s in trace["spans"]] == [1, 2]
        assert trace["spans"][1]["parent"] == 1
        assert trace["spans"][1]["attrs"] == {"k": "v"}
        assert all(s["end"] is not None for s in trace["spans"])

    def test_one_trace_per_incarnation(self):
        """Same (kind, ns, name), new uid = a recreated job = a fresh
        trace — exactly the UID-keyed terminal-metrics dedup rule."""
        tracer = Tracer()
        with tracer.span("sync", job=("TFJob", "default", "tj", "u1")):
            pass
        with tracer.span("sync", job=("TFJob", "default", "tj", "u2")):
            pass
        assert len(tracer.export()) == 2

    def test_ring_buffer_and_lru_bounds(self):
        tracer = Tracer(max_traces=2, max_spans=3)
        for i in range(5):
            with tracer.span("sync", job=("TFJob", "default", "tj", "u1")):
                pass
        trace = tracer.export()[0]
        # Only the newest 3 spans survive; ids keep counting (the seq is
        # per-trace monotonic, never reused after trimming).
        assert [s["id"] for s in trace["spans"]] == [3, 4, 5]
        for uid in ("a", "b", "c"):
            with tracer.span("sync", job=("TFJob", "default", uid, uid)):
                pass
        uids = {t["uid"] for t in tracer.export()}
        assert uids == {"b", "c"}, "oldest trace must be evicted"
        # True LRU, not FIFO: touching the older trace refreshes its
        # recency, so the idle newer one is the eviction victim.
        with tracer.span("sync", job=("TFJob", "default", "b", "b")):
            pass
        with tracer.span("sync", job=("TFJob", "default", "d", "d")):
            pass
        assert {t["uid"] for t in tracer.export()} == {"b", "d"}, (
            "a busy trace must never lose to an idle newer one")

    def test_active_trace_survives_eviction_by_churn(self):
        """Threads hold direct _Trace references for the whole sync: a
        long sync racing enough job churn to blow max_traces must NOT
        lose its later spans/write attribution to LRU eviction — every
        touch through the live reference restores the trace's slot."""
        tracer = Tracer(max_traces=2)
        with tracer.span("sync", job=("TFJob", "default", "busy", "u1")):
            for uid in ("a", "b", "c"):  # churn evicts "busy" mid-sync
                with tracer.span("sync", job=("TFJob", "default", uid, uid)):
                    pass
            tracer.record_request("create", "pods", "200")
            with tracer.span("inner"):
                pass
        busy = [t for t in tracer.export() if t["job"] == "busy"]
        assert busy, "the actively-syncing trace must win its slot back"
        assert busy[0]["writes"] == 1
        assert tracer.writes_by_job().get("TFJob/default/busy") == 1
        names = [s["name"] for s in busy[0]["spans"]]
        assert "api.create" in names and "inner" in names
        assert len(tracer.export()) <= 2, "the LRU bound still holds"

    def test_record_span_links_follow_on_parent(self):
        tracer = Tracer()
        wait_id = tracer.record_span("queue.wait", job=JOB, duration=1.5)
        with tracer.span("sync", job=JOB, parent=wait_id) as sync:
            assert sync.parent_id == wait_id
        spans = tracer.export()[0]["spans"]
        assert spans[0]["name"] == "queue.wait"
        assert spans[1]["parent"] == wait_id

    def test_event_and_error_attrs(self):
        tracer = Tracer()
        with pytest.raises(RuntimeError):
            with tracer.span("sync", job=JOB):
                tracer.event("fanout.wave", size=4)
                raise RuntimeError("boom")
        span = tracer.export()[0]["spans"][0]
        assert span["attrs"]["error"] == "RuntimeError"
        assert span["events"] == [{"name": "fanout.wave", "attrs": {"size": 4}}]

    def test_no_context_is_silent(self):
        """Engine helpers called outside a sync never crash on tracing:
        a job-less span with no active context records nothing."""
        tracer = Tracer()
        with tracer.span("orphan") as span:
            span.set(x=1)  # NULL_SPAN accepts everything
        tracer.event("nobody-listening")
        tracer.record_request("create", "pods", "200")
        assert tracer.export() == []

    def test_disabled_tracer_noops(self):
        assert NOOP_TRACER.enabled is False
        with NOOP_TRACER.span("sync", job=JOB):
            NOOP_TRACER.record_request("create", "pods", "200")
        assert NOOP_TRACER.export() == []
        assert NOOP_TRACER.record_span("queue.wait", job=JOB) is None

    def test_request_attribution_and_write_spans(self):
        tracer = Tracer()
        with tracer.span("sync", job=JOB) as sync:
            tracer.record_request("get", "jobs", "200")
            tracer.record_request("create", "pods", "200", duration=0.01)
            tracer.record_request("update", "status", "409")
        trace = tracer.export()[0]
        assert trace["writes"] == 2
        assert {(r["verb"], r["resource"], r["code"], r["count"])
                for r in trace["requests"]} == {
            ("get", "jobs", "200", 1),
            ("create", "pods", "200", 1),
            ("update", "status", "409", 1),
        }
        children = [s for s in trace["spans"] if s["parent"] == sync.span_id]
        assert [(s["name"], s["attrs"]["resource"]) for s in children] == [
            ("api.create", "pods"), ("api.update", "status"),
        ], "reads are counted but never become spans"
        assert tracer.writes_by_job() == {"TFJob/default/tj": 2}
        assert tracer.total_writes() == 2

    def test_cross_thread_context_propagation(self):
        """The fan-out rule: a pool thread has no stack; call_in_context
        must carry the job attribution over."""
        tracer = Tracer()
        with tracer.span("sync", job=JOB):
            ctx = tracer.current()

            def write():
                tracer.record_request("create", "pods", "200")

            t = threading.Thread(
                target=tracer.call_in_context, args=(ctx, write))
            t.start()
            t.join()
            # And a bare thread without the wrapper attributes nothing.
            t2 = threading.Thread(target=write)
            t2.start()
            t2.join()
        assert tracer.total_writes() == 1

    def test_span_sequence_drops_wall_clock_attrs(self):
        tracer = Tracer()
        with tracer.span("sync", job=JOB) as span:
            span.set(cause="Stall", count=3, age=1.234)
        seq = tracer.span_sequence()
        assert seq == [
            ("trace-000001", 1, None, "sync",
             (("cause", "Stall"), ("count", 3)), ()),
        ]

    def test_export_races_live_recording_safely(self):
        """A /tracez scrape racing live syncs: export snapshots under the
        tracer lock and span attrs are copy-on-write, so concurrent
        recording must never corrupt (or crash) an export."""
        tracer = Tracer(max_spans=32)
        stop = threading.Event()
        errors = []

        def writer():
            i = 0
            try:
                while not stop.is_set():
                    i += 1
                    with tracer.span(
                            "sync", job=("TFJob", "ns", f"j{i % 4}", "u")) as s:
                        s.set(round=i)
                        tracer.record_request("update", "status", "200")
                        tracer.event("tick", i=i)
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(150):
                for trace in tracer.export():
                    json.dumps(trace)
        finally:
            stop.set()
            t.join()
        assert errors == []

    def test_export_filters(self):
        tracer = Tracer()
        for ns, name in (("a", "j1"), ("b", "j2"), ("b", "j3")):
            with tracer.span("sync", job=("TFJob", ns, name, name)):
                pass
        assert len(tracer.export(namespace="b")) == 2
        assert len(tracer.export(job="j1")) == 1
        assert len(tracer.export(limit=1)) == 1
        assert tracer.export(limit=1)[0]["job"] == "j3", "newest last"
        payload = json.loads(tracer.export_json(namespace="a"))
        assert len(payload["traces"]) == 1


class TestAccountingCluster:
    def test_code_of_mapping(self):
        assert code_of(None) == "200"
        assert code_of(NotFound("x")) == "404"
        assert code_of(Conflict("x")) == "409"
        assert code_of(Gone("x")) == "410"
        assert code_of(ServerError("x")) == "500"
        assert code_of(ValueError("x")) == "ValueError"

    def test_counts_attributes_and_passes_through(self):
        mem = InMemoryCluster()
        metrics = Metrics()
        tracer = Tracer()
        acct = AccountingCluster(mem, metrics=metrics, tracer=tracer)
        job_dict = acct.create_job(tf_manifest())  # outside any span
        uid = job_dict["metadata"]["uid"] if job_dict else ""
        with tracer.span("sync", job=("TFJob", "default", "tj", uid)):
            acct.get_job("TFJob", "default", "tj")
            with pytest.raises(NotFound):
                acct.get_job("TFJob", "default", "ghost")
        counter = metrics.labeled_counter_value
        assert counter("training_operator_apiserver_requests_total",
                       "create", "jobs", "200") == 1
        assert counter("training_operator_apiserver_requests_total",
                       "get", "jobs", "200") == 1
        assert counter("training_operator_apiserver_requests_total",
                       "get", "jobs", "404") == 1
        # Only the in-span requests were attributed; the unattributed
        # create still hit the aggregate counter above.
        trace = tracer.export()[0]
        assert trace["writes"] == 0
        assert sum(r["count"] for r in trace["requests"]) == 2
        # Capability flags + watch pass through unaccounted.
        assert acct.supports_concurrent_writes == mem.supports_concurrent_writes
        seen = []
        acct.watch("pods", lambda *a: seen.append(a))
        assert counter("training_operator_apiserver_requests_total",
                       "create", "pods", "200") == 0

    def test_simulated_crash_recorded_and_reraised(self):
        """A planted crash's write must still appear in the timeline it
        kills — and the BaseException must escape unchanged."""
        from tf_operator_tpu.cluster.chaos import CrashPoint

        mem = InMemoryCluster()
        chaos = ChaosCluster(mem, ChaosSpec(
            seed=1, crash_points=(CrashPoint("create_pod", 0),),
        ))
        metrics = Metrics()
        acct = AccountingCluster(chaos, metrics=metrics, tracer=None)
        from tf_operator_tpu.api.k8s import ObjectMeta, Pod

        with pytest.raises(SimulatedCrash):
            acct.create_pod(Pod(metadata=ObjectMeta(
                name="p", namespace="default")))
        assert metrics.labeled_counter_value(
            "training_operator_apiserver_requests_total",
            "create", "pods", "SimulatedCrash") == 1


def converge_tf(controller, mem, key="TFJob:default/tj"):
    controller.queue.add(key)
    controller.run_until_idle()
    for p in mem.list_pods("default"):
        if p.status.phase == POD_PENDING:
            mem.set_pod_phase("default", p.metadata.name, POD_RUNNING)
    controller.run_until_idle()


class TestControllerIntegration:
    def test_sync_span_parented_to_queue_wait_with_attribution(self):
        mem = InMemoryCluster()
        metrics = Metrics()
        tracer = Tracer()
        controller = TFController(
            mem, queue=WorkQueue(), metrics=metrics, tracer=tracer)
        mem.create_job(tf_manifest(workers=2))
        converge_tf(controller, mem)

        traces = tracer.export(job="tj")
        assert len(traces) == 1
        trace = traces[0]
        waits = [s for s in trace["spans"] if s["name"] == "queue.wait"]
        syncs = [s for s in trace["spans"] if s["name"] == "sync"]
        assert waits and syncs
        assert syncs[0]["parent"] == waits[0]["id"], (
            "the sync span must be the child of its measured queue wait")
        creates = [
            s for s in trace["spans"]
            if s["name"] == "api.create" and s["parent"] == syncs[0]["id"]
        ]
        # 2 pods + 2 services + 1 Created event, all under the first sync.
        assert {s["attrs"]["resource"] for s in creates} >= {
            "pods", "services"}
        assert trace["writes"] == tracer.writes_by_job()["TFJob/default/tj"] > 0
        # The aggregate counter saw the same pod creates.
        assert metrics.labeled_counter_value(
            "training_operator_apiserver_requests_total",
            "create", "pods", "200") == 2
        # And the exposition page renders the new family.
        assert "training_operator_apiserver_requests_total" in metrics.render()

    def test_gang_restart_count_before_teardown_span_order(self):
        mem = InMemoryCluster()
        tracer = Tracer()
        controller = JAXController(
            mem, queue=WorkQueue(), metrics=Metrics(), tracer=tracer)
        mem.create_job(jax_manifest(workers=4))
        converge_tf(controller, mem, key="JAXJob:default/llama")
        mem.set_pod_phase(
            "default", "llama-worker-2", POD_FAILED, exit_code=137,
            disruption_target="Preempted",
        )
        controller.queue.add("JAXJob:default/llama")
        controller.run_until_idle()

        trace = tracer.export(job="llama")[0]
        restarts = [s for s in trace["spans"] if s["name"] == "gang.restart"]
        assert restarts, "gang restart must be traced"
        span = restarts[0]
        assert span["attrs"]["counted"] is True
        assert span["attrs"]["cause"] == "InfrastructureDisruption"
        assert span["attrs"]["targets"] == 4
        children = [s for s in trace["spans"] if s["parent"] == span["id"]]
        # api.patch on coalescing-capable seams (the counted write flows
        # through patch_job_status), api.update on legacy seams — the
        # invariant accepts either, and so does this regression.
        status_writes = [
            c["id"] for c in children
            if c["name"] in ("api.update", "api.patch")
            and c["attrs"]["resource"] == "status"
            and c["attrs"]["code"] == "200"
        ]
        deletes = [
            c["id"] for c in children
            if c["name"] == "api.delete" and c["attrs"]["resource"] == "pods"
        ]
        assert status_writes and len(deletes) == 4
        assert min(status_writes) < min(deletes), (
            "the counted status write must precede every teardown delete")
        assert check_span_invariants(tracer.export()) == []

    def test_check_span_invariants_flags_inverted_order(self):
        """The auditor itself must bite: a hand-built trace where a
        teardown delete precedes the counted write is a violation."""
        tracer = Tracer()
        with tracer.span("sync", job=JOB):
            with tracer.span("gang.restart", attrs={"counted": True}):
                tracer.record_request("delete", "pods", "200")
                tracer.record_request("update", "status", "200")
        violations = check_span_invariants(tracer.export())
        assert len(violations) == 1 and "precedes" in violations[0]
        # And with no successful write at all:
        tracer2 = Tracer()
        with tracer2.span("sync", job=JOB):
            with tracer2.span("gang.restart", attrs={"counted": True}):
                tracer2.record_request("delete", "pods", "200")
        violations = check_span_invariants(tracer2.export())
        assert len(violations) == 1 and "no successful" in violations[0]
        # A resume span (counted=False) carries no obligation.
        tracer3 = Tracer()
        with tracer3.span("sync", job=JOB):
            with tracer3.span("gang.restart", attrs={"counted": False}):
                tracer3.record_request("delete", "pods", "200")
        assert check_span_invariants(tracer3.export()) == []

    def test_dump_trace_writes_build_artifact(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TRACE_DUMP_DIR", str(tmp_path))
        tracer = Tracer()
        with tracer.span("sync", job=JOB):
            pass
        path = dump_trace(tracer, "unit test/slug")
        assert path is not None
        with open(path) as f:
            payload = json.load(f)
        assert payload["traces"][0]["job"] == "tj"
        assert dump_trace(None, "x") is None


def run_traced_chaos(seed, coalescing=False):
    """A fully deterministic seeded chaos scenario on fake clocks: gang
    bring-up under write conflicts, a retryable worker failure driving a
    counted gang restart, reconverge. Returns the two byte-replay
    artifacts (fault log + span sequence). `coalescing=True` opts the
    chaos seam into write coalescing (instance-level capability — the
    class default keeps every other tier byte-identical) and pins the
    CONTROLLER clock to the fake too, so the rate-window decisions are a
    pure function of the operation sequence."""
    mem = InMemoryCluster()
    chaos = ChaosCluster(mem, ChaosSpec(seed=seed, conflict_rate=0.15))
    now = {"t": 0.0}
    queue = WorkQueue(clock=lambda: now["t"])
    tracer = Tracer()
    kwargs = {}
    if coalescing:
        chaos.supports_write_coalescing = True
        kwargs["clock"] = lambda: now["t"]
    controller = JAXController(
        chaos, queue=queue, metrics=Metrics(), tracer=tracer, **kwargs)
    mem.create_job(jax_manifest(workers=4))

    failed = {"done": False}

    def drain():
        # Only pop when an item is due — get() with a fake clock must
        # never be allowed to park on an empty queue.
        for _ in range(200):
            if not len(queue):
                return
            controller.process_next(timeout=1.0)

    for _ in range(60):
        queue.add("JAXJob:default/llama")
        drain()
        pods = sorted(mem.list_pods("default"), key=lambda p: p.metadata.name)
        for p in pods:
            if p.status.phase == POD_PENDING:
                mem.set_pod_phase("default", p.metadata.name, POD_RUNNING)
        running = [p for p in pods if p.status.phase == POD_RUNNING]
        if len(running) == 4 and not failed["done"]:
            failed["done"] = True
            mem.set_pod_phase(
                "default", "llama-worker-1", POD_FAILED, exit_code=137,
                disruption_target="Preempted",
            )
        # Advance fake time so rate-limited retries come due.
        now["t"] += 1.0
    return {
        "fault_log": list(chaos.fault_log),
        "span_sequence": tracer.span_sequence(),
        "export": tracer.export(),
    }


class TestDeterministicReplay:
    """Acceptance criterion: tracing adds ZERO nondeterminism — the same
    seed replays the identical fault log AND the identical span sequence
    (names/parents/ids/non-float attrs), run to run."""

    def test_same_seed_same_fault_log_and_span_sequence(self):
        a = run_traced_chaos(seed=77)
        b = run_traced_chaos(seed=77)
        assert a["fault_log"] == b["fault_log"]
        assert a["fault_log"], "the seed must actually inject faults"
        assert a["span_sequence"] == b["span_sequence"]
        names = {s[3] for s in a["span_sequence"]}
        assert {"sync", "gang.restart", "api.create", "api.update",
                "api.delete"} <= names, names
        assert check_span_invariants(a["export"]) == []

    def test_different_seed_diverges(self):
        a = run_traced_chaos(seed=77)
        c = run_traced_chaos(seed=78)
        assert a["fault_log"] != c["fault_log"], (
            "sanity: the artifact must be seed-sensitive or the equality "
            "assertions above prove nothing")

    def test_same_seed_replays_with_coalescing_enabled(self):
        """ISSUE 7: the replay property must survive write coalescing ON
        (capability opted in over the chaos seam, fake controller clock).
        Both artifacts byte-equal run to run, counted writes ride the
        patch verb, and the span-order audit stays green."""
        a = run_traced_chaos(seed=77, coalescing=True)
        b = run_traced_chaos(seed=77, coalescing=True)
        assert a["fault_log"] == b["fault_log"]
        assert a["fault_log"], "the seed must actually inject faults"
        assert a["span_sequence"] == b["span_sequence"]
        names = {s[3] for s in a["span_sequence"]}
        assert {"sync", "gang.restart", "api.create", "api.patch",
                "api.delete"} <= names, names
        assert check_span_invariants(a["export"]) == []
        # And the coalesced run genuinely took the other write path
        # (api.patch, not api.update) — the capability pin, not luck, is
        # what keeps the legacy tiers byte-identical. (Fault logs may
        # coincide: they are keyed per-method, and this seed's conflicts
        # land on create_service, whose call indices coalescing does not
        # move.)
        legacy = run_traced_chaos(seed=77)
        assert a["span_sequence"] != legacy["span_sequence"]
        assert "api.update" in {s[3] for s in legacy["span_sequence"]}
        assert "api.update" not in names


class TestHttpSurfaces:
    def _serve(self, manager, handler_cls):
        import http.server

        handler = type("H", (handler_cls,), {"manager": manager})
        server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server, f"http://127.0.0.1:{server.server_address[1]}"

    def test_tracez_endpoint_filters_and_limits(self):
        from tf_operator_tpu.cli import _MetricsHandler

        tracer = Tracer()
        mem = InMemoryCluster()
        manager = OperatorManager(
            mem,
            OperatorOptions(enabled_schemes=["TFJob"], health_port=0,
                            metrics_port=0, enable_tracez=True),
            metrics=Metrics(),
            tracer=tracer,
        )
        server, base = self._serve(manager, _MetricsHandler)
        try:
            mem.create_job(tf_manifest())
            controller = manager.controllers["TFJob"]
            converge_tf(controller, mem)
            with tracer.span("sync", job=("TFJob", "other", "x", "u9")):
                pass

            body = json.loads(urllib.request.urlopen(
                f"{base}/tracez").read().decode())
            assert {t["job"] for t in body["traces"]} == {"tj", "x"}
            spans = [s for t in body["traces"] for s in t["spans"]]
            assert any(s["name"] == "sync" for s in spans)

            body = json.loads(urllib.request.urlopen(
                f"{base}/tracez?namespace=default&job=tj").read().decode())
            assert [t["job"] for t in body["traces"]] == ["tj"]
            assert body["traces"][0]["writes"] > 0

            body = json.loads(urllib.request.urlopen(
                f"{base}/tracez?limit=1").read().decode())
            assert len(body["traces"]) == 1
            # limit=0 means none — not "slice from -0 = everything".
            body = json.loads(urllib.request.urlopen(
                f"{base}/tracez?limit=0").read().decode())
            assert body["traces"] == []

            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}/tracez?limit=bogus")
            assert err.value.code == 400
            # A negative limit must not silently disable limiting.
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}/tracez?limit=-5")
            assert err.value.code == 400

            # The opt-in gate (the /debugz exposure rule): flag off -> 404.
            manager.options.enable_tracez = False
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}/tracez")
            assert err.value.code == 404
        finally:
            server.shutdown()
            server.server_close()
            manager.stop()

    def test_readyz_reflects_manager_state(self):
        """Satellite check: /readyz must track started/stopped state, not
        return 200 unconditionally (verified: it gates on manager.ready)."""
        from tf_operator_tpu.cli import _HealthHandler

        manager = OperatorManager(
            InMemoryCluster(),
            OperatorOptions(enabled_schemes=["TFJob"], health_port=0,
                            metrics_port=0),
            metrics=Metrics(),
            tracer=Tracer(),
        )
        server, base = self._serve(manager, _HealthHandler)
        try:
            # Not started yet: liveness yes, readiness no.
            assert urllib.request.urlopen(f"{base}/healthz").status == 200
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}/readyz")
            assert err.value.code == 503
            manager.start()
            assert urllib.request.urlopen(f"{base}/readyz").status == 200
            # Degraded (stop signalled): readiness drops again.
            manager._stop.set()
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}/readyz")
            assert err.value.code == 503
        finally:
            server.shutdown()
            server.server_close()
            manager.stop()


class TestJsonLogStamping:
    def test_records_inside_a_sync_carry_trace_ids(self):
        tracer = Tracer()
        formatter = json_log_formatter(tracer)

        def record(msg):
            return logging.LogRecord(
                "tf_operator_tpu.test", logging.INFO, __file__, 1, msg,
                (), None)

        with tracer.span("sync", job=JOB) as span:
            stamped = json.loads(formatter.format(record("inside")))
        plain = json.loads(formatter.format(record("outside")))
        assert stamped["msg"] == "inside"
        assert stamped["job"] == "default/tj"
        assert stamped["trace_id"] == "trace-000001"
        assert stamped["span_id"] == span.span_id
        assert "trace_id" not in plain and "job" not in plain
        assert plain["level"] == "info"

    def test_log_format_flag_maps_to_json(self):
        from tf_operator_tpu.cli import build_arg_parser, options_from_args

        args = build_arg_parser().parse_args(["--log-format", "json"])
        assert options_from_args(args).json_log_format is True
        args = build_arg_parser().parse_args([])
        assert options_from_args(args).json_log_format is False


class TestTraceDumpScript:
    def _mod(self):
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                            "trace_dump.py")
        spec = importlib.util.spec_from_file_location("trace_dump", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_timeline_rendering(self):
        mod = self._mod()
        tracer = Tracer()
        with tracer.span("sync", job=JOB):
            tracer.event("fanout.wave", size=2)
            tracer.record_request("create", "pods", "200")
        text = mod.format_export(json.loads(tracer.export_json()))
        assert "trace-000001 TFJob default/tj" in text
        assert "writes=1" in text
        assert "sync" in text and "api.create" in text
        assert "* fanout.wave size=2" in text
        assert "requests: create pods 200 x1" in text
        # Filters behave like /tracez.
        assert mod.format_export(
            json.loads(tracer.export_json()), job="ghost") == "(no traces)"
