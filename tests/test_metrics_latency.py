"""Startup-latency and restart-MTTR histograms (BASELINE.md: job-startup
p50 and restart MTTR are numbers the build must establish; the reference
has no latency metrics — SURVEY.md §5.5 lists counters only)."""

from tf_operator_tpu.cluster.memory import InMemoryCluster
from tf_operator_tpu.controllers.jax import JAXController
from tf_operator_tpu.metrics import Metrics


def jaxjob(name="lat", replicas=1, restart_policy="ExitCode"):
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "jaxReplicaSpecs": {
                "Worker": {
                    "replicas": replicas,
                    "restartPolicy": restart_policy,
                    "template": {
                        "spec": {"containers": [{"name": "jax", "image": "i"}]}
                    },
                }
            }
        },
    }


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def test_startup_and_restart_latency_observed():
    clock = FakeClock()
    cluster = InMemoryCluster(clock=clock)
    metrics = Metrics()
    ctrl = JAXController(cluster, metrics=metrics, clock=clock)

    cluster.create_job(jaxjob())
    ctrl.sync("default", "lat")  # creates the pod; Created condition stamped

    clock.advance(7.0)  # pod takes 7s to come up
    cluster.set_pod_phase("default", "lat-worker-0", "Running")
    ctrl.sync("default", "lat")

    startups = metrics.histogram_values(
        "training_operator_job_startup_seconds", "default", "JAXJob"
    )
    assert startups and abs(startups[0] - 7.0) < 1e-6

    # Retryable failure (exit 130) -> Restarting; recreated pod Running
    # again 5s later -> restart MTTR observed.
    clock.advance(60.0)
    cluster.set_pod_phase("default", "lat-worker-0", "Failed", exit_code=130)
    ctrl.sync("default", "lat")  # initiates restart (deletes the pod)
    ctrl.sync("default", "lat")  # recreates the pod
    clock.advance(5.0)
    cluster.set_pod_phase("default", "lat-worker-0", "Running")
    ctrl.sync("default", "lat")

    restarts = metrics.histogram_values(
        "training_operator_job_restart_seconds", "default", "JAXJob"
    )
    assert restarts and abs(restarts[0] - 5.0) < 1e-6
    # Startup histogram did not double-count the restart.
    assert len(
        metrics.histogram_values(
            "training_operator_job_startup_seconds", "default", "JAXJob"
        )
    ) == 1


def test_render_exposes_histograms():
    metrics = Metrics()
    metrics.observe_startup("default", "JAXJob", 3.0)
    metrics.observe_restart("default", "JAXJob", 1.5)
    text = metrics.render()
    assert "training_operator_job_startup_seconds" in text
    assert "training_operator_job_restart_seconds" in text


def test_reconcile_duration_observed():
    """Every sync feeds the reconcile-duration histogram (the reference only
    logs 'Finished syncing'; here it's scrapeable)."""
    metrics = Metrics()
    cluster = InMemoryCluster()
    controller = JAXController(cluster, metrics=metrics)
    cluster.create_job(jaxjob("rd"))
    controller.run_until_idle()
    samples = metrics.histogram_values(
        "training_operator_reconcile_duration_seconds", "default", "JAXJob"
    )
    assert len(samples) >= 1
    assert all(0 <= s < 10 for s in samples)
    text = metrics.render()
    assert 'training_operator_reconcile_duration_seconds_bucket' in text
    assert 'le="0.005"' in text  # ms-scale buckets, not the seconds-scale set


def test_histograms_are_streaming_not_unbounded():
    """Aggregates stay exact while raw retention is bounded: a long-running
    operator's per-sync observations must not grow memory without limit."""
    metrics = Metrics()
    for i in range(10_000):
        metrics.observe_reconcile("default", "JAXJob", (i % 100) / 1000.0)
    retained = metrics.histogram_values(
        "training_operator_reconcile_duration_seconds", "default", "JAXJob"
    )
    assert len(retained) <= 256
    text = metrics.render()
    assert "training_operator_reconcile_duration_seconds_count" in text
    assert " 10000" in text  # exact count survives the bounded window
    # le-boundary semantics: value == bucket bound counts into that bucket.
    m2 = Metrics()
    m2.observe_startup("d", "f", 0.5)
    assert 'training_operator_job_startup_seconds_bucket{job_namespace="d",framework="f",le="0.5"} 1' in m2.render()


def test_debugz_snapshot():
    """/debugz exposes thread stacks and workqueue depths."""
    from tf_operator_tpu.cli import OperatorManager, OperatorOptions

    cluster = InMemoryCluster()
    manager = OperatorManager(
        cluster,
        OperatorOptions(enabled_schemes=["JAXJob"], health_port=0, metrics_port=0),
        metrics=Metrics(),
    )
    manager.start()
    try:
        snap = manager.debug_snapshot()
        assert snap["ready"] is True
        assert "JAXJob" in snap["queues"]
        assert set(snap["queues"]["JAXJob"]) == {"queued", "processing", "delayed", "failing"}
        # The snapshotting (main) thread must show a live stack.
        assert any(stack for stack in snap["threads"].values())
    finally:
        manager.stop()


def test_step_profiler_noop_without_env(monkeypatch):
    from tf_operator_tpu.runtime import profiling

    monkeypatch.delenv(profiling.ENV_PROFILE_DIR, raising=False)
    for step in range(5):
        profiling.step_profiler(step)  # must not import jax or raise


# ------------------------------------------------- exposition + cleanup


def test_render_escapes_label_values():
    """Prometheus text-format escaping (satellite): an exception label
    carrying backslash/quote/newline — all legal in a Python exception
    message, and sync_errors_total interpolates them — used to invalidate
    the whole exposition page."""
    metrics = Metrics()
    metrics.sync_error_inc("ns", "TFJob", 'Boom"quote\\slash\nline')
    body = metrics.render()
    line = next(
        l for l in body.splitlines()
        if l.startswith("training_operator_sync_errors_total{")
    )
    assert '\\"quote' in line, "double quote must be escaped"
    assert "\\\\slash" in line, "backslash must be escaped"
    assert "\\nline" in line, "newline must be escaped to the 2-char form"
    assert "\n" not in line  # splitlines already proves it, but explicitly:
    # Round-trip: the escaped value decodes back to the original.
    import re

    match = re.search(r'exception="((?:[^"\\]|\\.)*)"', line)
    assert match
    decoded = (
        match.group(1)
        .replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )
    assert decoded == 'Boom"quote\\slash\nline'


def test_render_escapes_namespace_in_plain_counters():
    metrics = Metrics()
    metrics.created_inc('we"ird\\ns', "TFJob")
    body = metrics.render()
    assert 'job_namespace="we\\"ird\\\\ns"' in body


def test_histogram_quantile_inf_bucket_fallback():
    """A quantile landing in the +Inf bucket (satellite): every
    observation above the top bound must report the largest recent raw
    value as a best-effort cap, not None and not a finite bucket edge."""
    metrics = Metrics()
    # Top startup bucket is 600s; push everything beyond it.
    for seconds in (700.0, 900.0, 800.0):
        metrics.observe_startup("ns", "TFJob", seconds)
    q = metrics.histogram_quantile(
        "training_operator_job_startup_seconds", "ns", "TFJob", 0.5)
    assert q == 900.0
    # Mixed: rank 1 of {0.9 (le-1 bucket), 700, 900} -> the in-range
    # path still answers with a bucket upper bound, not the raw cap.
    metrics2 = Metrics()
    for seconds in (0.9, 700.0, 900.0):
        metrics2.observe_startup("ns", "TFJob", seconds)
    assert metrics2.histogram_quantile(
        "training_operator_job_startup_seconds", "ns", "TFJob", 0.3) == 1.0
    # No observations at all: None, not a crash.
    assert metrics2.histogram_quantile(
        "training_operator_job_startup_seconds", "other", "TFJob", 0.5) is None


def test_heartbeat_age_series_cleared_on_job_deletion():
    """The gauge-leak class (satellite): a deleted job's heartbeat-age
    series must leave the exposition page, or churn grows the gauge map
    (and the staleness alert pages for a ghost) forever."""
    metrics = Metrics()
    metrics.set_heartbeat_age("default", "JAXJob", "lat", 12.5)
    assert metrics.heartbeat_age_value("default", "JAXJob", "lat") == 12.5
    assert "training_operator_heartbeat_age_seconds{" in metrics.render()
    metrics.clear_heartbeat_age("default", "JAXJob", "lat")
    assert metrics.heartbeat_age_value("default", "JAXJob", "lat") is None
    assert 'job_name="lat"' not in metrics.render()
    # Clearing an unknown series is a no-op, not a KeyError.
    metrics.clear_heartbeat_age("default", "JAXJob", "ghost")


def test_forget_terminal_prunes_dedup_and_controller_forgets_on_delete():
    """forget_terminal (satellite): the UID-keyed terminal dedup must be
    prunable — and a recreated job with a fresh UID counts again — plus
    the controller end-to-end: a DELETED watch event clears both the
    dedup entry and the heartbeat gauge via _forget."""
    metrics = Metrics()
    metrics.successful_inc_once("ns", "TFJob", "uid-1")
    metrics.successful_inc_once("ns", "TFJob", "uid-1")  # deduped
    assert metrics.counter_value(
        "training_operator_jobs_successful_total", "ns", "TFJob") == 1
    metrics.forget_terminal("TFJob", "uid-1")
    metrics.successful_inc_once("ns", "TFJob", "uid-1")
    assert metrics.counter_value(
        "training_operator_jobs_successful_total", "ns", "TFJob") == 2

    # Controller path: DELETED event -> _forget -> both series pruned.
    from tf_operator_tpu.core.workqueue import WorkQueue

    mem = InMemoryCluster()
    cmetrics = Metrics()
    controller = JAXController(mem, queue=WorkQueue(), metrics=cmetrics)
    mem.create_job(jaxjob(name="lat"))
    job = mem.get_job("JAXJob", "default", "lat")
    uid = job["metadata"]["uid"]
    controller._note_uid("default/lat", uid)
    cmetrics.set_heartbeat_age("default", "JAXJob", "lat", 30.0)
    cmetrics.failed_inc_once("default", "JAXJob", uid)
    mem.delete_job("JAXJob", "default", "lat")
    assert cmetrics.heartbeat_age_value("default", "JAXJob", "lat") is None, (
        "DELETED event must clear the heartbeat-age series")
    # Dedup entry pruned: the same UID counts again (name reuse with the
    # SAME uid cannot happen on a real apiserver; this asserts the prune).
    cmetrics.failed_inc_once("default", "JAXJob", uid)
    assert cmetrics.counter_value(
        "training_operator_jobs_failed_total", "default", "JAXJob") == 2
