"""Startup-latency and restart-MTTR histograms (BASELINE.md: job-startup
p50 and restart MTTR are numbers the build must establish; the reference
has no latency metrics — SURVEY.md §5.5 lists counters only)."""

from tf_operator_tpu.cluster.memory import InMemoryCluster
from tf_operator_tpu.controllers.jax import JAXController
from tf_operator_tpu.metrics import Metrics


def jaxjob(name="lat", replicas=1, restart_policy="ExitCode"):
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "jaxReplicaSpecs": {
                "Worker": {
                    "replicas": replicas,
                    "restartPolicy": restart_policy,
                    "template": {
                        "spec": {"containers": [{"name": "jax", "image": "i"}]}
                    },
                }
            }
        },
    }


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def test_startup_and_restart_latency_observed():
    clock = FakeClock()
    cluster = InMemoryCluster(clock=clock)
    metrics = Metrics()
    ctrl = JAXController(cluster, metrics=metrics, clock=clock)

    cluster.create_job(jaxjob())
    ctrl.sync("default", "lat")  # creates the pod; Created condition stamped

    clock.advance(7.0)  # pod takes 7s to come up
    cluster.set_pod_phase("default", "lat-worker-0", "Running")
    ctrl.sync("default", "lat")

    startups = metrics.histogram_values(
        "training_operator_job_startup_seconds", "default", "JAXJob"
    )
    assert startups and abs(startups[0] - 7.0) < 1e-6

    # Retryable failure (exit 130) -> Restarting; recreated pod Running
    # again 5s later -> restart MTTR observed.
    clock.advance(60.0)
    cluster.set_pod_phase("default", "lat-worker-0", "Failed", exit_code=130)
    ctrl.sync("default", "lat")  # initiates restart (deletes the pod)
    ctrl.sync("default", "lat")  # recreates the pod
    clock.advance(5.0)
    cluster.set_pod_phase("default", "lat-worker-0", "Running")
    ctrl.sync("default", "lat")

    restarts = metrics.histogram_values(
        "training_operator_job_restart_seconds", "default", "JAXJob"
    )
    assert restarts and abs(restarts[0] - 5.0) < 1e-6
    # Startup histogram did not double-count the restart.
    assert len(
        metrics.histogram_values(
            "training_operator_job_startup_seconds", "default", "JAXJob"
        )
    ) == 1


def test_render_exposes_histograms():
    metrics = Metrics()
    metrics.observe_startup("default", "JAXJob", 3.0)
    metrics.observe_restart("default", "JAXJob", 1.5)
    text = metrics.render()
    assert "training_operator_job_startup_seconds" in text
    assert "training_operator_job_restart_seconds" in text
