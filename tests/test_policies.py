"""Pluggable-admission-policy tier (core/policies.py,
docs/design/gang_admission.md "Policy seam"): the pure-function seam
behind AdmissionController — the priority policy's byte-identical
re-expression of the PR 9 arbiter, gavel's heterogeneity-aware
placement (effective-throughput maximization + improvement-gated
preemption), drf's weighted work-conserving fairness, the extended
--capacity generation syntax, and the determinism audit: decisions are
a pure function of (queue, pool, usage, seed), proven by a 3-run
byte-equal decision-log regression per policy."""

import json

import pytest

from tf_operator_tpu.api.defaulting import ValidationError
from tf_operator_tpu.core.admission import (
    AdmissionController,
    PREEMPT_CAUSE_CAPACITY,
    gang_demand,
    parse_capacity_flag,
    parse_resource_list,
    parse_tenant_weight,
)
from tf_operator_tpu.core.policies import (
    PREEMPT_CAUSE_THROUGHPUT,
    build_policy,
)
from tf_operator_tpu.metrics import Metrics
from tf_operator_tpu.testing.invariants import check_admission_invariants


class FakeClock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


SENSITIVE = {"v5lite": 0.25, "v6": 1.0}


def controller(policy="priority", capacity="pods@v5lite=8,pods@v6=8",
               clock=None, weights=None, seed=0, quotas=None, **kw):
    flat, gens = parse_capacity_flag(capacity)
    return AdmissionController(
        capacity=flat or None, generations=gens or None,
        quotas=quotas, policy=policy, tenant_weights=weights, seed=seed,
        metrics=Metrics(), clock=clock or FakeClock(), **kw,
    )


def ask(adm, name, members=4, namespace="default", ratios=None, priority="",
        **kw):
    return adm.try_admit(
        key=f"JAXJob:{namespace}/{name}", kind="JAXJob", namespace=namespace,
        name=name, uid=f"uid-{namespace}-{name}", demand={"pods": members},
        members=members, priority_class=priority,
        throughput_ratios=dict(ratios or {}), **kw,
    )


def placements(adm):
    snap = adm.snapshot()
    return {
        e["key"].rpartition("/")[2]: e.get("generation")
        for e in snap["admitted"]
    }


# ------------------------------------------------------------- flag parsing


class TestCapacityFlagParsing:
    def test_plain_entries_stay_flat(self):
        flat, gens = parse_capacity_flag("pods=16,google.com/tpu=32")
        assert flat == {"pods": "16", "google.com/tpu": "32"}
        assert gens == {}

    def test_generation_entries(self):
        flat, gens = parse_capacity_flag("pods@v5lite=8,pods@v6=8,cpu=4")
        assert flat == {"cpu": "4"}
        assert gens == {"v5lite": {"pods": "8"}, "v6": {"pods": "8"}}

    @pytest.mark.parametrize("bad", [
        "pods@=8",          # empty generation
        "@v6=8",            # empty resource
        "pods@v6",          # no quantity
        "pods@v6=abc",      # malformed quantity
        "pods@v6=-2",       # negative sub-pool
        "pods@v6=8,pods@v6=4",  # duplicate resource in one generation
    ])
    def test_malformed_rejected(self, bad):
        with pytest.raises(ValueError):
            parse_capacity_flag(bad)

    def test_flat_pool_is_generation_sum(self):
        adm = controller(capacity="pods@a=8,pods@b=8,pods=4")
        cap = adm.effective_capacity()
        assert cap["pods"] == 20  # 8 + 8 + the flat 4

    def test_tenant_weight_parsing(self):
        assert parse_tenant_weight("team-a=2.5") == {"team-a": 2.5}
        for bad in ("team-a", "=2", "a=zero", "a=0", "a=-1", "a=inf",
                    "a=nan"):
            with pytest.raises(ValueError):
                parse_tenant_weight(bad)

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError):
            build_policy("fifo-but-wrong")


class TestResourceListEdgeCases:
    """Satellite coverage: fractional cpu strings, zero/negative
    values, unknown resource keys."""

    def test_fractional_cpu_forms(self):
        out = parse_resource_list("cpu=0.5,mem=500m")
        assert out == {"cpu": "0.5", "mem": "500m"}
        # Both spellings of half a core aggregate identically.
        demand = gang_demand([
            {"spec": {"minMember": 1, "minResources": {"cpu": "0.5"}}},
            {"spec": {"minMember": 1, "minResources": {"cpu": "500m"}}},
        ])
        assert demand["cpu"] == 1

    def test_zero_is_a_legal_bound(self):
        assert parse_resource_list("pods=0") == {"pods": "0"}

    def test_negative_quantities_rejected(self):
        with pytest.raises(ValueError):
            parse_resource_list("pods=-4")

    def test_unknown_resource_keys_flow_through(self):
        out = parse_resource_list("vendor.io/weird-chip=3")
        assert out == {"vendor.io/weird-chip": "3"}

    def test_gang_demand_skips_malformed_and_zero_members(self):
        demand = gang_demand([
            {"spec": {"minMember": 0,
                      "minResources": {"cpu": "garbage", "mem": "1Gi"}}},
        ])
        # Malformed stored quantity skipped, zero members -> no pods key.
        assert "pods" not in demand
        assert "cpu" not in demand
        assert demand["mem"] == 2 ** 30

    def test_gang_demand_missing_spec(self):
        assert gang_demand([{}]) == {}

    def test_quota_flag_edge_cases(self):
        from tf_operator_tpu.core.admission import parse_quota_flag

        assert parse_quota_flag("ns-a:cpu=0.5,pods=0") == {
            "ns-a": {"cpu": "0.5", "pods": "0"}}
        for bad in ("no-colon", ":cpu=1", "ns:cpu=-1", "ns:cpu=junk",
                    "ns:cpu"):
            with pytest.raises(ValueError):
                parse_quota_flag(bad)


class TestThroughputRatiosValidation:
    def _validate(self, ratios):
        from tf_operator_tpu.api.common import RunPolicy, SchedulingPolicy
        from tf_operator_tpu.api.defaulting import validate_scheduling_policy

        rp = RunPolicy(scheduling_policy=SchedulingPolicy(
            throughput_ratios=ratios))
        validate_scheduling_policy(rp, "JAXJob")

    def test_valid_ratios_accepted(self):
        self._validate({"v5lite": 0.25, "v6": 1, "v7": 2.5})

    @pytest.mark.parametrize("bad", [
        {"v6": "fast"},         # non-numeric
        {"v6": 0},              # zero divides the job out of the objective
        {"v6": -1.0},           # negative inverts the greedy comparison
        {"v6": float("inf")},
        {"v6": float("nan")},
        {"v6": True},           # bool is not a ratio
        {"": 1.0},              # empty generation key
        {3: 1.0},               # non-string key
    ])
    def test_malformed_ratios_rejected(self, bad):
        with pytest.raises(ValidationError):
            self._validate(bad)


# ------------------------------------------------------------ gavel policy


class TestGavelPlacement:
    def test_sensitive_jobs_get_the_fast_generation(self):
        """The head-to-head the contention gate measures: the default
        first-fits sensitive jobs onto the slow pool; gavel never
        does while the fast one has room."""
        prio = controller("priority")
        gavel = controller("gavel")
        for adm in (prio, gavel):
            ask(adm, "s0", ratios=SENSITIVE)
            ask(adm, "s1", ratios=SENSITIVE)
            ask(adm, "f0")
            ask(adm, "f1")
        assert placements(prio) == {
            "s0": "v5lite", "s1": "v5lite", "f0": "v6", "f1": "v6"}
        assert placements(gavel) == {
            "s0": "v6", "s1": "v6", "f0": "v5lite", "f1": "v5lite"}
        assert prio.effective_throughput() == pytest.approx(10.0)
        assert gavel.effective_throughput() == pytest.approx(16.0)

    def test_work_conserving_fallback(self):
        """With the fast generation full of equally-fast tenants (no
        improving swap exists), a sensitive gang takes the slow slots
        rather than idling them — utilization is half the objective."""
        adm = controller("gavel")
        ask(adm, "f0", members=8)          # fills v5lite or v6 (tie -> v5lite)
        ask(adm, "f1", members=8)          # fills the other
        adm.release("JAXJob:default/f0")   # free one pool
        ask(adm, "s0", ratios=SENSITIVE, members=4)
        ask(adm, "s1", ratios=SENSITIVE, members=4)
        placed = placements(adm)
        # f1 holds one generation whole; both sensitive gangs run on
        # whatever remains (one of them at 0.25x) instead of waiting.
        assert placed["s0"] is not None and placed["s1"] is not None
        assert adm.preemption_requested("JAXJob:default/f1") is None

    def test_preempt_to_improve_strict_gain(self):
        """The Gavel swap: evicting a small flexible gang from the fast
        generation strictly raises fleet-wide effective throughput, so
        gavel preempts it (cause ThroughputPreemption) and the head
        takes the fast slots; the victim re-queues and re-places."""
        clock = FakeClock()
        adm = controller("gavel", capacity="pods@v5lite=8,pods@v6=4",
                         clock=clock)
        # Small flexible gang that mildly prefers v6.
        ask(adm, "f0", members=2, ratios={"v5lite": 0.9, "v6": 1.0})
        assert placements(adm)["f0"] == "v6"
        # Gen-sensitive 4-member head: v6 (gain 4.0) beats both the
        # v5lite fallback (1.0) and f0's current contribution (2.0).
        result = ask(adm, "s0", members=4, ratios=SENSITIVE)
        assert not result.admitted
        cause = adm.preemption_requested("JAXJob:default/f0")
        assert cause == PREEMPT_CAUSE_THROUGHPUT
        # Engine ack: the counted teardown completed.
        adm.note_preempted("JAXJob:default/f0", "uid-default-f0", cause)
        assert ask(adm, "s0", members=4, ratios=SENSITIVE).admitted
        placed = placements(adm)
        assert placed["s0"] == "v6"
        # The victim re-placed on the slow pool it is nearly as fast on.
        assert placed["f0"] == "v5lite"
        assert adm.effective_throughput() == pytest.approx(4.0 + 1.8)

    def test_head_waits_out_its_own_pending_swap(self):
        """A pump landing BETWEEN a swap's preempt-mark and its teardown
        ack must keep the head waiting for the generation being freed —
        admitting it onto the inferior generation would waste the
        eviction it just ordered (victim gone AND head at 0.25x)."""
        adm = controller("gavel", capacity="pods@v5lite=4,pods@v6=4")
        ask(adm, "f0", members=2, ratios={"v5lite": 0.9, "v6": 1.0})
        assert placements(adm)["f0"] == "v6"
        # Pump 1: marks f0 (strict gain 4 - 2 > 1).
        assert not ask(adm, "s0", members=4, ratios=SENSITIVE).admitted
        assert adm.preemption_requested(
            "JAXJob:default/f0") == PREEMPT_CAUSE_THROUGHPUT
        # Pump 2, BEFORE the engine acks the teardown: the head must
        # stay blocked on the pending eviction, not settle for v5lite.
        result = ask(adm, "s0", members=4, ratios=SENSITIVE)
        assert not result.admitted
        assert result.blocked_on == "priority"
        # Ack lands -> the head takes the generation it waited for.
        adm.note_preempted("JAXJob:default/f0", "uid-default-f0",
                           PREEMPT_CAUSE_THROUGHPUT)
        assert ask(adm, "s0", members=4, ratios=SENSITIVE).admitted
        assert placements(adm)["s0"] == "v6"

    def test_no_preemption_without_strict_gain(self):
        """A zero-sum swap (equal contribution) must NOT preempt —
        churn without throughput gain is the livelock Gavel's strict
        inequality exists to prevent."""
        adm = controller("gavel", capacity="pods@v5lite=8,pods@v6=4")
        ask(adm, "f0", members=4, ratios={"v5lite": 0.9, "v6": 1.0})
        result = ask(adm, "s0", members=4, ratios=SENSITIVE)
        # gain 4.0 - lost 4.0 = 0 <= beat 1.0 (the v5lite fallback):
        # admit there instead.
        assert result.admitted
        assert placements(adm)["s0"] == "v5lite"
        assert adm.preemption_requested("JAXJob:default/f0") is None

    def test_generation_sub_pool_never_exceeded(self):
        adm = controller("gavel")
        for i in range(5):
            ask(adm, f"j{i}", members=4, ratios=SENSITIVE)
        assert check_admission_invariants(adm) == []
        snap = adm.snapshot()
        assert snap["policy"] == "gavel"
        gens = snap["generations"]
        assert set(gens) == {"v5lite", "v6"}
        for pools in gens.values():
            assert int(pools["usage"].get("pods", "0")) <= int(
                pools["capacity"]["pods"])

    def test_swap_prunes_gratuitous_victims(self):
        """The cheapest-first victim greedy can collect a small gang
        whose room a later, bigger victim makes unnecessary — the prune
        pass must evict ONLY the load-bearing victim."""
        adm = controller("gavel", capacity="pods@v5lite=8,pods@v6=6")
        ask(adm, "c1", members=2, ratios={"v5lite": 0.3, "v6": 0.4})
        ask(adm, "c2", members=4, ratios={"v5lite": 0.4, "v6": 0.5})
        assert placements(adm) == {"c1": "v6", "c2": "v6"}
        result = ask(adm, "s0", members=4, ratios=SENSITIVE)
        assert not result.admitted
        # c2 alone frees the 4 slots the head needs; c1 (cheaper but
        # useless alone) must NOT be collateral damage.
        assert adm.preemption_requested("JAXJob:default/c1") is None
        assert adm.preemption_requested(
            "JAXJob:default/c2") == PREEMPT_CAUSE_THROUGHPUT

    def test_clearing_throughput_ratios_takes_effect(self):
        """Deleting schedulingPolicy.throughputRatios from the spec must
        clear the stored ratios — the engine passes {} and the gang
        becomes generation-indifferent again."""
        adm = controller("gavel", capacity="pods@v5lite=4")
        ask(adm, "j0", members=4, ratios={"v5lite": 0.25})
        assert adm.effective_throughput() == pytest.approx(1.0)
        ask(adm, "j0", members=4, ratios={})
        assert adm.effective_throughput() == pytest.approx(4.0)

    def test_adoption_places_into_generation_sub_pools(self):
        """Operator-restart adoption (has_pods): live gangs must charge
        a generation sub-pool, or placement sees every sub-pool empty
        and oversubscribes real chips."""
        adm = controller("gavel", capacity="pods@v5lite=4,pods@v6=4")
        ask(adm, "j0", members=4, has_pods=True)
        ask(adm, "j1", members=4, has_pods=True)
        assert placements(adm) == {"j0": "v5lite", "j1": "v6"}
        # A newcomer must now wait — nothing looks free.
        assert not ask(adm, "j2", members=4).admitted
        assert check_admission_invariants(adm) == []

    def test_adoption_overcommit_resolves_by_preemption(self):
        """Adoption can oversubscribe ONE generation while the flat pool
        still fits (fragmented live pods); the generation-revocation
        sweep must preempt-to-fit, newest adoptee first."""
        adm = controller("priority", capacity="pods@v5lite=4,pods@v6=4")
        ask(adm, "j0", members=3, has_pods=True)   # v5lite 3/4
        ask(adm, "j1", members=3, has_pods=True)   # v6 3/4
        ask(adm, "j2", members=2, has_pods=True)   # nowhere fits -> v5lite 5/4
        assert adm.preemption_requested(
            "JAXJob:default/j2") == PREEMPT_CAUSE_CAPACITY
        assert adm.preemption_requested("JAXJob:default/j0") is None
        adm.note_preempted("JAXJob:default/j2", "uid-default-j2",
                           PREEMPT_CAUSE_CAPACITY)
        assert check_admission_invariants(adm) == []

    def test_generation_invariant_catches_overcommit(self):
        class Stub:
            def snapshot(self):
                return {
                    "capacity": {"pods": "16"}, "usage": {"pods": "12"},
                    "generations": {
                        "v6": {"capacity": {"pods": "8"},
                               "usage": {"pods": "12"}},
                    },
                }

        violations = check_admission_invariants(Stub())
        assert any("generation v6" in v for v in violations)


# -------------------------------------------------------------- drf policy


class TestDrfFairness:
    def test_release_time_selection_tracks_weights(self):
        """Weighted DRF's mechanism: when capacity frees, the next
        admit goes to the tenant with the smallest share/weight — the
        2x tenant converges to 2x the slots."""
        clock = FakeClock()
        adm = controller("drf", capacity="pods=12", clock=clock,
                         weights={"a": 2.0, "b": 1.0})
        # Saturate: interleaved streams register; 6 jobs admit
        # first-come, the rest wait.
        for i in range(8):
            for ns in ("a", "b"):
                ask(adm, f"j{i}", members=2, namespace=ns)
        # Drain-and-refill: every release hands the slot to whichever
        # tenant is most underserved by weight.
        for i in range(3):
            adm.release(f"JAXJob:b/j{i}")
        shares = adm.dominant_shares()
        assert shares["a"] / shares["b"] == pytest.approx(2.0, rel=1e-4)

    def test_work_conserving_single_tenant_takes_all(self):
        adm = controller("drf", capacity="pods=8",
                         weights={"a": 3.0, "b": 1.0})
        for i in range(4):
            ask(adm, f"j{i}", members=2, namespace="a")
        # No hard ceiling: tenant a alone owns the whole pool.
        assert adm.dominant_shares() == {"a": 1.0}
        assert check_admission_invariants(adm) == []

    def test_capacity_revocation_evicts_largest_share_first(self):
        live = {"pods": "12"}
        adm = controller("drf", capacity="pods=12",
                         weights={"a": 1.0, "b": 1.0},
                         capacity_fn=lambda: live)
        for i in range(4):
            ask(adm, f"a{i}", members=2, namespace="a")
        ask(adm, "b0", members=2, namespace="b")
        live["pods"] = "8"
        ask(adm, "b0", members=2, namespace="b")  # any sync pumps
        # 10 admitted pods over the shrunken 8-pod pool: ONE eviction
        # suffices, and it comes from the 8-pod tenant (largest
        # weighted share), newest admit first — never the 2-pod one.
        pending = [
            key for key in (f"JAXJob:a/a{i}" for i in range(4))
            if adm.preemption_requested(key)
        ]
        assert pending == ["JAXJob:a/a3"]
        assert adm.preemption_requested("JAXJob:b/b0") is None
        assert adm.preemption_requested(pending[0]) == PREEMPT_CAUSE_CAPACITY


# ------------------------------------------------------ determinism audit


def drive_script(policy, seed=0):
    """A fixed mixed scenario (bands, tenants, ratios, a release, a
    revocation + ack) on a fake clock: the decision log must come out
    byte-identical run over run — decisions are a pure function of
    (queue, pool, usage, seed)."""
    clock = FakeClock()
    live = {"pods": "16"}
    adm = controller(policy, capacity="pods@v5lite=8,pods@v6=8",
                     clock=clock, weights={"a": 2.0, "b": 1.0}, seed=seed,
                     capacity_fn=lambda: dict(live))
    ask(adm, "s0", members=4, namespace="a", ratios=SENSITIVE,
        priority="high")
    clock.advance(1.0)
    ask(adm, "f0", members=4, namespace="b")
    ask(adm, "f1", members=4, namespace="b", priority="low")
    clock.advance(1.0)
    ask(adm, "s1", members=4, namespace="a", ratios=SENSITIVE)
    ask(adm, "s2", members=4, namespace="a", ratios=SENSITIVE)
    adm.release("JAXJob:b/f0")
    clock.advance(1.0)
    ask(adm, "s2", members=4, namespace="a", ratios=SENSITIVE)
    live["pods"] = "8"
    ask(adm, "f1", members=4, namespace="b", priority="low")
    for key in ("JAXJob:b/f1", "JAXJob:a/s0", "JAXJob:a/s1",
                "JAXJob:a/s2"):
        cause = adm.preemption_requested(key)
        if cause:
            adm.note_preempted(key, f"uid-{key}", cause)
    clock.advance(1.0)
    ask(adm, "s2", members=4, namespace="a", ratios=SENSITIVE)
    return adm.decision_log_lines()


class TestDecisionDeterminism:
    @pytest.mark.parametrize("policy", ["priority", "gavel", "drf"])
    def test_same_seed_three_runs_byte_equal(self, policy):
        runs = [drive_script(policy, seed=7) for _ in range(3)]
        assert runs[0] == runs[1] == runs[2]
        assert runs[0], "script produced no decisions — scenario broken"
        # Every line is canonical JSON stamped with policy + seed.
        for line in runs[0]:
            entry = json.loads(line)
            assert entry["policy"] == policy
            assert entry["seed"] == 7

    def test_policies_disagree_on_the_same_script(self):
        """The seam is live: different policies produce different
        schedules from identical input (placement differs even when
        admit order agrees)."""
        assert drive_script("priority") != drive_script("gavel")


# ----------------------------------------------------- snapshot back-compat


class TestSnapshotShape:
    def test_homogeneous_pool_keeps_pr9_shape(self):
        adm = controller("priority", capacity="pods=8")
        ask(adm, "j0", members=4)
        snap = adm.snapshot()
        # PR 9 keys intact for the smoke JSON and old dashboards.
        for key in ("capacity", "usage", "quotas", "namespace_usage",
                    "aging_seconds", "backfill_max_members", "admitted",
                    "waiting", "preempting", "admit_log",
                    "preemption_ledger"):
            assert key in snap
        # No generation keys leak into homogeneous-pool snapshots.
        assert "generations" not in snap
        assert all("generation" not in e for e in snap["admitted"])
        assert all("generation" not in e for e in snap["admit_log"])
        # The additive policy-seam keys.
        assert snap["policy"] == "priority"
        assert snap["seed"] == 0
        assert snap["effective_throughput"] == pytest.approx(4.0)
        assert snap["dominant_shares"] == {"default": 0.5}

    def test_dominant_share_gauge_exported(self):
        metrics = Metrics()
        adm = AdmissionController(
            capacity={"pods": "8"}, metrics=metrics, clock=FakeClock())
        ask(adm, "j0", members=4, namespace="tenant-a")
        assert metrics.admission_dominant_share_value("tenant-a") == 0.5
        assert metrics.gauge_value(
            "training_operator_admission_effective_throughput") == 4.0
        rendered = metrics.render()
        assert "training_operator_admission_dominant_share" in rendered
        adm.release("JAXJob:tenant-a/j0")
        assert metrics.admission_dominant_share_value("tenant-a") is None
