"""WorkQueue unit coverage for the deque-backed immediate queue.

The immediate queue used to be a plain list popped at index 0 — O(n) per
pop, paid by every worker of the sync pool on every get once backlogs
grow. The deque swap must not change any visible semantics: strict FIFO
order, while-queued dedup, the dirty re-queue for items enqueued while
processing, and delayed items joining at their due time.
"""

from tf_operator_tpu.core.workqueue import WorkQueue


class FakeClock:
    def __init__(self, now=0.0):
        self.now = now

    def __call__(self):
        return self.now


def drain(q, limit=100):
    out = []
    for _ in range(limit):
        item = q.get(timeout=0)
        if item is None:
            break
        out.append(item)
        q.done(item)
    return out


class TestFifoOrder:
    def test_adds_pop_in_fifo_order(self):
        q = WorkQueue(clock=FakeClock())
        for item in ("a", "b", "c", "d", "e"):
            q.add(item)
        assert drain(q) == ["a", "b", "c", "d", "e"]

    def test_dedup_keeps_first_position(self):
        """Re-adding a queued item neither duplicates it nor moves it to
        the back (client-go set-queue semantics)."""
        q = WorkQueue(clock=FakeClock())
        q.add("a")
        q.add("b")
        q.add("a")  # dedup: "a" stays at the head
        q.add("c")
        assert drain(q) == ["a", "b", "c"]

    def test_dirty_requeue_preserves_order_behind_existing(self):
        """An item re-added while processing goes dirty and re-queues on
        done() — behind items that were already waiting."""
        q = WorkQueue(clock=FakeClock())
        q.add("a")
        q.add("b")
        item = q.get(timeout=0)
        assert item == "a"
        q.add("a")  # processing -> dirty, not queued
        assert len(q) == 1  # only "b" waits
        q.done("a")  # dirty "a" re-queues behind "b"
        assert drain(q) == ["b", "a"]

    def test_delayed_items_join_at_due_time_in_due_order(self):
        clock = FakeClock()
        q = WorkQueue(clock=clock)
        q.add_after("late", 10.0)
        q.add_after("early", 5.0)
        q.add("now")
        assert q.get(timeout=0) == "now"
        q.done("now")
        assert q.get(timeout=0) is None  # nothing due yet
        clock.now = 6.0
        assert q.get(timeout=0) == "early"
        q.done("early")
        clock.now = 11.0
        assert q.get(timeout=0) == "late"
        q.done("late")

    def test_interleaved_adds_and_pops_stay_fifo(self):
        q = WorkQueue(clock=FakeClock())
        q.add("a")
        q.add("b")
        assert q.get(timeout=0) == "a"
        q.add("c")
        q.done("a")
        assert q.get(timeout=0) == "b"
        q.done("b")
        q.add("d")
        assert q.get(timeout=0) == "c"
        q.done("c")
        assert q.get(timeout=0) == "d"
        q.done("d")

    def test_depth_and_len_track_the_deque(self):
        q = WorkQueue(clock=FakeClock())
        for item in ("a", "b", "c"):
            q.add(item)
        assert len(q) == 3
        assert q.depth()["queued"] == 3
        assert q.get(timeout=0) == "a"
        assert len(q) == 2
        assert q.depth()["processing"] == 1
