"""Overlapped input pipeline, correctness tier (docs/design/
workload_performance.md): the device-side double buffer
(train.data.DevicePrefetch) may change WHEN host->device transfers happen,
never WHAT the model trains on.

Three contracts:
- loss parity: overlap on vs off, same seed -> byte-equal loss sequence
  (the seed-determinism half of the acceptance rule: prefetch needs no
  capability gate because it cannot perturb a replay);
- donation safety: a step donating its batch buffer never aliases the
  in-flight buffer (every yielded batch is a distinct transfer);
- resume accounting: the TokenFileDataset skip-window contract holds
  THROUGH the device stage — skip is a function of steps trained, and the
  in-flight batches of a killed process are re-produced, not skipped.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from tf_operator_tpu.models import llama  # noqa: E402
from tf_operator_tpu.parallel.mesh import standard_mesh  # noqa: E402
from tf_operator_tpu.parallel.sharding import batch_sharding  # noqa: E402
from tf_operator_tpu.train.data import (  # noqa: E402
    DevicePrefetch,
    SyntheticTokens,
    TokenFileDataset,
    shard_batch,
    write_token_file,
)
from tf_operator_tpu.train.train_step import (  # noqa: E402
    init_sharded_train_state,
    make_optimizer,
    make_train_step,
)

BATCH, SEQ = 4, 32


def _tiny_step(donate_batch=False, n_devices=2):
    cfg = llama.CONFIGS["llama-tiny"]
    mesh = standard_mesh(n_devices, devices=jax.devices()[:n_devices])
    model = llama.Llama(cfg)
    opt = make_optimizer(warmup_steps=1, decay_steps=10)
    state, sharding = init_sharded_train_state(
        model, jax.random.PRNGKey(0), opt, mesh, batch=1, seq=SEQ
    )
    step_fn, _ = make_train_step(
        model, opt, mesh, state, sharding=sharding, donate_batch=donate_batch
    )
    return cfg, mesh, step_fn, state


class TestLossParity:
    def test_overlap_on_off_byte_equal_loss_sequence(self):
        """Same seed, same steps: the prefetched run's loss floats must be
        BIT-identical to the inline-device_put run's — the overlap stage
        feeds the exact same batches in the exact same order."""
        runs = []
        for overlap in (False, True):
            cfg, mesh, step_fn, state = _tiny_step()
            data = SyntheticTokens(BATCH, SEQ, cfg.vocab_size, seed=7)
            spec = batch_sharding(mesh, with_sp=False)
            if overlap:
                it = DevicePrefetch(data, spec, depth=2)
            else:
                host = iter(data)
                it = (shard_batch(next(host), spec) for _ in iter(int, 1))
            losses = []
            for _ in range(6):
                state, loss = step_fn(state, next(it))
                losses.append(float(loss))
            runs.append(losses)
        assert runs[0] == runs[1]  # exact float equality, not approx


class TestDonationSafety:
    def test_distinct_buffers_and_no_use_after_donate(self):
        """Every yielded batch is its own device buffer; with the batch
        argument donated, stepping never invalidates an in-flight batch."""
        cfg, mesh, step_fn, state = _tiny_step(donate_batch=True)
        data = SyntheticTokens(BATCH, SEQ, cfg.vocab_size, seed=3)
        pf = DevicePrefetch(data, batch_sharding(mesh, with_sp=False), depth=3)
        seen_ids = set()
        for _ in range(5):
            batch = next(pf)
            assert id(batch) not in seen_ids
            seen_ids.add(id(batch))
            state, loss = step_fn(state, batch)
            # The IN-FLIGHT buffers must remain readable after the step
            # donated `batch` — an aliasing bug would have deleted them.
            for pending in list(pf._buf):
                np.asarray(pending)
        assert np.isfinite(float(loss))

    def test_depth_one_degrades_to_inline_transfer(self):
        cfg, mesh, step_fn, state = _tiny_step()
        data = SyntheticTokens(BATCH, SEQ, cfg.vocab_size, seed=1)
        pf = DevicePrefetch(data, batch_sharding(mesh, with_sp=False), depth=1)
        state, loss = step_fn(state, next(pf))
        assert np.isfinite(float(loss))
        with pytest.raises(ValueError):
            DevicePrefetch(data, batch_sharding(mesh, with_sp=False), depth=0)

    def test_finite_host_iterator_drains_cleanly(self):
        mesh = standard_mesh(2, devices=jax.devices()[:2])
        spec = batch_sharding(mesh, with_sp=False)
        host = [np.full((2, 4), i, np.int32) for i in range(3)]
        pf = DevicePrefetch(iter(host), spec, depth=2)
        got = [int(np.asarray(b)[0, 0]) for b in pf]
        assert got == [0, 1, 2]
        with pytest.raises(StopIteration):
            next(pf)


class TestSkipWindowResume:
    def _write_shard(self, tmp_path, n_tokens=20_000):
        path = str(tmp_path / "tokens.bin")
        rng = np.random.default_rng(11)
        write_token_file(path, rng.integers(0, 250, size=n_tokens,
                                            dtype=np.int32))
        return path

    def test_resume_stream_matches_through_device_stage(self, tmp_path):
        """Train k steps through the prefetcher, 'crash', resume with
        skip_windows = k * batch: the resumed HOST stream must produce
        exactly the batch the prefetched run yields at step k — the
        in-flight buffer is neither double-consumed nor skipped."""
        path = self._write_shard(tmp_path)
        batch, seq = 2, 16
        mesh = standard_mesh(2, devices=jax.devices()[:2])
        spec = batch_sharding(mesh, with_sp=False)
        trained_steps = 3
        ds = TokenFileDataset(path, batch, seq)
        pf = DevicePrefetch(ds, spec, depth=2)
        first_run = [np.asarray(next(pf)) for _ in range(trained_steps)]
        # The prefetcher has in-flight batches beyond the trained steps —
        # the ones a crash would discard.
        assert pf.in_flight > 0
        # Resume: a fresh dataset skipping exactly steps*batch windows
        # (what llama_train derives from the checkpointed step count).
        ds_resume = TokenFileDataset(path, batch, seq,
                                     skip_windows=trained_steps * batch)
        expected_step4 = next(iter(ds_resume))
        np.testing.assert_array_equal(np.asarray(next(pf)), expected_step4)
        # And the discarded-buffer path: a fresh prefetcher over the
        # resumed dataset continues the same stream.
        pf_resume = DevicePrefetch(ds_resume, spec, depth=2)
        ds_check = TokenFileDataset(path, batch, seq,
                                    skip_windows=(trained_steps + 1) * batch)
        np.testing.assert_array_equal(
            np.asarray(next(pf_resume)), next(iter(ds_check))
        )
        for d in (ds, ds_resume, ds_check):
            d.close()
        assert first_run[0].shape == (batch, seq + 1)

    def test_python_and_native_paths_agree_through_prefetch(self, tmp_path):
        """Both loader backends feed identical batches through the device
        stage (the native ring + device buffer compose)."""
        path = self._write_shard(tmp_path)
        mesh = standard_mesh(2, devices=jax.devices()[:2])
        spec = batch_sharding(mesh, with_sp=False)
        ds_py = TokenFileDataset(path, 2, 16, force_python=True)
        ds_any = TokenFileDataset(path, 2, 16)
        pf_py = DevicePrefetch(ds_py, spec, depth=2)
        pf_any = DevicePrefetch(ds_any, spec, depth=2)
        for _ in range(4):
            np.testing.assert_array_equal(
                np.asarray(next(pf_py)), np.asarray(next(pf_any))
            )
        ds_py.close()
        ds_any.close()
