"""Multi-process throughput-parity e2e (tentpole (c), docs/design/
workload_performance.md): a 2-process CPU world formed from the
OPERATOR-INJECTED mesh env (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID / JAX_MESH_SPEC — exactly the variables bootstrap/jaxdist.py
publishes into pods) must reach per-chip step time within the documented
tolerance of a single-process run over the same mesh shape and global
batch. This ties the control-plane story to the hardware-speed north star:
the operator's env injection, the declared-mesh path in runtime/tpu_init,
and the overlapped input pipeline (DevicePrefetch through the multi-process
make_array_from_process_local_data seam) all sit on the measured path.

Tolerance: on CPU/gloo the 2-process run must hold >= 0.2x of the
single-process per-chip throughput (PARITY_MIN_RATIO) — transport dominates
a llama-tiny step on localhost sockets, so the CPU gate is a wiring/decade
check, not a speed promise; the TPU/ICI contract (>= 0.9x) is documented in
the design doc and measured by the live-chip tiers. Marked slow: two cold
JAX process starts; the CI dag runs it in its own step (throughput-parity).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

PARITY_MIN_RATIO = 0.2  # CPU/gloo bound; TPU contract documented at 0.9
STEPS, WARMUP, GLOBAL_BATCH, SEQ = 20, 3, 8, 64


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _base_env(device_count: int) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={device_count}",
        # The declared mesh the operator would publish (JAX_MESH_SPEC).
        "JAX_MESH_SPEC": json.dumps({"fsdp": 2}),
        "JAX_COMPILATION_CACHE_DIR": "/tmp/jax-ci-compile-cache",
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "10",
    })
    # A stray operator env from the harness must not leak in.
    for key in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID", "JAX_NUM_SLICES", "JAX_SLICE_INDEX",
                "TPU_HEARTBEAT_LEASE", "TPU_HEARTBEAT_FILE"):
        env.pop(key, None)
    return env


def _workload_cmd() -> list:
    return [sys.executable, "-m", "tf_operator_tpu.testing.parity_workload",
            "--steps", str(STEPS), "--warmup", str(WARMUP),
            "--global-batch", str(GLOBAL_BATCH), "--seq", str(SEQ)]


def _parse_result(proc: subprocess.CompletedProcess) -> dict:
    assert proc.returncode == 0, (
        f"parity workload rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-2000:]}"
    )
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    return json.loads(lines[-1])


@pytest.mark.slow
class TestThroughputParity:
    def test_two_process_world_holds_per_chip_throughput(self):
        # Single-process reference: 2 local devices, same mesh/global batch.
        single = _parse_result(subprocess.run(
            _workload_cmd(), env=_base_env(2),
            capture_output=True, text=True, timeout=600,
        ))
        assert single["devices"] == 2 and single["num_processes"] == 1

        # 2-process world through the operator env contract: 1 device per
        # process, rendezvous at an injected coordinator address.
        port = _free_port()
        procs = []
        for pid in (0, 1):
            env = _base_env(1)
            env.update({
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": "2",
                "JAX_PROCESS_ID": str(pid),
            })
            procs.append(subprocess.Popen(
                _workload_cmd(), env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            ))
        results = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=600)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            results.append(_parse_result(
                subprocess.CompletedProcess(p.args, p.returncode, out, err)
            ))

        multi = results[0]
        assert multi["devices"] == 2, "rendezvous did not federate devices"
        assert multi["num_processes"] == 2
        # Both processes time the same global steps; their numbers must
        # agree (they block on the same collectives).
        assert results[1]["tokens_per_sec_chip"] == pytest.approx(
            multi["tokens_per_sec_chip"],
            rel=0.5,
        )
        ratio = multi["tokens_per_sec_chip"] / single["tokens_per_sec_chip"]
        print(
            f"[parity] single={single['tokens_per_sec_chip']} tok/s/chip "
            f"({single['step_ms']} ms/step) "
            f"multi={multi['tokens_per_sec_chip']} tok/s/chip "
            f"({multi['step_ms']} ms/step) ratio={ratio:.3f}"
        )
        assert ratio >= PARITY_MIN_RATIO, (
            f"2-process per-chip throughput {multi['tokens_per_sec_chip']} "
            f"is {ratio:.3f}x of single-process "
            f"{single['tokens_per_sec_chip']} — below the documented "
            f"{PARITY_MIN_RATIO}x CPU/gloo tolerance"
        )
