"""Real-framework e2e: genuine TensorFlow and torch.distributed consume the
operator-injected bootstrap contracts in live subprocess pods.

Closes VERDICT r3 missing #1: until this file, the env the operator
injects had only ever been parsed by repo code or stdlib stand-ins. Here
the consumers are the actual frameworks the contracts target —
TFConfigClusterResolver / MultiWorkerMirroredStrategy for TF_CONFIG
(reference test/test-server/test_app.py:31-44 and examples/tensorflow/
dist-mnist/dist_mnist.py:139-143) and torch.distributed's env://
rendezvous for MASTER_ADDR/PORT/RANK/WORLD_SIZE (reference
examples/pytorch/smoke-dist/dist_sendrecv.py).

These tests are the slowest in the e2e tier (a TF import costs ~20 s per
pod); budget accordingly — they earn it by being the only place a real
framework validates the operator's output.
"""

import json
import os
import sys
import time
import urllib.request

import pytest

from tf_operator_tpu.cli import OperatorManager, OperatorOptions
from tf_operator_tpu.cluster.process import LocalProcessCluster
from tf_operator_tpu.metrics import Metrics

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Real frameworks run on CPU; no virtual-device flag needed (TF/torch are
# not jax consumers). PYTHONPATH makes the package importable in children.
CHILD_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO_ROOT,
    "TF_CPP_MIN_LOG_LEVEL": "3",
}

TEST_SERVER_CMD = [sys.executable, "-m", "tf_operator_tpu.testing.test_server"]
MWMS_CMD = [sys.executable, "-m", "tf_operator_tpu.testing.tf_mwms_workload"]
GLOO_CMD = [sys.executable, "-m", "tf_operator_tpu.testing.torch_gloo_workload"]


def wait_for(predicate, timeout=120.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def http_get_json(addr, path, timeout=90.0):
    """GET with retry-until-listening; long default timeout because the
    TF-observed runconfig pays a ~20 s tensorflow import on first hit."""
    url = f"http://{addr[0]}:{addr[1]}{path}"
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=45) as resp:
                return json.loads(resp.read())
        except Exception as exc:  # noqa: BLE001 - conn refused while booting
            last = exc
            time.sleep(0.2)
    raise AssertionError(f"GET {url} never succeeded: {last}")


def job_condition(cluster, kind, name, ctype):
    try:
        job = cluster.get_job(kind, "default", name)
    except KeyError:
        return False
    conds = (job.get("status") or {}).get("conditions") or []
    return any(c["type"] == ctype and c["status"] == "True" for c in conds)


@pytest.fixture
def harness():
    cluster = LocalProcessCluster(child_env=CHILD_ENV)
    manager = OperatorManager(
        cluster,
        OperatorOptions(
            enabled_schemes=["TFJob", "PyTorchJob"],
            health_port=0,
            metrics_port=0,
            resync_period=0.2,
        ),
        metrics=Metrics(),
    )
    manager.start()
    yield cluster
    manager.stop()
    cluster.shutdown()


class TestRealTensorFlowObservesTopology:
    def test_runconfig_is_tf_resolvers_view(self, harness):
        """/runconfig answered by REAL TensorFlow's TFConfigClusterResolver
        (source == "tensorflow"), not by repo code re-parsing TF_CONFIG —
        the reference returned tf.estimator.RunConfig fields the same way
        (test_app.py:31-44). Observed topology must equal the declared one."""
        harness.create_job({
            "apiVersion": "kubeflow.org/v1",
            "kind": "TFJob",
            "metadata": {"name": "tfobs", "namespace": "default"},
            "spec": {"tfReplicaSpecs": {"Worker": {
                "replicas": 2,
                "template": {"spec": {"containers": [{
                    "name": "tensorflow", "image": "local",
                    "command": TEST_SERVER_CMD,
                    "env": [{"name": "TEST_SERVER_RUNCONFIG_TF", "value": "1"}],
                }]}},
            }}},
        })
        assert wait_for(lambda: len(harness.list_pods("default")) == 2)
        for i in range(2):
            addr = harness.resolve(f"tfobs-worker-{i}.default.svc", 2222)
            cfg = http_get_json(addr, "/runconfig")
            assert cfg["source"] == "tensorflow", cfg
            assert cfg["task_type"] == "worker"
            assert cfg["task_id"] == i
            assert len(cfg["cluster_spec"]["worker"]) == 2
            assert not cfg["is_chief"]


class TestRealMultiWorkerMirroredStrategy:
    def test_chief_worker_mwms_trains_to_completion(self, harness):
        """Genuine TF MultiWorkerMirroredStrategy: collectives rendezvous
        over the injected TF_CONFIG addresses, an all-reduce spans both
        tasks, and a synchronized custom loop trains loss downward.

        Chief+worker rather than 2 workers, with distinct declared ports:
        TF's gRPC server binds its port on ALL interfaces (the host part of
        the cluster-spec entry is ignored for binding), so two same-port
        tasks on one machine collide — on a real cluster each pod has its
        own network namespace and the default port is fine."""
        harness.create_job({
            "apiVersion": "kubeflow.org/v1",
            "kind": "TFJob",
            "metadata": {"name": "mwms", "namespace": "default"},
            # Chief exit 0 ends the job; None keeps the worker's log (and
            # its just-about-to-exit process) from being reaped mid-flush.
            "spec": {"runPolicy": {"cleanPodPolicy": "None"},
                     "tfReplicaSpecs": {
                "Chief": {
                    "replicas": 1,
                    "template": {"spec": {"containers": [{
                        "name": "tensorflow", "image": "local",
                        "command": MWMS_CMD,
                    }]}},
                },
                "Worker": {
                    "replicas": 1,
                    "template": {"spec": {"containers": [{
                        "name": "tensorflow", "image": "local",
                        "command": MWMS_CMD,
                        "ports": [{"name": "tfjob-port", "containerPort": 2223}],
                    }]}},
                },
            }},
        })
        assert wait_for(
            lambda: job_condition(harness, "TFJob", "mwms", "Succeeded"),
            timeout=300,
        ), self._logs(harness, "mwms")
        pods = ("mwms-chief-0", "mwms-worker-0")
        # Chief completion ends the job; give the worker (kept by
        # cleanPodPolicy None) a beat to finish its own final steps.
        assert wait_for(
            lambda: "MWMS_OK" in harness.get_pod_log("default", "mwms-worker-0"),
            timeout=60,
        ), self._logs(harness, "mwms")
        for name in pods:
            log = harness.get_pod_log("default", name)
            assert "MWMS_OK" in log, log[-2000:]
            assert "MWMS_REPLICAS 2" in log
            # Collective proof: mean of flat positions 0,1 across the ring.
            assert "MWMS_ALLREDUCE 0.5" in log
        # Synchronized training: both tasks saw the SAME loss trajectory.
        lines = [
            {l.split()[0]: l.split()[1] for l in
             harness.get_pod_log("default", name).splitlines()
             if l.startswith("MWMS_LOSS_")}
            for name in pods
        ]
        assert lines[0] == lines[1], lines
        assert float(lines[0]["MWMS_LOSS_last"]) < float(lines[0]["MWMS_LOSS_first"])

    @staticmethod
    def _logs(cluster, job):
        out = []
        for p in cluster.list_pods("default"):
            if p.metadata.name.startswith(job):
                out.append(f"--- {p.metadata.name} ({p.status.phase})")
                out.append(cluster.get_pod_log("default", p.metadata.name)[-2000:])
        return "\n".join(out)


class TestRealTorchDistributedGloo:
    def test_master_worker_gloo_rendezvous_and_allreduce(self, harness):
        """Genuine torch.distributed env:// rendezvous over the injected
        MASTER_ADDR/PORT/RANK/WORLD_SIZE (bootstrap/c10d.py, reference
        pytorch.go:27-82): one allreduce + one send/recv ring across a
        master + one worker."""
        replica = lambda: {"template": {"spec": {"containers": [{
            "name": "pytorch", "image": "local", "command": GLOO_CMD,
        }]}}}
        harness.create_job({
            "apiVersion": "kubeflow.org/v1",
            "kind": "PyTorchJob",
            "metadata": {"name": "gloo", "namespace": "default"},
            # cleanPodPolicy None: the default (Running) races log
            # collection — the job completes on the master's success and
            # can reap a worker that is still flushing its last lines.
            "spec": {"runPolicy": {"cleanPodPolicy": "None"},
                     "pytorchReplicaSpecs": {
                "Master": {"replicas": 1, **replica()},
                "Worker": {"replicas": 1, **replica()},
            }},
        })
        assert wait_for(
            lambda: job_condition(harness, "PyTorchJob", "gloo", "Succeeded"),
            timeout=240,
        ), TestRealMultiWorkerMirroredStrategy._logs(harness, "gloo")
        master_log = harness.get_pod_log("default", "gloo-master-0")
        worker_log = harness.get_pod_log("default", "gloo-worker-0")
        for log, rank in ((master_log, 0), (worker_log, 1)):
            assert "GLOO_OK" in log, log[-2000:]
            env = json.loads(
                [l for l in log.splitlines() if l.startswith("GLOO_ENV ")][0]
                .split(" ", 1)[1]
            )
            assert env["RANK"] == str(rank)
            assert env["WORLD_SIZE"] == "2"
            # world*(world+1)/2 with world=2
            assert "GLOO_ALLREDUCE 3.0" in log
        assert json.loads(
            [l for l in master_log.splitlines() if l.startswith("GLOO_ENV ")][0]
            .split(" ", 1)[1]
        )["MASTER_ADDR"] == "localhost"


class TestRealTorchSendRecv:
    def test_master_two_workers_pairwise_sendrecv(self, harness):
        """The smoke-dist example (re-design of reference
        examples/pytorch/smoke-dist/dist_sendrecv.py) under real
        torch.distributed: every master<->worker pair exchanges tensors
        point-to-point over the injected c10d env, so one broken address
        mapping is attributable to a specific peer."""
        cmd = [sys.executable, os.path.join(
            REPO_ROOT, "examples", "pytorch", "smoke-dist", "dist_sendrecv.py")]
        replica = lambda n: {"replicas": n, "template": {"spec": {
            "containers": [{"name": "pytorch", "image": "local",
                            "command": cmd}]}}}
        harness.create_job({
            "apiVersion": "kubeflow.org/v1",
            "kind": "PyTorchJob",
            "metadata": {"name": "sendrecv", "namespace": "default"},
            "spec": {"runPolicy": {"cleanPodPolicy": "None"},
                     "pytorchReplicaSpecs": {
                         "Master": replica(1), "Worker": replica(2)}},
        })
        assert wait_for(
            lambda: job_condition(harness, "PyTorchJob", "sendrecv",
                                  "Succeeded"),
            timeout=240,
        ), TestRealMultiWorkerMirroredStrategy._logs(harness, "sendrecv")
        master_log = harness.get_pod_log("default", "sendrecv-master-0")
        assert "SENDRECV_OK peer=1" in master_log, master_log[-2000:]
        assert "SENDRECV_OK peer=2" in master_log, master_log[-2000:]
        for i in range(2):
            worker_log = harness.get_pod_log("default", f"sendrecv-worker-{i}")
            assert "SENDRECV_OK worker" in worker_log, worker_log[-2000:]


class TestRealTrainAndEvaluate:
    def test_chief_worker_evaluator_topology(self, harness, tmp_path):
        """The estimator-API re-design under real TensorFlow: chief+worker
        train under MultiWorkerMirroredStrategy while a genuine `evaluator`
        task (excluded from the collective world by TF itself) evaluates
        each published weights file and exits on the chief's DONE marker —
        train_and_evaluate semantics without the removed estimator API."""
        model_dir = str(tmp_path / "model")
        cmd = [sys.executable,
               os.path.join(REPO_ROOT, "examples", "tensorflow",
                            "distribution_strategy",
                            "keras_train_and_evaluate.py"),
               "--model-dir", model_dir, "--epochs", "2",
               "--steps-per-epoch", "5", "--evaluator-timeout", "180"]
        # Distinct declared ports per trainer task: TF's collective gRPC
        # server binds on ALL interfaces, so same-port tasks on one test
        # machine collide (see the MWMS test above). The evaluator starts
        # no collective server.
        def replica(port=None):
            c = {"name": "tensorflow", "image": "local", "command": cmd}
            if port:
                c["ports"] = [{"name": "tfjob-port", "containerPort": port}]
            return {"replicas": 1, "template": {"spec": {"containers": [c]}}}

        harness.create_job({
            "apiVersion": "kubeflow.org/v1",
            "kind": "TFJob",
            "metadata": {"name": "tae", "namespace": "default"},
            "spec": {"runPolicy": {"cleanPodPolicy": "None"},
                     "tfReplicaSpecs": {"Chief": replica(),
                                        "Worker": replica(2223),
                                        "Evaluator": replica(2224)}},
        })
        assert wait_for(
            lambda: job_condition(harness, "TFJob", "tae", "Succeeded"),
            timeout=300,
        ), TestRealMultiWorkerMirroredStrategy._logs(harness, "tae")

        def evaluator_done():
            try:
                return "EVAL_DONE" in harness.get_pod_log(
                    "default", "tae-evaluator-0")
            except KeyError:
                return False

        assert wait_for(evaluator_done, timeout=120), harness.get_pod_log(
            "default", "tae-evaluator-0")[-2000:]
        eval_log = harness.get_pod_log("default", "tae-evaluator-0")
        assert "EVAL file=epoch-0000.weights.h5" in eval_log, eval_log[-2000:]
        done = [l for l in eval_log.splitlines() if l.startswith("EVAL_DONE")]
        assert int(done[0].split("count=")[1]) >= 2  # one eval per epoch
        chief_log = harness.get_pod_log("default", "tae-chief-0")
        assert "replicas_in_sync=2" in chief_log, chief_log[-2000:]


class TestRealTFSmoke:
    def test_chief_places_ops_on_every_task(self, harness):
        """The tf_smoke re-design under real TensorFlow: the chief connects
        to the whole cluster and runs a matmul pinned to EACH task's device
        (chief/worker/ps), verifying every address in the injected
        TF_CONFIG actually computes — placement breadth a collective ring
        can't attribute. One replica per type because each type declares
        its own port and tf.distribute.Server binds it on all interfaces
        (same one-machine constraint as the MWMS test)."""
        cmd = [sys.executable, os.path.join(
            REPO_ROOT, "examples", "tensorflow", "tf_smoke", "tf_smoke.py")]

        def replica(port=None):
            c = {"name": "tensorflow", "image": "local", "command": cmd}
            if port:
                c["ports"] = [{"name": "tfjob-port", "containerPort": port}]
            return {"replicas": 1, "template": {"spec": {"containers": [c]}}}

        harness.create_job({
            "apiVersion": "kubeflow.org/v1",
            "kind": "TFJob",
            "metadata": {"name": "smoke", "namespace": "default"},
            "spec": {"runPolicy": {"cleanPodPolicy": "Running"},
                     "tfReplicaSpecs": {"Chief": replica(),
                                        "Worker": replica(2223),
                                        "PS": replica(2224)}},
        })
        assert wait_for(
            lambda: job_condition(harness, "TFJob", "smoke", "Succeeded"),
            timeout=240,
        ), TestRealMultiWorkerMirroredStrategy._logs(harness, "smoke")
        chief_log = harness.get_pod_log("default", "smoke-chief-0")
        for device in ("/job:chief/task:0", "/job:worker/task:0",
                       "/job:ps/task:0"):
            assert f"SMOKE_OK {device}" in chief_log, chief_log[-2000:]
        assert "SMOKE_DONE tasks=3" in chief_log
