"""Benchmark harness: Llama training throughput on the available hardware.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

The reference publishes no performance numbers (BASELINE.md: the operator is
a control plane). The north-star workload metric is Llama training MFU
(target >= 45% on v5e); this harness measures tokens/sec/chip and MFU for a
model sized to the present chip count, so vs_baseline is MFU/0.45.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


# Per-chip peak bf16 TFLOP/s (for MFU accounting).
PEAK_TFLOPS = {
    "tpu v5 lite": 197.0,  # v5e
    "tpu v5e": 197.0,
    "tpu v5": 459.0,  # v5p
    "tpu v4": 275.0,
    "tpu v6 lite": 918.0,  # v6e (trillium)
    "cpu": 1.0,
}


def peak_tflops_for(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_TFLOPS.items():
        if kind.startswith(key):
            return val
    return 197.0 if device.platform == "tpu" else 1.0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default=None, help="config name from models.llama.CONFIGS")
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--seq", type=int, default=2048)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=3)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models import llama
    from tf_operator_tpu.parallel.mesh import standard_mesh
    from tf_operator_tpu.train.data import SyntheticTokens
    from tf_operator_tpu.train.train_step import (
        init_sharded_train_state,
        make_optimizer,
        make_train_step,
    )
    from tf_operator_tpu.parallel.sharding import batch_sharding

    devices = jax.devices()
    n = len(devices)
    on_tpu = devices[0].platform == "tpu"

    # Size the model to the hardware: single chip -> 400M-class; pods -> 7B.
    if args.model is None:
        args.model = "llama2-7b" if (on_tpu and n >= 16) else ("llama-400m" if on_tpu else "llama-tiny")
    config = llama.CONFIGS[args.model]
    if args.seq and args.seq != config.max_seq_len:
        config = type(config)(**{**config.__dict__, "max_seq_len": args.seq})
    seq = min(args.seq, config.max_seq_len)
    if args.batch is None:
        args.batch = max(n, 8) if on_tpu else 2
    if not on_tpu:
        seq = min(seq, 128)
        args.steps = min(args.steps, 3)

    mesh = standard_mesh(n)  # pure FSDP by default; tp via env later
    model = llama.Llama(config)
    optimizer = make_optimizer(warmup_steps=10, decay_steps=1000)
    # Born-sharded init: a 7B state never exists unsharded on one chip.
    state, sharding = init_sharded_train_state(
        model, jax.random.PRNGKey(0), optimizer, mesh, batch=1, seq=min(seq, 128)
    )
    step_fn, _ = make_train_step(model, optimizer, mesh, state, sharding=sharding)

    data = SyntheticTokens(args.batch, seq, config.vocab_size)
    data_sharding = batch_sharding(mesh, with_sp=False)
    it = iter(data)

    # Warmup (compile). Synchronize via an actual host fetch of the loss:
    # on remote-relay PJRT backends block_until_ready can return before the
    # queued executions run, wildly under-reporting step time — a device->
    # host value transfer is the only reliable barrier.
    for _ in range(max(args.warmup, 1)):  # >=1: compile must stay out of the timed region
        state, loss = step_fn(state, jax.device_put(next(it), data_sharding))
    float(loss)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, loss = step_fn(state, jax.device_put(next(it), data_sharding))
    final_loss = float(loss)  # barrier: forces the whole chain
    dt = time.perf_counter() - t0

    tokens_per_step = args.batch * seq
    tokens_per_sec = tokens_per_step * args.steps / dt
    tokens_per_sec_chip = tokens_per_sec / n

    achieved_tflops_chip = tokens_per_sec_chip * config.flops_per_token(seq) / 1e12
    mfu = achieved_tflops_chip / peak_tflops_for(devices[0])

    result = {
        "metric": f"llama[{args.model}] train tokens/sec/chip (seq={seq}, bs={args.batch}, {n}x {devices[0].device_kind})",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "tokens_per_sec_total": round(tokens_per_sec, 1),
            "achieved_tflops_per_chip": round(achieved_tflops_chip, 2),
            "loss": round(final_loss, 4),
            "params": config.param_count(),
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
