"""Benchmark harness: training throughput on the available hardware.

Stdout contract: the LAST line is the result JSON —
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, "extra": {...}}
A full-suite run prints the headline-only line EARLY (extra.configs =
{"status": "secondaries running"}) and the complete line at the end, so a
capture killed mid-secondary still ends on a valid measurement. Consumers
must parse the last line (the driver and ci/check_bench_7b.py do).

The headline metric is the Llama-400M training MFU on the present chip
(north star >= 45% — BASELINE.md; the reference publishes no numbers, it is
a control plane). `extra.configs` carries the secondary suite so the bench
is not a single-config story: the MoE (expert) path, BERT, and a run fed by
the native C++ token loader (proving the input pipeline does not eat MFU).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


# Per-chip peak bf16 TFLOP/s (for MFU accounting).
PEAK_TFLOPS = {
    "tpu v5 lite": 197.0,  # v5e
    "tpu v5e": 197.0,
    "tpu v5": 459.0,  # v5p
    "tpu v4": 275.0,
    "tpu v6 lite": 918.0,  # v6e (trillium)
    "cpu": 1.0,
}


def peak_tflops_for(device):
    """(peak bf16 TFLOP/s, assumed-chip name or None).

    Unknown device kinds score against an ASSUMED chip (v5e for TPUs, the
    1.0 cpu token otherwise) — returned as the second element and stamped
    into the per-config extra by the callers, with a stderr warning, so an
    MFU computed on new hardware is never silently wrong-looking-right."""
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_TFLOPS.items():
        if kind.startswith(key):
            return val, None
    assumed = "tpu v5 lite" if device.platform == "tpu" else "cpu"
    print(
        f"bench: WARNING unknown device kind {kind!r} "
        f"(platform={device.platform}) — MFU scored against assumed "
        f"{assumed!r} peak {PEAK_TFLOPS[assumed]} TFLOP/s; add the chip to "
        "PEAK_TFLOPS for a real number",
        file=sys.stderr,
    )
    return PEAK_TFLOPS[assumed], assumed


def _device_batches(host_iter, data_sharding):
    """Host batches -> device arrays, overlapped by default: a
    DevicePrefetch double buffer issues batch k+1's transfer while step k
    runs, so the one host->device copy per step (a network round trip on
    remote-relay PJRT backends) leaves the critical path.
    TF_OPERATOR_BENCH_OVERLAP=0 restores the in-line device_put (the
    overlap-off A/B lever; loss sequences are byte-identical either way —
    tests/test_train_pipeline.py)."""
    import jax

    if os.environ.get("TF_OPERATOR_BENCH_OVERLAP", "1") != "0":
        from tf_operator_tpu.train.data import DevicePrefetch

        return DevicePrefetch(host_iter, data_sharding, depth=2)
    it = iter(host_iter)
    return (jax.device_put(next(it), data_sharding) for _ in iter(int, 1))


def _timed_steps(step_fn, state, batches, steps):
    """Run `steps` steps; device->host loss fetch is the barrier (on
    remote-relay PJRT backends block_until_ready can return early)."""
    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step_fn(state, next(batches))
    final_loss = float(loss)
    return time.perf_counter() - t0, final_loss, state


def bench_llama(config_name, batch, seq, steps, warmup, mesh, devices,
                loader_path=None):
    import jax

    from tf_operator_tpu.models import llama
    from tf_operator_tpu.parallel.sharding import batch_sharding
    from tf_operator_tpu.train.data import SyntheticTokens, TokenFileDataset
    from tf_operator_tpu.train.train_step import (
        init_sharded_train_state,
        make_optimizer,
        make_train_step,
    )

    config = llama.CONFIGS[config_name]
    if seq != config.max_seq_len:
        config = type(config)(**{**config.__dict__, "max_seq_len": seq})
    # CI-only shrink: exercise a big config's bench code path (selection,
    # sharded init, loader plumbing, timing loop) on hardware that cannot
    # hold the full model — layer count drops, per-layer geometry stays.
    # Never set in a real measurement run; the emitted config name would
    # otherwise overstate the model.
    layers_env = os.environ.get("TF_OPERATOR_BENCH_LAYERS")
    if layers_env:
        config = type(config)(**{**config.__dict__, "n_layers": int(layers_env)})
    # Remat sweep knob: override the config's measured default policy
    # (models/llama.py REMAT_SAVEABLE vocabulary) without a code edit;
    # recorded in the per-config extra so a sweep's JSON is self-describing.
    remat_env = os.environ.get("TF_OPERATOR_REMAT_POLICY")
    if remat_env:
        config = type(config)(**{**config.__dict__, "remat_policy": remat_env})
    model = llama.Llama(config)
    optimizer = make_optimizer(warmup_steps=10, decay_steps=1000)
    # Born-sharded init: a 7B state never exists unsharded on one chip.
    state, sharding = init_sharded_train_state(
        model, jax.random.PRNGKey(0), optimizer, mesh, batch=1, seq=min(seq, 128)
    )
    # Batch donated: with the prefetch stage each batch is a fresh device
    # buffer, so the step recycles the previous one's HBM in place.
    step_fn, _ = make_train_step(
        model, optimizer, mesh, state, sharding=sharding, donate_batch=True
    )

    data_sharding = batch_sharding(mesh, with_sp=False)
    if loader_path is not None:
        data = TokenFileDataset(loader_path, batch, seq, dtype="int32")
        native = data.native
    else:
        data = SyntheticTokens(batch, seq, config.vocab_size)
        native = None

    batches = _device_batches(data, data_sharding)
    for _ in range(max(warmup, 1)):
        state, loss = step_fn(state, next(batches))
    float(loss)
    dt, final_loss, _ = _timed_steps(step_fn, state, batches, steps)

    n = len(devices)
    tokens_per_sec = batch * seq * steps / dt
    achieved = tokens_per_sec / n * config.flops_per_token(seq) / 1e12
    peak, assumed_chip = peak_tflops_for(devices[0])
    mfu = achieved / peak
    out = {
        "tokens_per_sec_chip": round(tokens_per_sec / n, 1),
        "mfu": round(mfu, 4),
        "achieved_tflops_per_chip": round(achieved, 2),
        "loss": round(final_loss, 4),
        "params": config.param_count(),
        "seq": seq,
        "batch": batch,
    }
    if assumed_chip is not None:
        out["assumed_chip"] = assumed_chip
    if remat_env:
        out["remat_policy"] = remat_env
    if native is not None:
        out["native_loader"] = bool(native)
    return out


def bench_bert(config_name, batch, seq, steps, warmup, mesh, devices):
    """Masked-LM-style training step on the BERT encoder (synthetic ids):
    forward + CE over all positions + backward + adamw, jitted over the
    mesh like the Llama path."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models import bert
    from tf_operator_tpu.parallel.sharding import batch_sharding
    from tf_operator_tpu.train.train_step import (
        TrainState,
        make_optimizer,
        make_train_step_for,
    )

    config = bert.CONFIGS[config_name]
    model = bert.Bert(config)
    optimizer = make_optimizer(warmup_steps=10, decay_steps=1000)
    params = {"params": bert.init_params(
        model, jax.random.PRNGKey(0), batch=1, seq=min(seq, 128)
    )}
    state = TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=optimizer.init(params),
    )

    def loss_fn(params, batch_ids):
        # MLM-shaped throughput loss via the SHARED chunked-head path
        # (train_step.loss_fn → head_kernel_and_bias → chunked CE): the
        # [b, s, 30k] fp32 logits tensor never exists whole in HBM
        # (~0.5 GB at bs 8 — materializing it plus its log_softmax was
        # measured costing bert-base several MFU points of pure
        # bandwidth).
        from tf_operator_tpu.train.train_step import loss_fn as shared_loss

        return shared_loss(model, params, batch_ids)

    step_fn, sharding = make_train_step_for(
        loss_fn, optimizer, mesh, state, donate_batch=True
    )
    state = jax.tree.map(jax.device_put, state, sharding)

    import numpy as np

    rng_np = np.random.default_rng(0)
    data_sharding = batch_sharding(mesh, with_sp=False)

    def host_batches():
        while True:
            yield rng_np.integers(0, config.vocab_size, size=(batch, seq + 1),
                                  dtype=np.int32)

    it = _device_batches(host_batches(), data_sharding)
    for _ in range(max(warmup, 1)):
        state, loss = step_fn(state, next(it))
    float(loss)
    dt, final_loss, _ = _timed_steps(step_fn, state, it, steps)

    n = len(devices)
    tokens_per_sec = batch * seq * steps / dt
    achieved = tokens_per_sec / n * config.flops_per_token(seq) / 1e12
    peak, assumed_chip = peak_tflops_for(devices[0])
    mfu = achieved / peak
    out = {
        "tokens_per_sec_chip": round(tokens_per_sec / n, 1),
        "mfu": round(mfu, 4),
        "achieved_tflops_per_chip": round(achieved, 2),
        "loss": round(final_loss, 4),
        "params": config.param_count(),
        "seq": seq,
        "batch": batch,
    }
    if assumed_chip is not None:
        out["assumed_chip"] = assumed_chip
    return out


def _check_floors(floors_path: str, model: str, headline: dict,
                  configs: dict, device) -> int:
    """Compare EVERY measured config (headline under its model name, plus
    each extra.configs entry) against the committed per-platform floor
    table (ci/bench_floors.json). Returns 0 on pass, 3 on any violation —
    a secondary config regressing (or silently vanishing from the suite,
    or erroring) fails CI, not just the headline.

    Floor keys are device-kind prefixes (same matching as peak_tflops_for),
    longest first; an unlisted platform passes report-only so new hardware
    is never red on day one."""
    try:
        with open(floors_path) as fh:
            floors = json.load(fh)
    except (OSError, ValueError) as exc:
        print(f"bench --check: cannot read floors {floors_path}: {exc}",
              file=sys.stderr)
        return 3
    kind = getattr(device, "device_kind", "cpu").lower()
    table = None
    for key in sorted((k for k in floors if not k.startswith("_")),
                      key=len, reverse=True):
        if kind.startswith(key):
            table = floors[key]
            break
    if table is None:
        print(f"bench --check: no floor table for device kind {kind!r} — "
              "report-only pass", file=sys.stderr)
        return 0
    measured = {model: headline, **configs}
    failures = []
    for name, floor in table.items():
        entry = measured.get(name)
        if entry is None:
            failures.append(f"{name}: floored config missing from results")
        elif "error" in entry:
            failures.append(f"{name}: errored: {entry['error']}")
        elif entry.get("mfu", 0.0) < floor:
            failures.append(
                f"{name}: mfu {entry.get('mfu')} < floor {floor}"
            )
    for name, entry in measured.items():
        if name not in table and isinstance(entry, dict) and "error" in entry:
            failures.append(f"{name}: errored (unfloored): {entry['error']}")
    if failures:
        for f in failures:
            print(f"bench --check FAIL: {f}", file=sys.stderr)
        return 3
    print(
        f"bench --check OK: {len(table)} floors held on {kind!r} "
        f"({len(measured)} configs measured)",
        file=sys.stderr,
    )
    return 0


def _emit_error(stage: str, exc: BaseException, extra: dict | None = None) -> None:
    """The driver parses our last stdout line as JSON; a traceback instead
    of a line erased all of round 2's perf evidence (BENCH_r02 rc=1,
    parsed=null). Whatever fails, the line gets printed."""
    print(json.dumps({
        "metric": "bench-error",
        "value": 0,
        "unit": "error",
        "vs_baseline": 0,
        "extra": {
            "stage": stage,
            "error": f"{type(exc).__name__}: {exc}"[:500],
            **(extra or {}),
        },
    }))


def _wait_for_backend(window: float, probe_timeout: float = 120.0,
                      interval: float = 60.0, require_tpu: bool = True) -> list:
    """Probe backend init in a FRESH subprocess every ~`interval` s until one
    succeeds or `window` closes; returns the attempt log (last entry
    ``ok=True`` on success).

    A fresh process per probe is the only reliable reset for both observed
    tunnel failure modes: a *hang* wedges the probing process inside PJRT
    client creation forever (a thread in this process would pin the backend
    cache in a poisoned state), and a raised UNAVAILABLE is cached by jax
    in-process. Round 2 and round 3 both lost their capture to a tunnel
    outage that a single-shot init couldn't outlast; a tunnel that recovers
    mid-window now still yields a measurement.

    `require_tpu`: a probe that "succeeds" by silently falling back to the
    CPU backend (jax does this when the TPU plugin raises UNAVAILABLE) is
    NOT success — benching llama-tiny on CPU and emitting a plausible
    headline would be worse than an honest error line."""
    import subprocess

    attempts = []
    start = time.monotonic()
    deadline = start + window
    while True:
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices(); print(len(d), d[0].platform)"],
                capture_output=True, text=True, timeout=probe_timeout,
            )
            ok = proc.returncode == 0
            tail = (proc.stdout if ok else proc.stderr).strip()
            detail = tail.splitlines()[-1][:200] if tail else f"rc={proc.returncode}"
            if ok and require_tpu and detail.endswith(" cpu"):
                ok = False
                detail = f"cpu fallback (tpu backend unavailable): {detail}"
        except subprocess.TimeoutExpired:
            ok = False
            detail = f"hang: probe subprocess killed after {probe_timeout:.0f}s"
        attempts.append({
            "at_s": round(t0 - start, 1),
            "took_s": round(time.monotonic() - t0, 1),
            "ok": ok,
            "detail": detail,
        })
        if ok or time.monotonic() >= deadline:
            return attempts
        if not ok and any(
            marker in detail
            for marker in ("ModuleNotFoundError", "ImportError", "SyntaxError")
        ):
            # Deterministic environment breakage, not a tunnel outage:
            # every retry would fail identically — emit the error line now
            # rather than after the full wait window.
            return attempts
        remaining = deadline - time.monotonic()
        print(
            f"bench: backend unavailable ({detail}); retrying, "
            f"{remaining:.0f}s left in wait window",
            file=sys.stderr,
        )
        sys.stderr.flush()
        time.sleep(max(0.0, min(interval - (time.monotonic() - t0), remaining)))


class _BackendInitHang(RuntimeError):
    """Backend init blocked past the deadline inside a C call (observed: the
    TPU tunnel can *hang* rather than raise UNAVAILABLE). The probe thread
    cannot be interrupted; the caller must os._exit after reporting."""


def _init_devices(total_timeout: float = 180.0):
    """jax.devices() with retry/backoff in a watchdog thread.

    Two observed failure modes of the remote TPU backend at capture time:
    raising UNAVAILABLE (round 2 — jax then caches the *failure*, so each
    retry clears the backend cache first), and hanging indefinitely inside
    PJRT client creation (no exception ever surfaces). The probe runs in a
    daemon thread so the second mode still yields a parseable error line.
    """
    import threading

    import jax

    result: dict = {}

    def probe_loop() -> None:
        deadline = time.monotonic() + total_timeout
        delay = 5.0
        while True:
            try:
                result["devices"] = jax.devices()
                return
            except Exception as exc:  # noqa: BLE001 — UNAVAILABLE etc.
                result["exc"] = exc
                if time.monotonic() >= deadline:
                    return
            try:
                from jax.extend import backend as _jax_backend

                _jax_backend.clear_backends()
            except Exception:  # noqa: BLE001 — best effort; private fallback
                try:
                    jax._src.xla_bridge._clear_backends()
                except Exception:
                    pass
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 1.6, 30.0)

    thread = threading.Thread(target=probe_loop, daemon=True, name="bench-init")
    thread.start()
    thread.join(total_timeout + 30.0)
    if "devices" in result:
        return result["devices"]
    if thread.is_alive():
        raise _BackendInitHang(
            f"backend init still blocked after {total_timeout + 30.0:.0f}s"
        )
    raise result.get("exc") or RuntimeError("backend init failed")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default=None, help="headline config (models.llama.CONFIGS)")
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--seq", type=int, default=2048)
    # Default steps resolve per-platform below (TPU: 100 — on a
    # remote-relay backend short runs under-measure: llama-400m reads
    # 64.6% MFU at 20 steps vs 65.4% at 100, pure dispatch-amortization
    # artifact; CPU smoke: 3).
    parser.add_argument("--steps", type=int, default=None)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--suite", choices=("full", "headline"), default=None,
                        help="full = headline + moe/bert/loader secondaries (TPU default)")
    parser.add_argument("--check", action="store_true",
                        help="compare every measured config against the "
                             "committed floor table; exit 3 on regression")
    parser.add_argument("--floors",
                        default=os.path.join(os.path.dirname(
                            os.path.abspath(__file__)), "ci", "bench_floors.json"),
                        help="floor table for --check (ci/bench_floors.json)")
    args = parser.parse_args()

    import jax

    # Honor JAX_PLATFORMS=cpu even on images whose sitecustomize pins the
    # TPU plugin (same guard as __graft_entry__.dryrun_multichip) — also the
    # escape hatch when the chip/tunnel is down.
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from tf_operator_tpu.parallel.mesh import standard_mesh

    def _envf(name: str, default: float) -> float:
        try:
            return float(os.environ.get(name, str(default)))
        except ValueError:
            return default

    init_timeout = _envf("TF_OPERATOR_BENCH_INIT_TIMEOUT", 180.0)
    # Bounded wait-for-backend (VERDICT r3 #1): the happy path pays NOTHING
    # extra — _init_devices runs directly. Only when init fails or hangs
    # does bench re-exec itself into a clean process (a hang wedges a
    # thread inside PJRT client creation, poisoning this process's backend
    # state forever — exec is the only real reset) where fresh-subprocess
    # probes every ~60 s cover the rest of a shared deadline, so a tunnel
    # that recovers mid-window still yields a measurement.
    expect_tpu = os.environ.get("JAX_PLATFORMS", "").lower() != "cpu"
    wait_window = _envf("TF_OPERATOR_BENCH_WAIT", 1800.0)
    attempt = int(os.environ.get("TF_OPERATOR_BENCH_ATTEMPT", "0"))
    if expect_tpu and wait_window > 0 and attempt > 0:
        # Re-exec after a failed/hung init: wait for a TPU-positive probe
        # before touching jax in this process. The deadline is shared
        # across re-execs (set below on first failure) so flapping cannot
        # extend the total window.
        deadline = _envf("TF_OPERATOR_BENCH_DEADLINE", 0.0)
        remaining = deadline - time.time() if deadline else wait_window
        if remaining <= 0:
            remaining = 60.0  # one last short probe pass
        probe_log = _wait_for_backend(
            remaining, _envf("TF_OPERATOR_BENCH_PROBE_TIMEOUT", 120.0)
        )
        if not probe_log[-1]["ok"]:
            _emit_error(
                "backend-wait",
                RuntimeError(
                    f"backend never became available across "
                    f"{len(probe_log)} probes in {remaining:.0f}s "
                    f"(attempt {attempt})"
                ),
                extra={
                    "attempts": len(probe_log),
                    "window_s": round(remaining, 1),
                    "probe_log": probe_log[-20:],
                },
            )
            return 1
    try:
        devices = _init_devices(init_timeout)
        if expect_tpu and devices and devices[0].platform == "cpu":
            # Silent CPU fallback after an UNAVAILABLE from the TPU plugin:
            # a llama-tiny CPU number with a plausible-looking headline
            # would be worse than an honest retry/error.
            raise RuntimeError("backend fell back to cpu; tpu unavailable")
        init_failed = None
    except Exception as exc:  # noqa: BLE001 — incl. _BackendInitHang
        init_failed = exc
    if init_failed is not None:
        if expect_tpu and wait_window > 0 and attempt < 3:
            os.environ["TF_OPERATOR_BENCH_ATTEMPT"] = str(attempt + 1)
            os.environ.setdefault(
                "TF_OPERATOR_BENCH_DEADLINE", str(time.time() + wait_window)
            )
            print(
                f"bench: backend init failed ({type(init_failed).__name__}: "
                f"{init_failed}); re-exec attempt {attempt + 1}",
                file=sys.stderr,
            )
            sys.stderr.flush()
            sys.stdout.flush()
            os.execv(sys.executable, [sys.executable] + sys.argv)
        _emit_error("backend-init", init_failed)
        if isinstance(init_failed, _BackendInitHang):
            sys.stdout.flush()
            os._exit(1)  # a thread is wedged in PJRT init; exit can hang
        return 1
    try:
        n = len(devices)
        on_tpu = devices[0].platform == "tpu"

        # Size the model to the hardware: single chip -> 400M-class; pods -> 7B.
        if args.model is None:
            args.model = "llama2-7b" if (on_tpu and n >= 16) else ("llama-400m" if on_tpu else "llama-tiny")
        seq = args.seq
        if args.batch is None:
            # Off-TPU too, the batch must cover the mesh's data extent —
            # a bare CPU smoke with 8 virtual devices can't device_put a
            # batch of 2 over an fsdp=8 mesh.
            args.batch = max(n, 8) if on_tpu else max(2, n)
        if args.steps is None:
            args.steps = 100 if on_tpu else 3
        if not on_tpu:
            # Short sequences only off-TPU; an explicit --steps is honored
            # (e.g. studying the dispatch-amortization artifact on CPU).
            seq = min(seq, 128)
        suite = args.suite or ("full" if on_tpu else "headline")
        if args.check and suite != "full":
            if args.suite == "headline":
                # Explicit contradiction: the floor tables cover the whole
                # suite, so a headline-only check would report every
                # secondary as missing — refuse loudly rather than fail
                # confusingly.
                print("bench --check requires the full suite; drop "
                      "--suite headline", file=sys.stderr)
                return 2
            suite = "full"  # --check implies the full suite off-TPU too

        mesh = standard_mesh(n)  # pure FSDP by default
    except Exception as exc:  # noqa: BLE001 — empty device list, mesh factory
        _emit_error("setup", exc)
        return 1

    try:
        headline = bench_llama(
            args.model, args.batch, seq, args.steps, args.warmup, mesh, devices
        )
    except Exception as exc:  # noqa: BLE001
        _emit_error(f"headline[{args.model}]", exc)
        return 1

    def result_line(configs_so_far):
        mfu = headline["mfu"]
        return {
            "metric": f"llama[{args.model}] train tokens/sec/chip (seq={seq}, bs={args.batch}, {n}x {devices[0].device_kind})",
            "value": headline["tokens_per_sec_chip"],
            "unit": "tokens/sec/chip",
            "vs_baseline": round(mfu / 0.45, 4),
            "extra": {
                "mfu": mfu,
                "tokens_per_sec_total": round(headline["tokens_per_sec_chip"] * n, 1),
                "achieved_tflops_per_chip": headline["achieved_tflops_per_chip"],
                "loss": headline["loss"],
                "params": headline["params"],
                "configs": configs_so_far,
            },
        }

    if suite == "full":
        # Emit the headline IMMEDIATELY: if the capture is killed
        # mid-secondary (driver timeout, infra flake), the last stdout line
        # is still a valid measurement rather than nothing. The complete
        # line replaces it at the end.
        print(json.dumps(result_line({"status": "secondaries running"})))
        sys.stdout.flush()

    configs = {}
    if suite == "full":
        sub_steps = max(6, args.steps // 2)

        def secondary(name, fn):
            # A failing secondary must never cost the headline JSON line
            # (the driver parses it): record the error and move on.
            try:
                configs[name] = fn()
            except Exception as exc:  # noqa: BLE001
                configs[name] = {"error": f"{type(exc).__name__}: {exc}"[:200]}

        def loader_run():
            # Native-loader-fed run: identical config, tokens streamed from
            # a real shard file via the C++ loader — must be within ~1% of
            # the synthetic headline or the input pipeline is eating MFU.
            import numpy as np

            from tf_operator_tpu.train.data import write_token_file

            from tf_operator_tpu.models import llama as llama_models

            vocab = llama_models.CONFIGS[args.model].vocab_size
            with tempfile.TemporaryDirectory() as td:
                shard = os.path.join(td, "tokens.bin")
                need = (args.batch * (seq + 1)) * 64 + 1024
                write_token_file(
                    shard,
                    np.random.default_rng(7).integers(0, vocab, size=need,
                                                      dtype=np.int32),
                )
                return bench_llama(
                    args.model, args.batch, seq, sub_steps, args.warmup, mesh,
                    devices, loader_path=shard,
                )

        secondary(f"{args.model}+native-loader", loader_run)
        # Off-TPU (CPU smoke), the 125M-class secondaries take tens of
        # minutes — use the tiny stand-ins that exercise the same code paths.
        moe_name = "moe-125m" if on_tpu else "moe-tiny"
        secondary(moe_name, lambda: bench_llama(
            moe_name, args.batch, min(seq, 2048), sub_steps, args.warmup,
            mesh, devices,
        ))
        bert_name = "bert-base" if on_tpu else "bert-tiny"
        # bs 16 for bert on TPU (not the llama headline's bs): seq 512
        # gives the flash kernel a small grid per sequence; the larger
        # batch keeps the MXU fed (+0.5 MFU over bs 8, round-5 sweep).
        # And MORE steps than the other secondaries: a bert step is ~60 ms
        # — at 10 steps the per-dispatch latency of a remote-relay backend
        # eats 4-6 MFU points of pure measurement artifact (41% at 10
        # steps vs 47.5% at 40 on the same config).
        bert_batch = 16 if on_tpu else args.batch
        bert_steps = max(sub_steps, 40) if on_tpu else sub_steps
        secondary(bert_name, lambda: bench_bert(
            bert_name, bert_batch, min(seq, 512), bert_steps, args.warmup,
            mesh, devices,
        ))
        if on_tpu and n == 1 and args.model != "llama-1b":
            # ~1B dense anchor for the 7B tokens/sec extrapolation
            # (BASELINE.md): head_dim 128, bs 4 is the single-v5e HBM limit.
            secondary("llama-1b", lambda: bench_llama(
                "llama-1b", 4, seq, sub_steps, args.warmup, mesh, devices,
            ))

    print(json.dumps(result_line(configs)))
    if args.check:
        # After the result line (the stdout contract keeps the last line a
        # valid measurement either way); violations go to stderr, rc=3.
        return _check_floors(args.floors, args.model, headline, configs,
                             devices[0])
    return 0


if __name__ == "__main__":
    sys.exit(main())
