"""BERT-base MLM training over PJRT/XLA on TPU (BASELINE config #3).

Runs inside the PyTorchJob of pytorchjob_bert_pjrt_v5e16.yaml: each host
pod gets PJRT_DEVICE=TPU + libtpu identity from the operator, so torch_xla
brings up the slice with no torchrun and no cloud metadata. Off-TPU (smoke
runs, CI) it falls back to plain torch.distributed gloo over the injected
c10d env — the same model step, CPU tensors.

The GPU-era ancestor is the reference's pytorch mnist DDP example
(examples/pytorch/mnist/mnist.py); PJRT replaces the NCCL process group
with XLA's, which is the point of the CRD extension.
"""

from __future__ import annotations

import argparse
import os


def build_model(vocab: int = 30522, hidden: int = 256, layers: int = 4):
    import torch

    encoder_layer = torch.nn.TransformerEncoderLayer(
        d_model=hidden, nhead=8, dim_feedforward=hidden * 4, batch_first=True
    )
    return torch.nn.Sequential(
        torch.nn.Embedding(vocab, hidden),
        torch.nn.TransformerEncoder(encoder_layer, num_layers=layers),
        torch.nn.Linear(hidden, vocab),
    )


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--per-host-batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--steps", type=int, default=10)
    args = parser.parse_args()

    import torch

    on_tpu = os.environ.get("PJRT_DEVICE") == "TPU"
    if on_tpu:
        import torch_xla.core.xla_model as xm  # type: ignore
        import torch_xla.distributed.xla_backend  # noqa: F401
        import torch.distributed as dist

        dist.init_process_group("xla", init_method="xla://")
        device = xm.xla_device()
    else:
        import torch.distributed as dist

        dist.init_process_group("gloo", init_method="env://")
        device = torch.device("cpu")

    model = build_model().to(device)
    model = torch.nn.parallel.DistributedDataParallel(model)
    optimizer = torch.optim.AdamW(model.parameters(), lr=1e-4)
    loss_fn = torch.nn.CrossEntropyLoss()

    g = torch.Generator().manual_seed(int(os.environ.get("RANK", "0")))
    for step in range(args.steps):
        ids = torch.randint(
            0, 30522, (args.per_host_batch, args.seq), generator=g
        ).to(device)
        targets = torch.roll(ids, -1, dims=1)
        optimizer.zero_grad()
        logits = model(ids)
        loss = loss_fn(logits.reshape(-1, logits.size(-1)), targets.reshape(-1))
        loss.backward()
        optimizer.step()
        if on_tpu:
            import torch_xla.core.xla_model as xm

            xm.mark_step()
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step} loss {loss.item():.4f}", flush=True)

    import torch.distributed as dist

    dist.barrier()
    dist.destroy_process_group()
    print("done", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
