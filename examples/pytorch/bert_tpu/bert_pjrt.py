"""BERT-base MLM training over PJRT/XLA on TPU (BASELINE config #3).

Runs inside the PyTorchJob of pytorchjob_bert_pjrt_v5e16.yaml: each host
pod gets PJRT_DEVICE=TPU + libtpu identity from the operator, so torch_xla
brings up the slice with no torchrun and no cloud metadata. PJRT wants one
process per chip, so on TPU the entrypoint fans out with xmp.spawn (4
processes on a v5e host) and each process joins the xla:// rendezvous;
the injected c10d env (RANK/WORLD_SIZE) describes hosts, the xla world
describes chips. Off-TPU (smoke runs, CI) it falls back to a single plain
torch.distributed gloo process over the injected c10d env — the same
model step, CPU tensors.

The GPU-era ancestor is the reference's pytorch mnist DDP example
(examples/pytorch/mnist/mnist.py); PJRT replaces the NCCL process group
with XLA's, which is the point of the CRD extension.
"""

from __future__ import annotations

import argparse
import os


def build_model(vocab: int = 30522, hidden: int = 256, layers: int = 4):
    import torch

    encoder_layer = torch.nn.TransformerEncoderLayer(
        d_model=hidden, nhead=8, dim_feedforward=hidden * 4, batch_first=True
    )
    return torch.nn.Sequential(
        torch.nn.Embedding(vocab, hidden),
        torch.nn.TransformerEncoder(encoder_layer, num_layers=layers),
        torch.nn.Linear(hidden, vocab),
    )


def train(args, on_tpu: bool, batch: int) -> None:
    import torch
    import torch.distributed as dist

    if on_tpu:
        import torch_xla.core.xla_model as xm  # type: ignore
        import torch_xla.distributed.xla_backend  # noqa: F401

        dist.init_process_group("xla", init_method="xla://")
        device = xm.xla_device()
    else:
        dist.init_process_group("gloo", init_method="env://")
        device = torch.device("cpu")

    model = build_model().to(device)
    model = torch.nn.parallel.DistributedDataParallel(model)
    optimizer = torch.optim.AdamW(model.parameters(), lr=1e-4)
    loss_fn = torch.nn.CrossEntropyLoss()

    g = torch.Generator().manual_seed(dist.get_rank())
    for step in range(args.steps):
        ids = torch.randint(0, 30522, (batch, args.seq), generator=g).to(device)
        targets = torch.roll(ids, -1, dims=1)
        optimizer.zero_grad()
        logits = model(ids)
        loss = loss_fn(logits.reshape(-1, logits.size(-1)), targets.reshape(-1))
        loss.backward()
        optimizer.step()
        if on_tpu:
            import torch_xla.core.xla_model as xm

            xm.mark_step()
        if step % 5 == 0 or step == args.steps - 1:
            print(f"rank {dist.get_rank()} step {step} loss {loss.item():.4f}",
                  flush=True)

    dist.barrier()
    dist.destroy_process_group()
    print("done", flush=True)


def _tpu_worker(index: int, args, batch: int) -> None:
    train(args, on_tpu=True, batch=batch)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--per-host-batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--steps", type=int, default=10)
    args = parser.parse_args()

    if os.environ.get("PJRT_DEVICE") == "TPU":
        # One process per chip: a single un-spawned process would leave the
        # xla:// rendezvous waiting on ranks that never start (world size =
        # chips, not hosts). xmp.spawn sizes itself from the PJRT runtime.
        import torch_xla.distributed.xla_multiprocessing as xmp  # type: ignore

        chips = int(os.environ.get("TPU_CHIPS_PER_HOST", "4"))
        batch = max(1, args.per_host_batch // chips)
        xmp.spawn(_tpu_worker, args=(args, batch))
    else:
        train(args, on_tpu=False, batch=args.per_host_batch)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
