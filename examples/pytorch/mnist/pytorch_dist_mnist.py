"""Distributed PyTorch MNIST via the operator's c10d env contract.

Reference counterpart: examples/pytorch/mnist/mnist.py (DDP over gloo/nccl,
launched by pytorch_job_mnist_gloo.yaml). Consumes exactly the env the
PyTorchJob controller injects (MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK —
bootstrap/c10d.py), trains a small CNN with DistributedDataParallel on
synthetic digits, and verifies gradients actually all-reduced. The
process-backed e2e suite runs this for real on CPU/gloo.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--backend", default=os.environ.get("PT_BACKEND", "gloo"))
    args = parser.parse_args(argv)

    import torch
    import torch.distributed as dist
    import torch.nn as nn
    import torch.nn.functional as F

    world_size = int(os.environ.get("WORLD_SIZE", "1"))
    rank = int(os.environ.get("RANK", "0"))
    distributed = world_size > 1
    if distributed:
        dist.init_process_group(args.backend, rank=rank, world_size=world_size)
        print(
            f"[pt-mnist] rank {rank}/{world_size} rendezvous ok "
            f"(master {os.environ.get('MASTER_ADDR')}:{os.environ.get('MASTER_PORT')})",
            flush=True,
        )

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(1, 16, 5, padding=2)
            self.conv2 = nn.Conv2d(16, 32, 5, padding=2)
            self.fc1 = nn.Linear(32 * 7 * 7, 64)
            self.fc2 = nn.Linear(64, 10)

        def forward(self, x):
            x = F.max_pool2d(F.relu(self.conv1(x)), 2)
            x = F.max_pool2d(F.relu(self.conv2(x)), 2)
            x = x.flatten(1)
            return self.fc2(F.relu(self.fc1(x)))

    torch.manual_seed(0)  # identical init everywhere; DDP keeps it in sync
    model = Net()
    if distributed:
        model = nn.parallel.DistributedDataParallel(model)
    opt = torch.optim.SGD(model.parameters(), lr=args.lr, momentum=0.9)

    gen = torch.Generator().manual_seed(rank + 1)
    loss = None
    for step in range(args.steps):
        labels = torch.randint(0, 10, (args.batch,), generator=gen)
        images = torch.randn(args.batch, 1, 28, 28, generator=gen) * 0.25
        for i, lab in enumerate(labels):  # class-dependent bright rows
            images[i, 0, 2 + 2 * lab : 4 + 2 * lab, :] += 1.5
        opt.zero_grad()
        loss = F.cross_entropy(model(images), labels)
        loss.backward()
        opt.step()
        if step % 5 == 0 or step == args.steps - 1:
            print(f"[pt-mnist] rank {rank} step {step} loss {loss.item():.4f}", flush=True)

    if distributed:
        # Parameters must be bit-identical across ranks after DDP training.
        probe = next(model.parameters()).detach().clone()
        gathered = [torch.empty_like(probe) for _ in range(world_size)]
        dist.all_gather(gathered, probe)
        for other in gathered:
            if not torch.equal(other, gathered[rank]):
                print("[pt-mnist] FAIL: ranks diverged", flush=True)
                return 2
        print("[pt-mnist] ranks in sync", flush=True)
        dist.destroy_process_group()
    print("[pt-mnist] done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
