"""Point-to-point smoke test for the c10d contract (master <-> workers).

The smallest possible proof that the operator's injected rendezvous env
(MASTER_ADDR / MASTER_PORT / RANK / WORLD_SIZE — bootstrap/c10d.py) forms
a working process group: rank 0 sends each worker a tensor, the worker
squares it elementwise and sends it back, rank 0 checks the arithmetic.
Unlike an allreduce, send/recv exercises every pairwise master<->worker
path individually, so a single broken address mapping is attributable.

Re-design of the reference's pytorch smoke-dist example
(examples/pytorch/smoke-dist/dist_sendrecv.py): same topology and
behavior, rebuilt on torch.distributed's modern env:// init with explicit
verification (the original only logged the tensors).
"""

from __future__ import annotations

import os

import torch
import torch.distributed as dist


def run() -> None:
    rank = dist.get_rank()
    world = dist.get_world_size()
    if rank == 0:
        for peer in range(1, world):
            payload = torch.full((2, 2), float(peer))
            dist.send(tensor=payload, dst=peer)
            result = torch.zeros(2, 2)
            dist.recv(tensor=result, src=peer)
            expected = payload * payload
            assert torch.equal(result, expected), (
                f"worker {peer} returned {result}, expected {expected}"
            )
            print(f"SENDRECV_OK peer={peer}", flush=True)
    else:
        payload = torch.zeros(2, 2)
        dist.recv(tensor=payload, src=0)
        dist.send(tensor=payload * payload, dst=0)
        print("SENDRECV_OK worker", flush=True)


def main() -> int:
    env = {k: os.environ.get(k, "") for k in
           ("MASTER_ADDR", "MASTER_PORT", "RANK", "WORLD_SIZE")}
    print(f"SENDRECV_ENV {env}", flush=True)
    dist.init_process_group("gloo", init_method="env://")
    run()
    dist.barrier()
    dist.destroy_process_group()
    print("done", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
