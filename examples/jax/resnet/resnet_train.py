"""ResNet-50 data-parallel training — the BASELINE.md "ResNet-50
TPUStrategy v5e-8" config, TPU-natively (pjit DP instead of TPUStrategy).

Reference counterpart: the TF distribution_strategy examples
(examples/tensorflow/distribution_strategy/keras-API/
multi_worker_strategy-with-keras.py) driven through TF_CONFIG.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

try:
    import tf_operator_tpu  # noqa: F401
except ImportError:
    sys.path.insert(
        0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default=None)
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=256, help="global batch size")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--log-every", type=int, default=10)
    args = parser.parse_args(argv)

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tf_operator_tpu.models import resnet
    from tf_operator_tpu.runtime.tpu_init import tpu_init
    from tf_operator_tpu.train.data import shard_batch

    topo, mesh = tpu_init()
    n = jax.device_count()
    on_tpu = jax.devices()[0].platform == "tpu"
    if args.model is None:
        args.model = "resnet50" if on_tpu else "resnet-tiny"
    if not on_tpu:
        args.image_size = min(args.image_size, 32)
        args.batch = min(args.batch, 2 * n)
    cfg = resnet.CONFIGS[args.model]
    print(
        f"[resnet] {args.model} process {topo.process_id}/{topo.num_processes} "
        f"devices={n} batch={args.batch}",
        flush=True,
    )

    model = resnet.ResNet(cfg)
    variables = resnet.init_variables(
        model, jax.random.PRNGKey(0), batch=1, image_size=args.image_size
    )
    tx = optax.sgd(args.lr, momentum=0.9, nesterov=True)
    opt_state = tx.init(variables["params"])

    data_sharding = NamedSharding(mesh, P(mesh.axis_names))
    repl = NamedSharding(mesh, P())

    @jax.jit
    def train_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats},
                images, train=True, mutable=["batch_stats"],
            )
            one_hot = jax.nn.one_hot(labels, cfg.num_classes)
            loss = -jax.numpy.mean(
                jax.numpy.sum(one_hot * jax.nn.log_softmax(logits), axis=-1)
            )
            return loss, mut["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, opt_state, loss

    params = jax.device_put(variables["params"], repl)
    batch_stats = jax.device_put(variables["batch_stats"], repl)
    opt_state = jax.device_put(opt_state, repl)

    if args.batch % topo.num_processes:
        raise SystemExit("--batch must divide by the process count")
    local_batch = args.batch // topo.num_processes
    rng = np.random.default_rng(topo.process_id)
    t0 = time.perf_counter()
    for step in range(args.steps):
        images = rng.normal(0, 1, (local_batch, args.image_size, args.image_size, 3)).astype(np.float32)
        labels = rng.integers(0, cfg.num_classes, (local_batch,)).astype(np.int32)
        images = shard_batch(images, data_sharding)
        labels = shard_batch(labels, data_sharding)
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels
        )
        if step % args.log_every == 0 or step == args.steps - 1:
            dt = time.perf_counter() - t0
            ips = (step + 1) * args.batch / max(dt, 1e-9)
            print(
                f"[resnet] step {step} loss {float(loss):.4f} images/sec {ips:,.0f}",
                flush=True,
            )
    print("[resnet] done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
