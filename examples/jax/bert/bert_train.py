"""BERT-base MLM pretraining — the BASELINE.md "BERT-base v5e-16" config,
TPU-natively (Flax under pjit; no torch-XLA bridge needed).

Reference counterpart: BERT as a PyTorchJob user container over the c10d
env contract (pkg/controller.v1/pytorch/pytorch.go:27-82).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

try:
    import tf_operator_tpu  # noqa: F401
except ImportError:
    sys.path.insert(
        0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default=None)
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=64, help="global batch size")
    parser.add_argument("--seq", type=int, default=512)
    parser.add_argument("--mask-prob", type=float, default=0.15)
    parser.add_argument("--lr", type=float, default=1e-4)
    parser.add_argument("--log-every", type=int, default=10)
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tf_operator_tpu.models import bert
    from tf_operator_tpu.runtime.tpu_init import tpu_init
    from tf_operator_tpu.train.data import shard_batch

    topo, mesh = tpu_init()
    n = jax.device_count()
    on_tpu = jax.devices()[0].platform == "tpu"
    if args.model is None:
        args.model = "bert-base" if on_tpu else "bert-tiny"
    cfg = bert.CONFIGS[args.model]
    if not on_tpu:
        args.batch = min(args.batch, 2 * n)
    args.seq = min(args.seq, cfg.max_len)
    print(
        f"[bert] {args.model} process {topo.process_id}/{topo.num_processes} "
        f"devices={n} seq={args.seq}",
        flush=True,
    )

    model = bert.make_model(cfg)
    params = bert.init_params(model, jax.random.PRNGKey(0), batch=1, seq=args.seq)
    tx = optax.adamw(args.lr, weight_decay=0.01)
    opt_state = tx.init(params)

    MASK_ID = 4  # conventional [MASK]-style id for the synthetic stream
    data_sharding = NamedSharding(mesh, P(mesh.axis_names))
    repl = NamedSharding(mesh, P())

    @jax.jit
    def train_step(params, opt_state, input_ids, labels, mask):
        def loss_fn(p):
            logits = model.apply({"params": p}, input_ids, attention_mask=mask)
            logits = logits.astype(jnp.float32)
            ll = jnp.take_along_axis(
                jax.nn.log_softmax(logits), labels[..., None].clip(0), axis=-1
            )[..., 0]
            weights = (labels >= 0).astype(jnp.float32)
            return -(ll * weights).sum() / jnp.maximum(weights.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params = jax.device_put(params, repl)
    opt_state = jax.device_put(opt_state, repl)

    if args.batch % topo.num_processes:
        raise SystemExit("--batch must divide by the process count")
    local_batch = args.batch // topo.num_processes
    rng = np.random.default_rng(topo.process_id)
    t0 = time.perf_counter()
    for step in range(args.steps):
        tokens = rng.integers(5, cfg.vocab_size, (local_batch, args.seq)).astype(np.int32)
        mask_pos = rng.random((local_batch, args.seq)) < args.mask_prob
        labels = np.where(mask_pos, tokens, -1).astype(np.int32)
        input_ids = np.where(mask_pos, MASK_ID, tokens).astype(np.int32)
        attn = np.ones((local_batch, args.seq), dtype=bool)
        step_args = [
            shard_batch(x, data_sharding) for x in (input_ids, labels, attn)
        ]
        params, opt_state, loss = train_step(params, opt_state, *step_args)
        if step % args.log_every == 0 or step == args.steps - 1:
            dt = time.perf_counter() - t0
            tps = (step + 1) * args.batch * args.seq / max(dt, 1e-9)
            print(
                f"[bert] step {step} loss {float(loss):.4f} tokens/sec {tps:,.0f}",
                flush=True,
            )
    print("[bert] done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
