"""Distributed MNIST on JAX — parity with the reference's canonical example
(examples/tensorflow/dist-mnist/dist_mnist.py): same model topology, but
data-parallel over a device mesh instead of PS/Worker gRPC.

Runs identically as a single process (dev box), one TPU chip, or an
operator-launched multi-host JAXJob (env injected by bootstrap/jaxdist.py).
"""

from __future__ import annotations

import argparse
import os
import sys

# Containers pip-install the package; running from a source checkout works too.
try:
    import tf_operator_tpu  # noqa: F401
except ImportError:
    sys.path.insert(
        0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch", type=int, default=64, help="global batch size")
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--target-accuracy", type=float, default=0.0)
    args = parser.parse_args(argv)

    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tf_operator_tpu.models import mnist
    from tf_operator_tpu.runtime.tpu_init import tpu_init

    topo, mesh = tpu_init()
    print(
        f"[mnist] process {topo.process_id}/{topo.num_processes} "
        f"devices={jax.device_count()} mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}",
        flush=True,
    )

    model = mnist.make_model()
    params = mnist.init_params(model, jax.random.PRNGKey(0), batch=1)
    tx = optax.sgd(args.lr, momentum=0.9)
    opt_state = tx.init(params)

    # Data-parallel: batch sharded over every mesh axis, params replicated.
    data_sharding = NamedSharding(mesh, P(mesh.axis_names))
    repl = NamedSharding(mesh, P())

    @jax.jit
    def train_step(params, opt_state, images, labels):
        def loss_fn(p):
            return mnist.loss_and_accuracy(model, p, images, labels)

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    params = jax.device_put(params, repl)
    opt_state = jax.device_put(opt_state, repl)

    # Each process feeds its local shard of the global batch; JAX assembles
    # the global array (no host gathers a full batch).
    if args.batch % topo.num_processes:
        raise SystemExit("--batch must divide by the process count")
    local_batch = args.batch // topo.num_processes
    data = mnist.SyntheticMnist(local_batch, seed=topo.process_id * 7919)
    acc = 0.0
    for step, (images, labels) in zip(range(args.steps), data):
        images = jax.make_array_from_process_local_data(data_sharding, images)
        labels = jax.make_array_from_process_local_data(data_sharding, labels)
        params, opt_state, loss, acc = train_step(params, opt_state, images, labels)
        if step % 50 == 0 or step == args.steps - 1:
            print(
                f"[mnist] step {step} loss {float(loss):.4f} acc {float(acc):.3f}",
                flush=True,
            )

    final_acc = float(acc)
    print(f"[mnist] done final_acc={final_acc:.3f}", flush=True)
    if args.target_accuracy and final_acc < args.target_accuracy:
        print(f"[mnist] FAIL: accuracy {final_acc} < {args.target_accuracy}", flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
