"""Llama pretraining — the flagship JAXJob workload (BASELINE.md:
Llama-2-7B Flax FSDP on v5e-32, ≥45% MFU target).

The whole distributed story lives in three lines: `tpu_init()` rendezvouses
and builds the mesh the job manifest declared (JAX_MESH_SPEC), the train
state initializes born-sharded over it, and one jitted step carries
forward+backward+optimizer with XLA-scheduled collectives. The same script
is the single-chip dev loop and the 32-chip FSDP job.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

try:
    import tf_operator_tpu  # noqa: F401
except ImportError:
    sys.path.insert(
        0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default=None, help="default: sized to the hardware")
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=32, help="global batch size")
    parser.add_argument("--seq", type=int, default=2048)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--log-every", type=int, default=10)
    parser.add_argument("--checkpoint-dir", default=os.environ.get("CHECKPOINT_DIR", ""))
    parser.add_argument("--checkpoint-every", type=int, default=200)
    parser.add_argument("--data", default="", help="token shard file (raw ids); synthetic when empty")
    parser.add_argument("--data-dtype", default="int32", choices=["int32", "uint16"])
    args = parser.parse_args(argv)

    import jax

    from tf_operator_tpu.models import llama
    from tf_operator_tpu.parallel.sharding import batch_sharding
    from tf_operator_tpu.runtime.heartbeat import (
        record_checkpoint,
        record_peer_address,
        record_progress,
        record_restore,
    )
    from tf_operator_tpu.runtime.profiling import step_profiler
    from tf_operator_tpu.runtime.tpu_init import tpu_init
    from tf_operator_tpu.train.data import DevicePrefetch, SyntheticTokens
    from tf_operator_tpu.train.train_step import (
        init_sharded_train_state,
        make_optimizer,
        make_train_step,
    )

    topo, mesh = tpu_init()
    n = jax.device_count()
    slice_note = (
        f" slice={topo.slice_index}/{topo.num_slices}" if topo.num_slices > 1 else ""
    )
    print(
        f"[llama] process {topo.process_id}/{topo.num_processes} devices={n} "
        f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}{slice_note}",
        flush=True,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if args.model is None:
        # Size to the hardware: 7B needs a pod slice; one chip fits 400M;
        # a dev box gets the tiny config.
        args.model = "llama2-7b" if (on_tpu and n >= 16) else (
            "llama-400m" if on_tpu else "llama-tiny"
        )
    config = llama.CONFIGS[args.model]
    if not on_tpu:
        args.seq = min(args.seq, config.max_seq_len)
    model = llama.Llama(config)
    optimizer = make_optimizer(learning_rate=args.lr, decay_steps=max(args.steps, 101))
    state, sharding = init_sharded_train_state(
        model, jax.random.PRNGKey(0), optimizer, mesh, batch=1, seq=min(args.seq, 128)
    )
    # donate_batch: with the device-prefetch stage below every batch is a
    # fresh device buffer, so the step may recycle the consumed one.
    step_fn, _ = make_train_step(
        model, optimizer, mesh, state, sharding=sharding, donate_batch=True
    )

    ckpt = None
    shard_srv = None
    if args.checkpoint_dir:
        from tf_operator_tpu.bootstrap.heartbeat import (
            ENV_DELTA_PERSIST,
            ENV_PEER_RESTORE_ADDRS,
            ENV_SHARD_SERVER,
            ENV_SHARDED_RESTORE,
            ENV_WARM_START,
        )
        from tf_operator_tpu.train.checkpoint import CheckpointManager
        from tf_operator_tpu.train.restore import restore_with_fallback

        ckpt_dir = args.checkpoint_dir
        if getattr(topo, "slice_world", False) and topo.num_slices > 1:
            # Slice-local worlds (JAX_SLICE_LOCAL_WORLD) are independent
            # training replicas: each slice owns its own checkpoint
            # stream, or two coordinators would race one orbax dir.
            ckpt_dir = os.path.join(ckpt_dir, f"slice-{topo.slice_index}")
        truthy = ("1", "true", "yes")
        delta_persist = os.environ.get(ENV_DELTA_PERSIST) in truthy
        ckpt = CheckpointManager(
            ckpt_dir, sharding=sharding, model_meta=config.geometry(),
            # Operator contract (bootstrap/heartbeat.py): persists write
            # only changed shards + a step manifest — bytes O(change).
            delta_persist=delta_persist,
        )
        # DURABILITY ORDERING: record_checkpoint fires ONLY from the
        # persist-finalized callback, never after save() returns — save()
        # only proves the host snapshot, and publishing a step whose
        # persist is still in flight would let the operator's
        # checkpoint-gated elastic shrink take workers away against a
        # checkpoint a crash in the persist window erases.
        ckpt.add_durability_listener(record_checkpoint)
        peers = [
            a for a in os.environ.get(ENV_PEER_RESTORE_ADDRS, "").split(",")
            if a
        ]
        outcome = restore_with_fallback(
            state, ckpt, peers,
            # Operator contracts (bootstrap/heartbeat.py): scatter-gather
            # across survivors, and the elastic-grow zero-storage-read
            # warm start. Both absent on a dev box. Under delta persists
            # the restore also advertises this rank's have-list so peers
            # send only the shards that actually differ.
            sharded=os.environ.get(ENV_SHARDED_RESTORE) in truthy,
            warm_start=os.environ.get(ENV_WARM_START) in truthy,
            have=delta_persist,
        )
        state = outcome.state
        record_restore(outcome.path, outcome.cause, outcome.seconds,
                       outcome.bytes_moved)
        if outcome.step is not None:
            print(
                f"[llama] resumed from step {outcome.step} "
                f"via {outcome.path} ({outcome.cause})",
                flush=True,
            )
        if os.environ.get(ENV_SHARD_SERVER) in ("1", "true", "yes"):
            # Serve this rank's host snapshot to restoring peers and
            # advertise the address on the heartbeat lease.
            from tf_operator_tpu.runtime.shard_server import start_shard_server

            # Slice topology shapes the /v1/manifest ownership stride so
            # scatter-gather clients split their pull across slices.
            shard_srv = start_shard_server(
                ckpt,
                slice_index=topo.slice_index if topo.num_slices > 1 else None,
                num_slices=topo.num_slices if topo.num_slices > 1 else None,
            )
            record_peer_address(shard_srv.address)

    if args.batch % topo.num_processes:
        raise SystemExit("--batch must divide by the process count")
    local_batch = args.batch // topo.num_processes
    start_step = int(state.step)
    if args.data:
        # Real token shards through the native (C++ mmap + prefetch) loader;
        # each process reads a disjoint window stream of the same file. On
        # checkpoint resume, skip the windows already consumed — otherwise
        # the resumed run double-trains early data and never sees the rest.
        from tf_operator_tpu.train.data import TokenFileDataset

        # Vocab sanity BEFORE any collective: every process scans the SAME
        # file prefix (deterministic verdict on all hosts — a per-process
        # probe of disjoint windows would exit on some hosts and hang the
        # rest at the first collective), via memmap, without constructing
        # or consuming the loader.
        import numpy as np

        head = np.memmap(args.data, dtype=args.data_dtype, mode="r")
        head = head[: min(len(head), 10_000_000)]
        lo, hi = int(head.min()), int(head.max())
        if hi >= config.vocab_size or lo < 0:
            raise SystemExit(
                f"--data token ids span [{lo}, {hi}] but "
                f"{args.model or 'the selected model'} has vocab_size="
                f"{config.vocab_size}; the embedding gather would silently "
                "clamp them — pick a matching --model/config"
            )
        del head
        data = TokenFileDataset(
            args.data, local_batch, args.seq,
            dtype=args.data_dtype,
            process_id=topo.process_id, num_processes=topo.num_processes,
            skip_windows=start_step * local_batch,
        )
    else:
        data = SyntheticTokens(local_batch, args.seq, config.vocab_size,
                               seed=topo.process_id)
    data_spec = batch_sharding(mesh, with_sp=False)
    # Device-side double buffer: batch k+1's host->device transfer is
    # issued while step k runs (multi-process it rides
    # make_array_from_process_local_data via shard_batch). Restart-safe by
    # construction: the window stream is a pure function of the STEP count
    # (skip_windows = start_step * local_batch above), so the in-flight
    # batches of a killed process are re-produced by its successor and a
    # checkpoint resume can never double-consume or skip data.
    batches = DevicePrefetch(data, data_spec, depth=2)

    t0 = time.perf_counter()
    try:
        for step in range(start_step, args.steps):
            state, loss = step_fn(state, next(batches))
            # XLA trace capture when TPU_PROFILE_DIR is set (no-op otherwise).
            step_profiler(step)
            if step % args.log_every == 0 or step == args.steps - 1:
                dt = time.perf_counter() - t0
                done = step - start_step + 1
                tps = done * args.batch * args.seq / max(dt, 1e-9)
                print(
                    f"[llama] step {step} loss {float(loss):.4f} "
                    f"tokens/sec {tps:,.0f} ({tps / max(n,1):,.0f}/chip)",
                    flush=True,
                )
                # Surface throughput to the operator (gang liveness already
                # rides the heartbeat; this adds the utilization signal the
                # autoscaler consumes as training_workload_tokens_per_sec).
                # Log-cadence, not per-step: each call wakes the renewal
                # thread, and a lease write per step would be apiserver spam.
                record_progress(step=step, tokens_per_sec=tps)
            if ckpt is not None and (step + 1) % args.checkpoint_every == 0:
                # Synchronous device->host snapshot only; the persist runs
                # in the background and the durability listener publishes
                # the step once — and only once — it is finalized.
                ckpt.save(state)
        if ckpt is not None:
            ckpt.save(state, force=True)
    finally:
        # Shutdown hygiene: drain the persist queue and close orbax on
        # EVERY exit path — a completing (or dying) job must never leave
        # an in-flight async write behind as a torn tmp dir.
        if ckpt is not None:
            ckpt.close()
        if shard_srv is not None:
            shard_srv.stop()
    print("[llama] done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
