"""Cluster smoke test: prove ops execute on EVERY task of a TFJob.

Re-design of the reference's tf_smoke (examples/tensorflow/tf_sample/
tf_smoke.py): the TF1 original had the master build one graph with a
matmul pinned to each `/job:<type>/task:<i>` device. The TF2 form keeps
the behavior — the chief connects to the whole cluster and places an
eager matmul on every remote task, verifying each one actually computes —
while non-chief tasks just serve (`tf.distribute.Server`) until the chief
reports success.

The point is placement breadth: a broken address for ANY task fails the
chief's loop with that task's name in hand, which a collective allreduce
(that only proves the ring) cannot attribute.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--width", type=int, default=64)
    parser.add_argument("--serve-secs", type=float, default=120.0,
                        help="non-chief tasks exit after this long")
    args = parser.parse_args()

    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    import numpy as np
    import tensorflow as tf

    resolver = tf.distribute.cluster_resolver.TFConfigClusterResolver()
    cluster_spec = resolver.cluster_spec().as_dict()
    task_type, task_id = resolver.task_type, int(resolver.task_id)
    print(f"SMOKE_TASK {json.dumps({'type': task_type, 'index': task_id})}",
          flush=True)

    is_chief = task_type in (None, "chief") or (
        task_type == "worker" and task_id == 0 and "chief" not in cluster_spec
    )
    if not is_chief:
        # Serve the chief's remote ops; bounded lifetime so an orphaned
        # worker cannot outlive the job forever.
        server = tf.distribute.Server(
            resolver.cluster_spec(), job_name=task_type, task_index=task_id
        )
        print("SMOKE_SERVING", flush=True)
        time.sleep(args.serve_secs)
        print("SMOKE_SERVER_DONE", flush=True)
        return 0

    # Graph placement through the chief's own server — the reference's
    # architecture, still the supported TF surface for per-task device
    # pinning. (Eager `connect_to_cluster` cannot do this from inside the
    # cluster: it rewrites the current task as an external client while
    # its coordination service waits on the declared chief address that
    # the client, by construction, no longer serves.)
    server = tf.distribute.Server(
        resolver.cluster_spec(), job_name=task_type or "chief",
        task_index=task_id
    )
    rng = np.random.default_rng(0)
    a = rng.random((args.width, args.width), dtype=np.float32)
    b = rng.random((args.width, args.width), dtype=np.float32)
    want = a @ b
    devices, results = [], []
    with tf.Graph().as_default():
        for job_name, addrs in sorted(cluster_spec.items()):
            if job_name == "evaluator":
                continue  # not part of the training cluster
            for i in range(len(addrs)):
                device = f"/job:{job_name}/task:{i}"
                with tf.device(device):
                    results.append(tf.matmul(tf.constant(a), tf.constant(b)))
                devices.append(device)
        with tf.compat.v1.Session(server.target) as sess:
            outs = sess.run(results)
    for device, got in zip(devices, outs):
        if not np.allclose(got, want, atol=1e-3):
            print(f"SMOKE_FAIL {device}", flush=True)
            return 1
        print(f"SMOKE_OK {device}", flush=True)
    print(f"SMOKE_DONE tasks={len(devices)}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
