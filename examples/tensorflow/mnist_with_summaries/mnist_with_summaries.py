"""Single-worker MNIST with TensorBoard summaries on a shared volume.

Re-design of the reference's mnist_with_summaries (examples/tensorflow/
mnist_with_summaries/mnist_with_summaries.py): the TF1 original existed
to exercise every TensorBoard dashboard from a TFJob whose event files
land on a PV. The modern form keeps that: a keras model trained with a
custom loop that writes scalar (loss/accuracy), histogram (weights), and
image (input digits) summaries via tf.summary to --log-dir, which the
manifest mounts from a PVC so TensorBoard can serve it after the job.

--synthetic-data skips the MNIST download for hermetic clusters/CI.
"""

from __future__ import annotations

import argparse
import os


def load_data(synthetic: bool):
    import numpy as np

    if synthetic:
        rng = np.random.default_rng(0)
        x = rng.random((2048, 28, 28), dtype=np.float32)
        y = rng.integers(0, 10, size=(2048,)).astype(np.int64)
        return x, y
    import tensorflow as tf

    (x, y), _ = tf.keras.datasets.mnist.load_data()
    return (x / 255.0).astype("float32"), y.astype("int64")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--log-dir", default="/train/logs")
    parser.add_argument("--summary-every", type=int, default=10)
    parser.add_argument("--synthetic-data", action="store_true")
    args = parser.parse_args()

    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    import tensorflow as tf

    x, y = load_data(args.synthetic_data)
    model = tf.keras.Sequential([
        tf.keras.layers.Input((28, 28)),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)
    optimizer = tf.keras.optimizers.Adam(args.lr)
    writer = tf.summary.create_file_writer(args.log_dir)

    for step in range(args.steps):
        lo = step * args.batch % (len(x) - args.batch)
        xb, yb = x[lo:lo + args.batch], y[lo:lo + args.batch]
        with tf.GradientTape() as tape:
            logits = model(xb, training=True)
            loss = loss_fn(yb, logits)
        grads = tape.gradient(loss, model.trainable_variables)
        optimizer.apply_gradients(zip(grads, model.trainable_variables))
        if step % args.summary_every == 0 or step == args.steps - 1:
            acc = float(tf.reduce_mean(tf.cast(
                tf.argmax(logits, axis=-1) == yb, tf.float32)))
            with writer.as_default(step=step):
                tf.summary.scalar("loss", loss)
                tf.summary.scalar("accuracy", acc)
                for v in model.trainable_variables:
                    tf.summary.histogram(v.name, v)
                tf.summary.image("input", xb[:3][..., None], max_outputs=3)
            print(f"step {step} loss {float(loss):.4f} acc {acc:.3f}",
                  flush=True)
    writer.flush()
    print(f"SUMMARIES_WRITTEN {args.log_dir}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
