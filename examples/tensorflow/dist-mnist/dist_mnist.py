"""Between-graph PS/Worker MNIST training over the operator's TF_CONFIG.

Reference counterpart: examples/tensorflow/dist-mnist/dist_mnist.py
(TF_CONFIG parse at :102-110, ClusterSpec/Server at :139-143,
replica_device_setter + SyncReplicasOptimizer below that). This rewrite
keeps the reference's *architecture* — parameter servers hold the model,
workers pull/push over the network, topology comes entirely from the
operator-injected TF_CONFIG and headless-service DNS — but implements the
transport with numpy + stdlib sockets instead of TensorFlow's gRPC, so the
example runs in any image (TF isn't required) and the operator contract is
exercised for real: if TF_CONFIG or the service DNS is wrong, training
cannot converge or even start.

Roles (same dispatch as the reference):
  ps      — serve GET/PUSH on this shard of the weights; SGD-apply pushed
            gradients (async updates, the reference's non-sync default);
            exits after every worker says DONE (the real dist_mnist's PS
            blocks in server.join() forever and relies on CleanPodPolicy —
            supporting DONE keeps standalone runs finite too).
  worker  — synthetic-MNIST logistic regression: pull weights, local
            gradient step, push; worker-0's exit ends the TFJob
            (IsWorker0Completed semantics).
  chief   — worker duties + final loss report (when the topology has one).
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import socket
import socketserver
import struct
import sys
import threading
import time

import numpy as np

DIM, CLASSES = 784, 10


# ----------------------------------------------------------- wire protocol
def send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def recv_msg(sock: socket.socket):
    header = _recv_exact(sock, 4)
    return pickle.loads(_recv_exact(sock, struct.unpack("!I", header)[0]))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def call(addr, obj, retries: int = 60):
    """RPC with connect-retry: peers come up in any order (the reference
    leans on gRPC's lazy channel for the same tolerance)."""
    last = None
    for _ in range(retries):
        try:
            with socket.create_connection(addr, timeout=10) as sock:
                send_msg(sock, obj)
                return recv_msg(sock)
        except OSError as exc:
            last = exc
            time.sleep(0.25)
    raise ConnectionError(f"{addr}: {last}")


def split_host(hostport: str):
    host, _, port = hostport.rpartition(":")
    return host, int(port)


# ------------------------------------------------------------------ roles
def run_ps(index: int, cluster: dict) -> int:
    """One PS shard: weights for a contiguous slice of the output classes
    (the reference shards variables across PS tasks via
    replica_device_setter round-robin)."""
    n_ps = len(cluster["ps"])
    classes = [c for c in range(CLASSES) if c % n_ps == index]
    rng = np.random.default_rng(index)
    weights = {c: rng.normal(0, 0.01, size=(DIM + 1,)).astype(np.float32)
               for c in classes}
    lock = threading.Lock()
    done_workers = set()
    n_workers = len(cluster["worker"]) + len(cluster.get("chief", []))
    shutdown = threading.Event()

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            try:
                op, payload = recv_msg(self.request)
            except ConnectionError:
                return
            with lock:
                if op == "GET":
                    send_msg(self.request, weights)
                elif op == "PUSH":
                    lr, grads = payload
                    for c, g in grads.items():
                        weights[c] -= lr * g  # async apply, arrival order
                    send_msg(self.request, "ok")
                elif op == "DONE":
                    done_workers.add(payload)
                    send_msg(self.request, "ok")
                    if len(done_workers) >= n_workers:
                        shutdown.set()

    class _Server(socketserver.ThreadingTCPServer):
        daemon_threads = True
        allow_reuse_address = True

    # Bind the address the operator's service DNS names for THIS replica
    # (under LocalProcessCluster that's the service's own loopback alias,
    # so several PS tasks can share a declared port).
    host, port = split_host(cluster["ps"][index])
    try:
        server = _Server((host, port), Handler)
    except OSError:
        server = _Server(("0.0.0.0", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    print(f"[dist-mnist] ps {index} serving classes {classes} on :{port}",
          flush=True)
    shutdown.wait()
    server.shutdown()
    print(f"[dist-mnist] ps {index} done", flush=True)
    return 0


def run_worker(task_type: str, index: int, cluster: dict, steps: int,
               batch: int, lr: float) -> int:
    ps_addrs = [split_host(h) for h in cluster["ps"]]
    rng = np.random.default_rng(100 + index)
    # Synthetic MNIST-shaped data, per-worker shard (the reference reads
    # its shard of real MNIST; shape + flow are what matter here).
    x = rng.random((4096, DIM), dtype=np.float32)
    true_w = np.random.default_rng(7).normal(size=(DIM, CLASSES))
    y = (x @ true_w + 0.1 * rng.standard_normal((4096, CLASSES))).argmax(1)

    loss = float("nan")
    for step in range(steps):
        # Pull the full model from every PS shard.
        weights = {}
        for addr in ps_addrs:
            weights.update(call(addr, ("GET", None)))
        w = np.stack([weights[c][:DIM] for c in range(CLASSES)], axis=1)
        b = np.stack([weights[c][DIM] for c in range(CLASSES)])

        idx = rng.integers(0, len(x), size=batch)
        xb, yb = x[idx], y[idx]
        logits = xb @ w + b
        logits -= logits.max(1, keepdims=True)
        p = np.exp(logits)
        p /= p.sum(1, keepdims=True)
        loss = float(-np.log(p[np.arange(batch), yb] + 1e-9).mean())
        p[np.arange(batch), yb] -= 1.0
        gw = xb.T @ p / batch  # [DIM, CLASSES]
        gb = p.mean(0)

        # Push each PS its own classes' gradients.
        n_ps = len(ps_addrs)
        for ps_i, addr in enumerate(ps_addrs):
            grads = {
                c: np.concatenate([gw[:, c], [gb[c]]]).astype(np.float32)
                for c in range(CLASSES) if c % n_ps == ps_i
            }
            call(addr, ("PUSH", (lr, grads)))
        if step % 10 == 0:
            print(f"[dist-mnist] {task_type}-{index} step {step} "
                  f"loss {loss:.4f}", flush=True)

    for addr in ps_addrs:
        call(addr, ("DONE", f"{task_type}-{index}"))
    print(f"[dist-mnist] {task_type}-{index} final loss {loss:.4f}", flush=True)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    args = parser.parse_args(argv)

    raw = os.environ.get("TF_CONFIG", "")
    if not raw:
        # Standalone dev mode: single in-process "cluster".
        print("[dist-mnist] no TF_CONFIG; running 1 ps + 1 worker locally",
              flush=True)
        cluster = {"ps": ["127.0.0.1:22231"], "worker": ["127.0.0.1:22232"]}
        ps = threading.Thread(target=run_ps, args=(0, cluster), daemon=True)
        ps.start()
        return run_worker("worker", 0, cluster, args.steps, args.batch, args.lr)

    config = json.loads(raw)  # reference dist_mnist.py:102-110
    cluster = config["cluster"]
    task_type = config["task"]["type"]
    index = int(config["task"]["index"])
    print(f"[dist-mnist] task {task_type}:{index} cluster "
          f"{ {k: len(v) for k, v in cluster.items()} }", flush=True)
    if task_type == "ps":
        return run_ps(index, cluster)
    return run_worker(task_type, index, cluster, args.steps, args.batch, args.lr)


if __name__ == "__main__":
    sys.exit(main())
