"""Keras MultiWorkerMirroredStrategy MNIST — reference parity with
examples/tensorflow/distribution_strategy/keras-API/
multi_worker_strategy-with-keras.py.

The operator injects TF_CONFIG (bootstrap/tf_config.py) with every
worker's stable headless-service DNS name; MultiWorkerMirroredStrategy
reads it and runs collective all-reduce data parallelism. Checkpoints go
through a per-worker temp dir so non-chief workers never race the chief's
writes (the standard MWMS filepath dance).

Run under the operator with `tf_job_mwms_keras.yaml`; standalone it trains
single-worker.
"""

from __future__ import annotations

import argparse
import json
import os


def mnist_dataset(batch_size: int, synthetic: bool):
    import numpy as np
    import tensorflow as tf

    if synthetic:
        x = np.random.default_rng(0).random((2048, 28, 28), dtype=np.float32)
        y = np.random.default_rng(1).integers(0, 10, size=(2048,))
    else:
        (x, y), _ = tf.keras.datasets.mnist.load_data()
        x = (x / 255.0).astype("float32")
    return (
        tf.data.Dataset.from_tensor_slices((x, y))
        .shuffle(len(x))
        .repeat()
        .batch(batch_size)
    )


def build_model():
    import tensorflow as tf

    return tf.keras.Sequential(
        [
            tf.keras.layers.Flatten(input_shape=(28, 28)),
            tf.keras.layers.Dense(128, activation="relu"),
            tf.keras.layers.Dense(10),
        ]
    )


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--steps-per-epoch", type=int, default=70)
    parser.add_argument("--per-worker-batch", type=int, default=64)
    parser.add_argument("--model-dir", default="/tmp/mwms-model")
    parser.add_argument("--synthetic-data", action="store_true",
                        help="skip the MNIST download (hermetic clusters)")
    args = parser.parse_args()

    import tensorflow as tf

    tf_config = json.loads(os.environ.get("TF_CONFIG", "{}"))
    n_workers = len(tf_config.get("cluster", {}).get("worker", [1]))

    strategy = tf.distribute.MultiWorkerMirroredStrategy()
    global_batch = args.per_worker_batch * n_workers
    with strategy.scope():
        model = build_model()
        model.compile(
            loss=tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True),
            optimizer=tf.keras.optimizers.SGD(learning_rate=0.001),
            metrics=["accuracy"],
        )

    model.fit(
        mnist_dataset(global_batch, args.synthetic_data),
        epochs=args.epochs,
        steps_per_epoch=args.steps_per_epoch,
    )

    # Chief writes the real model dir; workers write (and discard) temp
    # dirs — everyone must call save() because it is a collective op.
    task = tf_config.get("task", {})
    is_chief = task.get("type") in (None, "chief") or (
        task.get("type") == "worker" and task.get("index") == 0
        and "chief" not in tf_config.get("cluster", {})
    )
    path = args.model_dir if is_chief else os.path.join(
        args.model_dir, f"worker-tmp-{task.get('index', 0)}"
    )
    model.save(os.path.join(path, "model.keras"))
    print("saved:", path, "chief:", is_chief)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
