"""Chief/Worker/Evaluator topology with keras — the modern re-design of
the reference's estimator-API example (examples/tensorflow/
distribution_strategy/estimator-API/keras_model_to_estimator.py).

That example existed to demo `tf.estimator.train_and_evaluate`: workers
train under a collective strategy while a separate `evaluator` task
evaluates checkpoints as they appear. The estimator API is gone from
TF >= 2.16, so the same topology is rebuilt on its modern form:

- Chief + workers: MultiWorkerMirroredStrategy over the operator-injected
  TF_CONFIG; the chief publishes per-epoch weights to --model-dir.
- Evaluator: a TFJob `Evaluator` replica (TF_CONFIG task type
  "evaluator", which TF excludes from the collective world). It tails the
  model dir, evaluates each new weights file, and exits when the chief's
  DONE marker lands — sidecar evaluation, estimator semantics without
  estimator.

Run under the operator with `tf_job_train_and_evaluate.yaml`; standalone
it trains single-worker and skips the evaluator loop.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def dataset(batch: int, seed: int = 0):
    import numpy as np
    import tensorflow as tf

    rng = np.random.default_rng(seed)
    x = rng.random((1024, 10), dtype=np.float32)
    y = (x.sum(axis=1) > 5.0).astype(np.int32).reshape(-1, 1)
    return (
        tf.data.Dataset.from_tensor_slices((x, y))
        .repeat()
        .batch(batch)
    )


def build_model():
    import tensorflow as tf

    return tf.keras.Sequential([
        tf.keras.layers.Dense(16, activation="relu", input_shape=(10,)),
        tf.keras.layers.Dense(1, activation="sigmoid"),
    ])


def compile_model(model):
    import tensorflow as tf

    model.compile(
        loss=tf.keras.losses.BinaryCrossentropy(),
        optimizer=tf.keras.optimizers.SGD(0.2),
        metrics=["accuracy"],
    )


def run_evaluator(args) -> int:
    """Sidecar evaluation: evaluate every weights file the chief publishes,
    newest-first, until the DONE marker appears."""
    model = build_model()
    compile_model(model)
    data = dataset(64, seed=1)
    seen = set()
    evaluated = 0
    done_marker = os.path.join(args.model_dir, "DONE")
    deadline = time.monotonic() + args.evaluator_timeout
    while time.monotonic() < deadline:
        # Read the DONE marker BEFORE listing: the chief commits the final
        # weights before writing DONE, so a directory listing taken after
        # the marker was observed necessarily includes the last checkpoint
        # — "done and nothing fresh" can then never skip it. (Checking DONE
        # after the listing races: the chief may publish final weights +
        # DONE between the two reads.)
        done = os.path.exists(done_marker)
        fresh = []
        if os.path.isdir(args.model_dir):
            fresh = sorted(
                f for f in os.listdir(args.model_dir)
                # Skip the chief's in-progress ".tmp-*" files: only the
                # rename-committed names are safe to load.
                if f.endswith(".weights.h5") and not f.startswith(".")
                and f not in seen
            )
        for fname in fresh:
            seen.add(fname)
            try:
                model.load_weights(os.path.join(args.model_dir, fname))
            except Exception:
                continue  # chief mid-write; next pass retries a newer file
            loss, acc = model.evaluate(data, steps=8, verbose=0)
            evaluated += 1
            print(f"EVAL file={fname} loss={loss:.4f} acc={acc:.4f}",
                  flush=True)
        if done and not fresh:
            print(f"EVAL_DONE count={evaluated}", flush=True)
            return 0
        time.sleep(0.5)
    print(f"EVAL_TIMEOUT count={evaluated}", flush=True)
    return 1


def run_trainer(args, tf_config: dict) -> int:
    import numpy as np
    import tensorflow as tf

    strategy = tf.distribute.MultiWorkerMirroredStrategy()
    with strategy.scope():
        model = build_model()

    task = tf_config.get("task", {})
    cluster = tf_config.get("cluster", {})
    is_chief = task.get("type") in (None, "chief") or (
        task.get("type") == "worker" and task.get("index") == 0
        and "chief" not in cluster
    )
    n_sync = int(strategy.num_replicas_in_sync)
    print(f"trainer task={task} replicas_in_sync={n_sync}", flush=True)

    # Custom synchronized loop: Keras 3's model.fit cannot drive
    # MultiWorkerMirroredStrategy, so the step runs under strategy.run and
    # the mean gradient is applied in cross-replica context (updates every
    # mirrored copy identically).
    loss_fn = tf.keras.losses.BinaryCrossentropy()
    rng = np.random.default_rng(0)
    x_np = rng.random((1024, 10), dtype=np.float32)
    y_np = (x_np.sum(axis=1) > 5.0).astype(np.float32).reshape(-1, 1)
    lr = 0.2
    batch = args.per_worker_batch

    @tf.function
    def train_step(xb, yb):
        def step_fn(xb, yb):
            with tf.GradientTape() as tape:
                loss = loss_fn(yb, model(xb, training=True))
            return tape.gradient(loss, model.trainable_variables), loss

        per_grads, per_loss = strategy.run(step_fn, args=(xb, yb))
        grads = [
            strategy.reduce(tf.distribute.ReduceOp.MEAN, g, axis=None)
            for g in per_grads
        ]
        for v, g in zip(model.trainable_variables, grads):
            v.assign_sub(lr * g)
        return strategy.reduce(tf.distribute.ReduceOp.MEAN, per_loss, axis=None)

    def publish(epoch: int) -> None:
        """Write-then-rename so the evaluator never loads a partial file."""
        os.makedirs(args.model_dir, exist_ok=True)
        tmp = os.path.join(args.model_dir, f".tmp-{epoch}.weights.h5")
        model.save_weights(tmp)
        os.replace(tmp, os.path.join(
            args.model_dir, f"epoch-{epoch:04d}.weights.h5"))

    step = 0
    for epoch in range(args.epochs):
        for _ in range(args.steps_per_epoch):
            lo = step * batch % (len(x_np) - batch)
            loss = train_step(x_np[lo:lo + batch], y_np[lo:lo + batch])
            step += 1
        print(f"epoch {epoch} loss {float(loss):.4f}", flush=True)
        if is_chief:
            publish(epoch)
    if is_chief:
        with open(os.path.join(args.model_dir, "DONE"), "w") as f:
            f.write("ok")
    print("trainer done", flush=True)
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--steps-per-epoch", type=int, default=20)
    parser.add_argument("--per-worker-batch", type=int, default=32)
    parser.add_argument("--model-dir", default="/tmp/train-and-evaluate")
    parser.add_argument("--evaluator-timeout", type=float, default=300.0)
    args = parser.parse_args()

    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    tf_config = json.loads(os.environ.get("TF_CONFIG", "{}"))
    if tf_config.get("task", {}).get("type") == "evaluator":
        return run_evaluator(args)
    return run_trainer(args, tf_config)


if __name__ == "__main__":
    raise SystemExit(main())
