"""ResNet-50 training under tf.distribute.TPUStrategy (BASELINE config #2).

Runs inside the TFJob of tfjob_resnet50_tpustrategy_v5e8.yaml: on a TPU
host pod the operator has already injected the libtpu identity env
(TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / TPU_ACCELERATOR_TYPE), so
TPUClusterResolver(tpu="local") finds the slice without cloud metadata
queries. Off-TPU (smoke runs, CI) it falls back to the default strategy on
CPU with a tiny synthetic dataset.

The GPU-era ancestor is the reference's MultiWorkerMirroredStrategy keras
example (examples/tensorflow/distribution_strategy/keras-API); TPUStrategy
replaces the NCCL ring with the slice's ICI mesh — no code change beyond
the strategy constructor, which is the point of the CRD extension.
"""

from __future__ import annotations

import argparse
import os


# Detect the slice from env only the OPERATOR injects: the per-pod
# TPU_WORKER_ID (or an explicit TPU_NAME). The broader libtpu vars are
# unreliable markers — tensorflow's import and single-host TPU runtimes
# set TPU_WORKER_HOSTNAMES/TPU_ACCELERATOR_TYPE on any machine with a
# libtpu, slice job or not.
_ON_TPU = bool(os.environ.get("TPU_WORKER_ID") or os.environ.get("TPU_NAME"))


def build_strategy():
    import tensorflow as tf

    if _ON_TPU:
        resolver = tf.distribute.cluster_resolver.TPUClusterResolver(tpu="local")
        tf.config.experimental_connect_to_cluster(resolver)
        tf.tpu.experimental.initialize_tpu_system(resolver)
        return tf.distribute.TPUStrategy(resolver)
    return tf.distribute.get_strategy()  # CPU/GPU fallback for smoke runs


def synthetic_dataset(global_batch: int, image_size: int):
    import tensorflow as tf

    images = tf.random.stateless_uniform(
        [global_batch, image_size, image_size, 3], seed=(0, 0)
    )
    labels = tf.random.stateless_uniform(
        [global_batch], seed=(0, 1), maxval=1000, dtype=tf.int32
    )
    return (
        # Unbounded repeat: steps_per_epoch bounds each epoch, so a finite
        # repeat(steps) would starve model.fit after the first epoch.
        tf.data.Dataset.from_tensors((images, labels))
        .repeat()
        .prefetch(tf.data.AUTOTUNE)
    )


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--global-batch", type=int, default=32)
    parser.add_argument("--steps-per-epoch", type=int, default=10)
    parser.add_argument("--image-size", type=int, default=64)
    args = parser.parse_args()

    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    import tensorflow as tf

    strategy = build_strategy()
    print(f"replicas in sync: {strategy.num_replicas_in_sync}", flush=True)

    with strategy.scope():
        model = tf.keras.applications.ResNet50(
            weights=None,
            input_shape=(args.image_size, args.image_size, 3),
            classes=1000,
        )
        model.compile(
            optimizer=tf.keras.optimizers.SGD(0.1, momentum=0.9),
            loss=tf.keras.losses.SparseCategoricalCrossentropy(from_logits=False),
        )

    dataset = synthetic_dataset(args.global_batch, args.image_size)
    history = model.fit(
        dataset, epochs=args.epochs, steps_per_epoch=args.steps_per_epoch,
        verbose=2,
    )
    print(f"final loss: {history.history['loss'][-1]:.4f}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
