"""Runnable auto-tuning through the MXTune topology (MXJob jobMode=MXTune).

The reference ships TVM autotuning driven by `auto-tuning.py`/`start-job.py`
(/root/reference/examples/mxnet/tune/ — tracker process, RPC servers keyed
by device class, a tuner searching CUDA schedules). This re-design keeps
the exact topology and operator contract but replaces the TVM/CUDA search
with a dependency-free toy: tuning the k-tile size of a blocked float32
matmul — a real measurement-driven search (cache locality makes the tile
choice genuinely matter) that runs anywhere in seconds.

Roles (one script, dispatched on MX_CONFIG task.type, like start-job.py):

- **tunertracker** — the rendezvous point (DMLC_PS_ROOT_URI points here).
  Serves /healthz and waits for the tuner's POST /done {best}; then prints
  the verdict and exits 0 — MXTune jobs complete on the TunerTracker
  (controllers/mxnet.py _completion_key), so tracker exit 0 = job
  Succeeded and the operator reaps the still-running servers per
  CleanPodPolicy.
- **tunerserver** — a measurement worker: POST /measure {"n","tile"} times
  the blocked matmul locally and returns achieved GFLOP/s. Its
  `tuner-server-key` annotation surfaces in MX_CONFIG.labels so a tuner
  can address a device class, exactly as the reference keys RPC servers.
- **tuner** — drives the search: reads the server addresses from
  MX_CONFIG.cluster.tunerserver, waits for them, dispatches each tile
  candidate round-robin, and reports the best config to the tracker.

Run under the operator: `kubectl apply -f mxjob_tune.yaml` (image with
this file), or locally via the process backend — the e2e
(tests/test_e2e_process.py TestMXTuneSearch) runs this exact search
end-to-end through live operator-launched processes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

TILE_CANDIDATES = (16, 32, 64, 128, 384)
MATMUL_N = 384


def mx_config() -> dict:
    raw = os.environ.get("MX_CONFIG")
    if not raw:
        raise SystemExit("MX_CONFIG not set — run this under an MXJob")
    return json.loads(raw)


def own_entry(cfg: dict) -> tuple:
    task = cfg.get("task", {})
    entries = (cfg.get("cluster") or {}).get(task.get("type", ""), [])
    entry = entries[int(task.get("index", 0))]
    return entry["url"], int(entry["port"])


def post_json(url: str, payload: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def wait_healthy(host: str, port: int, timeout: float = 60.0) -> None:
    deadline = time.monotonic() + timeout
    url = f"http://{host}:{port}/healthz"
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2):
                return
        except Exception:  # noqa: BLE001 — booting
            time.sleep(0.2)
    raise SystemExit(f"peer {host}:{port} never became healthy")


def measure_tile(n: int, tile: int, repeats: int = 3) -> float:
    """GFLOP/s of a k-blocked matmul at this tile size (best of repeats).
    The accumulation loop over k-tiles changes the working-set size per
    pass — the toy analog of a TVM schedule's tiling knob."""
    rng = np.random.default_rng(0)
    a = rng.random((n, n), dtype=np.float32)
    b = rng.random((n, n), dtype=np.float32)
    best = 0.0
    for _ in range(repeats):
        c = np.zeros((n, n), dtype=np.float32)
        t0 = time.perf_counter()
        for k0 in range(0, n, tile):
            c += a[:, k0:k0 + tile] @ b[k0:k0 + tile, :]
        dt = time.perf_counter() - t0
        best = max(best, 2.0 * n ** 3 / dt / 1e9)
    # Keep the result honest: the blocked product must match the plain one.
    if not np.allclose(c, a @ b, atol=1e-2):
        raise SystemExit(f"blocked matmul wrong at tile={tile}")
    return best


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxtune/1.0"

    def log_message(self, fmt, *args):  # noqa: A003 — quiet
        pass

    def _json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        if self.path == "/healthz":
            return self._json(200, {"ok": True, "role": self.server.role})
        return self._json(404, {"error": self.path})

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(length) or b"{}")
        if self.path == "/measure" and self.server.role == "tunerserver":
            tile = int(payload["tile"])
            gflops = measure_tile(int(payload.get("n", MATMUL_N)), tile)
            print(f"[server] tile={tile} -> {gflops:.2f} GFLOP/s", flush=True)
            return self._json(200, {"tile": tile, "gflops": gflops})
        if self.path == "/done" and self.server.role == "tunertracker":
            # Respond BEFORE signaling completion: the main thread exits
            # the process on `done`, and setting it first could kill this
            # daemon handler between the event and the response write,
            # resetting the tuner's connection.
            self.server.best = payload
            self._json(200, {"ok": True})
            try:
                self.wfile.flush()
            except OSError:
                pass
            self.server.done.set()
            return None
        return self._json(404, {"error": self.path})


def serve(role: str, host: str, port: int) -> ThreadingHTTPServer:
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.role = role
    httpd.done = threading.Event()
    httpd.best = None
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    print(f"[{role}] listening on {host}:{port}", flush=True)
    return httpd


def run_tracker(cfg: dict) -> int:
    host, port = own_entry(cfg)
    httpd = serve("tunertracker", host, port)
    # The tracker is the job's completion key: it exits 0 only once the
    # tuner reports the finished search.
    httpd.done.wait()
    best = httpd.best or {}
    print(f"[tracker] search finished: best={best}", flush=True)
    httpd.shutdown()
    return 0


def run_server(cfg: dict) -> int:
    host, port = own_entry(cfg)
    key = (cfg.get("labels") or {}).get("tunerserver", "")
    httpd = serve("tunerserver", host, port)
    print(f"[server] device-class key={key!r}", flush=True)
    # Serve until the operator reaps this pod after job completion
    # (CleanPodPolicy) — the reference's RPC servers behave the same way.
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        httpd.shutdown()
    return 0


def run_tuner(cfg: dict) -> int:
    cluster = cfg.get("cluster") or {}
    servers = [(e["url"], int(e["port"])) for e in cluster.get("tunerserver", [])]
    tracker = cluster["tunertracker"][0]
    if not servers:
        raise SystemExit("no tunerserver replicas in MX_CONFIG")
    for host, port in servers + [(tracker["url"], int(tracker["port"]))]:
        wait_healthy(host, port)

    results = []
    for i, tile in enumerate(TILE_CANDIDATES):
        host, port = servers[i % len(servers)]  # round-robin device class
        out = post_json(f"http://{host}:{port}/measure",
                        {"n": MATMUL_N, "tile": tile})
        print(f"[tuner] server={host}:{port} tile={tile} "
              f"-> {out['gflops']:.2f} GFLOP/s", flush=True)
        results.append(out)
    best = max(results, key=lambda r: r["gflops"])
    print(f"[tuner] BEST tile={best['tile']} gflops={best['gflops']:.2f} "
          f"({len(results)} candidates over {len(servers)} servers)",
          flush=True)
    post_json(f"http://{tracker['url']}:{tracker['port']}/done", best)
    print("[tuner] done", flush=True)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--role", default="",
                        help="override MX_CONFIG task.type (local debugging)")
    args = parser.parse_args(argv)
    cfg = mx_config()
    role = args.role or cfg.get("task", {}).get("type", "")
    if role == "tunertracker":
        return run_tracker(cfg)
    if role == "tunerserver":
        return run_server(cfg)
    if role == "tuner":
        return run_tuner(cfg)
    raise SystemExit(f"unknown MXTune role {role!r}")


if __name__ == "__main__":
    sys.exit(main())
