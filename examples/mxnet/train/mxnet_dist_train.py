"""Distributed PS training over the operator's DMLC env contract.

Reference counterpart: examples/mxnet/train (dist_device_sync kvstore on
the mxnet/PS-Lite stack). The operator's obligation is the DMLC bootstrap
env — DMLC_ROLE, DMLC_PS_ROOT_URI/PORT, DMLC_NUM_SERVER, DMLC_NUM_WORKER,
DMLC_WORKER_ID (bootstrap/dmlc.py; reference mxnet.go:69-134) — and this
example consumes exactly that contract with a PS-Lite-shaped topology
implemented in numpy + stdlib sockets, so it runs in any image and fails
loudly if the injected env or service DNS is wrong:

  scheduler — rendezvous at DMLC_PS_ROOT_URI:PORT: servers register their
              own listen addresses, workers fetch the server list once all
              servers are in (PS-Lite's node-management role), then waits
              for every worker's FINISH before releasing the servers.
  server    — key-value store for its shard of the weight vector:
              ZPUSH (grad, SGD-applied) / ZPULL (weights).
  worker    — synthetic linear-regression shards: pull, local grad, push,
              DMLC_WORKER_ID-seeded data (mxnet.go:240-247 injects the id
              for exactly this kind of sharding).
"""

from __future__ import annotations

import argparse
import os
import pickle
import socket
import socketserver
import struct
import sys
import threading
import time

import numpy as np

DIM = 64


def send_msg(sock, obj) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def recv_msg(sock):
    header = _recv_exact(sock, 4)
    return pickle.loads(_recv_exact(sock, struct.unpack("!I", header)[0]))


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def call(addr, obj, retries: int = 120):
    last = None
    for _ in range(retries):
        try:
            with socket.create_connection(addr, timeout=10) as sock:
                send_msg(sock, obj)
                return recv_msg(sock)
        except OSError as exc:
            last = exc
            time.sleep(0.25)
    raise ConnectionError(f"{addr}: {last}")


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


def run_scheduler(root_port: int, n_servers: int, n_workers: int) -> int:
    servers: dict = {}
    finished: set = set()
    lock = threading.Lock()
    shutdown = threading.Event()

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            try:
                op, payload = recv_msg(self.request)
            except ConnectionError:
                return
            with lock:
                if op == "REGISTER_SERVER":
                    rank = len(servers)
                    servers[rank] = payload  # (host, port)
                    send_msg(self.request, rank)
                elif op == "GET_SERVERS":
                    ready = len(servers) >= n_servers
                    send_msg(self.request, dict(servers) if ready else None)
                elif op == "FINISH":
                    finished.add(payload)
                    send_msg(self.request, "ok")
                    if len(finished) >= n_workers:
                        # Orderly teardown: release every registered server
                        # before the scheduler exits (PS-Lite node
                        # management sends the terminate barrier the same
                        # way); the liveness poll in run_server stays as
                        # the crash fallback.
                        for addr in servers.values():
                            try:
                                call(tuple(addr), ("RELEASE", None), retries=2)
                            except ConnectionError:
                                pass
                        shutdown.set()

    bind_host = os.environ.get("DMLC_PS_ROOT_URI", "0.0.0.0")
    try:
        server = _Server((bind_host, root_port), Handler)
    except OSError:
        server = _Server(("0.0.0.0", root_port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    print(f"[mx-dist] scheduler up on :{root_port} expecting "
          f"{n_servers} servers / {n_workers} workers", flush=True)
    shutdown.wait()
    server.shutdown()
    print("[mx-dist] scheduler done", flush=True)
    return 0


def run_server(root_addr, lr: float) -> int:
    released = threading.Event()
    lock = threading.Lock()
    weights: dict = {}

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            try:
                op, payload = recv_msg(self.request)
            except ConnectionError:
                return
            with lock:
                if op == "ZPULL":
                    send_msg(self.request,
                             {k: weights[k] for k in payload if k in weights})
                elif op == "ZPUSH":
                    for key, grad in payload.items():
                        weights.setdefault(
                            key, np.zeros_like(grad))
                        weights[key] = weights[key] - lr * grad
                    send_msg(self.request, "ok")
                elif op == "RELEASE":
                    send_msg(self.request, "ok")
                    released.set()

    kv = _Server(("0.0.0.0", 0), Handler)
    port = kv.server_address[1]
    threading.Thread(target=kv.serve_forever, daemon=True).start()
    my_host = socket.gethostbyname(socket.gethostname())
    rank = call(root_addr, ("REGISTER_SERVER", (my_host, port)))
    print(f"[mx-dist] server rank {rank} serving on {my_host}:{port}", flush=True)
    # PS-Lite servers live until the scheduler tears the group down; here
    # the scheduler's exit closes the job (Scheduler-completion status rule,
    # controllers/mxnet.py), so a poll against it doubles as the release.
    while not released.is_set():
        try:
            call(root_addr, ("GET_SERVERS", []), retries=1)
        except ConnectionError:
            break  # scheduler gone: group is done
        time.sleep(0.5)
    kv.shutdown()
    print(f"[mx-dist] server rank {rank} done", flush=True)
    return 0


def run_worker(root_addr, worker_id: int, steps: int, batch: int) -> int:
    servers = None
    for _ in range(240):
        servers = call(root_addr, ("GET_SERVERS", []))
        if servers:
            break
        time.sleep(0.25)
    if not servers:
        raise ConnectionError("server list never completed")
    addrs = [tuple(servers[r]) for r in sorted(servers)]
    n = len(addrs)
    print(f"[mx-dist] worker {worker_id} sees {n} servers", flush=True)

    # Keys shard round-robin across servers (PS-Lite key partitioning).
    keys = [f"w{i}" for i in range(8)]
    by_server = {i: [k for j, k in enumerate(keys) if j % n == i]
                 for i in range(n)}
    rng = np.random.default_rng(worker_id)
    true_w = np.random.default_rng(42).standard_normal(8 * DIM)
    x = rng.standard_normal((2048, 8 * DIM)).astype(np.float64)
    y = x @ true_w + 0.01 * rng.standard_normal(2048)

    loss = float("nan")
    for step in range(steps):
        flat = {}
        for i, addr in enumerate(addrs):
            got = call(addr, ("ZPULL", by_server[i]))
            flat.update(got)
        w = np.concatenate([
            flat.get(k, np.zeros(DIM)) for k in keys
        ])
        idx = rng.integers(0, len(x), size=batch)
        xb, yb = x[idx], y[idx]
        err = xb @ w - yb
        loss = float((err ** 2).mean())
        grad = 2 * xb.T @ err / batch
        for i, addr in enumerate(addrs):
            call(addr, ("ZPUSH", {
                k: grad[j * DIM:(j + 1) * DIM]
                for j, k in enumerate(keys) if j % n == i
            }))
        if step % 10 == 0:
            print(f"[mx-dist] worker {worker_id} step {step} "
                  f"loss {loss:.4f}", flush=True)

    call(root_addr, ("FINISH", worker_id))
    print(f"[mx-dist] worker {worker_id} final loss {loss:.4f}", flush=True)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args(argv)

    role = os.environ.get("DMLC_ROLE", "")
    if not role:
        print("[mx-dist] no DMLC_ROLE; single-process smoke", flush=True)
        root = ("127.0.0.1", 29091)
        threading.Thread(target=run_scheduler, args=(root[1], 1, 1),
                         daemon=True).start()
        threading.Thread(target=run_server, args=(root, args.lr),
                         daemon=True).start()
        return run_worker(root, 0, args.steps, args.batch)

    root = (os.environ["DMLC_PS_ROOT_URI"], int(os.environ["DMLC_PS_ROOT_PORT"]))
    if role == "scheduler":
        return run_scheduler(
            root[1],
            int(os.environ["DMLC_NUM_SERVER"]),
            int(os.environ["DMLC_NUM_WORKER"]),
        )
    if role == "server":
        return run_server(root, args.lr)
    return run_worker(root, int(os.environ.get("DMLC_WORKER_ID", "0")),
                      args.steps, args.batch)


if __name__ == "__main__":
    sys.exit(main())
