"""Distributed XGBoost iris training via the operator's Rabit env contract.

Reference counterpart: examples/xgboost/xgboostjob.yaml +
the dist-iris training image. Consumes MASTER_ADDR/MASTER_PORT/WORLD_SIZE/
RANK (bootstrap/rabit.py): rank 0 runs the Rabit tracker, every rank joins
the allreduce ring and trains on its shard of iris.

Requires the xgboost package (the example image); degrades to a clear
message when absent so the manifest stays testable without it.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    try:
        import xgboost as xgb
    except ImportError:
        print("[xgb-iris] xgboost not installed in this image", flush=True)
        return 0

    import numpy as np

    world_size = int(os.environ.get("WORLD_SIZE", "1"))
    rank = int(os.environ.get("RANK", "0"))
    master = os.environ.get("MASTER_ADDR", "127.0.0.1")
    port = int(os.environ.get("MASTER_PORT", "9991"))

    if world_size > 1 and rank == 0:
        # Rank 0 doubles as the tracker host (the reference runs the Rabit
        # tracker on the Master replica).
        from xgboost.tracker import RabitTracker

        try:  # >= 1.7 signature
            tracker = RabitTracker(host_ip="0.0.0.0", n_workers=world_size, port=port)
        except TypeError:  # <= 1.6: (hostIP=..., nslave=...)
            tracker = RabitTracker(hostIP="0.0.0.0", nslave=world_size, port=port)
        try:  # 2.x: start(); 1.x: start(n_workers)
            tracker.start()
        except TypeError:
            tracker.start(world_size)

    if world_size > 1 and hasattr(xgb, "collective"):
        # xgboost >= 2.0: xgb.rabit was removed; join via collective.
        ctx = xgb.collective.CommunicatorContext(
            dmlc_communicator="rabit",
            dmlc_tracker_uri=master,
            dmlc_tracker_port=port,
            dmlc_task_id=str(rank),
        )
    elif world_size > 1:
        args = [
            f"DMLC_TRACKER_URI={master}",
            f"DMLC_TRACKER_PORT={port}",
            f"DMLC_TASK_ID={rank}",
        ]
        ctx = xgb.rabit.RabitContext([a.encode() for a in args])
    else:
        import contextlib

        ctx = contextlib.nullcontext()
    with ctx:
        rng = np.random.default_rng(rank)
        # Synthetic iris-like data (4 features, 3 classes), sharded by rank.
        n = 50
        X = rng.normal(0, 1, (n, 4))
        y = rng.integers(0, 3, n)
        X[np.arange(n), y] += 2.0  # separable signal
        dtrain = xgb.DMatrix(X, label=y)
        booster = xgb.train(
            {"objective": "multi:softmax", "num_class": 3, "eta": 0.3},
            dtrain,
            num_boost_round=10,
        )
        pred = booster.predict(dtrain)
        acc = float((pred == y).mean())
        print(f"[xgb-iris] rank {rank}/{world_size} accuracy {acc:.3f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
