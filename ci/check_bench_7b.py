#!/usr/bin/env python
"""CI: run bench.py's llama2-7b branch on the virtual CPU mesh and check
its output line.

The real bench auto-selects llama2-7b on >=16 TPU chips — hardware CI never
has — so the first v5e-32 run would otherwise be this code path's maiden
execution (VERDICT r2 weak #7). Here the same path (config resolution,
born-sharded init over the mesh, train-step timing loop, JSON emission)
runs with TF_OPERATOR_BENCH_LAYERS shrinking the layer count to fit CPU;
dims/heads/vocab stay 7B-shaped.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "TF_OPERATOR_BENCH_LAYERS": "2",
        # The 7B-dims step costs ~7 min of XLA CPU compile; cache it so
        # repeat CI runs on one machine pay it once.
        "JAX_COMPILATION_CACHE_DIR": "/tmp/jax-ci-compile-cache",
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "10",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--model", "llama2-7b", "--suite", "headline",
         "--steps", "2", "--warmup", "1", "--batch", "8", "--seq", "64"],
        env=env, capture_output=True, text=True, timeout=1800,
    )
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    if proc.returncode != 0 or not lines:
        print(proc.stdout)
        print(proc.stderr[-2000:], file=sys.stderr)
        print(f"FAIL: bench rc={proc.returncode}, no output line")
        return 1
    result = json.loads(lines[-1])
    if "llama2-7b" not in result.get("metric", ""):
        print(f"FAIL: expected llama2-7b metric, got {result['metric']!r}")
        return 1
    if result.get("unit") == "error":
        print(f"FAIL: bench error line: {result}")
        return 1
    if not result.get("value", 0) > 0:
        print(f"FAIL: non-positive throughput: {result}")
        return 1
    print(f"OK: 7B bench path ran: {result['metric']} -> "
          f"{result['value']} {result['unit']} (loss {result['extra']['loss']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
