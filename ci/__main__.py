"""``python -m ci`` — run the CI DAG locally (the reference's Prow/Argo
entry point, minus the cluster)."""

from __future__ import annotations

import argparse
import pathlib
import sys

from .dag import DagRun, default_dag, run_dag


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="Run the CI DAG")
    parser.add_argument("--junit", default="", help="Write junit XML here")
    parser.add_argument("--max-parallel", type=int, default=2)
    parser.add_argument("--only", nargs="*", default=None, help="Subset of step names (plus their deps)")
    args = parser.parse_args(argv)

    steps = default_dag()
    if args.only:
        by_name = {s.name: s for s in steps}
        unknown = [n for n in args.only if n not in by_name]
        if unknown:
            print(
                f"unknown step(s) {unknown}; available: {sorted(by_name)}",
                file=sys.stderr,
            )
            return 2
        keep = set(args.only)
        changed = True
        while changed:
            changed = False
            for name in list(keep):
                for d in by_name[name].deps:
                    if d not in keep:
                        keep.add(d)
                        changed = True
        steps = [s for s in steps if s.name in keep]

    run: DagRun = run_dag(steps, max_parallel=args.max_parallel)
    for r in run.results.values():
        print(f"[ci] {r.name}: {r.status} ({r.duration:.1f}s, {r.attempts} attempts)")
    if args.junit:
        pathlib.Path(args.junit).write_text(run.junit_xml())
    return 0 if run.ok else 1


if __name__ == "__main__":
    sys.exit(main())
