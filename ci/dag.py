"""CI DAG runner — the plain-Python replacement for the reference's
Argo/ksonnet workflow tree (test/workflows/components/workflows.libsonnet:
218-300 plus a 95k-LoC vendored jsonnet tree; SURVEY.md §7 anti-goals say:
don't reintroduce that).

A workflow is a list of Steps with dependencies; the runner executes them in
dependency order with bounded parallelism, per-step retries (the reference
test_runner.py:23-67 retries each test `num_trials` times), captures
per-step logs, and writes a junit-style XML report any CI system ingests.

The default DAG mirrors the reference's Argo step list (build, then the
test suites fanned out in parallel) with this repo's tiers.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
import xml.sax.saxutils as sx
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence


@dataclass
class Step:
    name: str
    command: Sequence[str]
    deps: Sequence[str] = ()
    retries: int = 1  # total attempts
    timeout: Optional[float] = None


@dataclass
class StepResult:
    name: str
    status: str  # "passed" | "failed" | "skipped"
    attempts: int
    duration: float
    log: str = ""


class CycleError(ValueError):
    pass


def _validate(steps: Sequence[Step]) -> Dict[str, Step]:
    by_name = {}
    for s in steps:
        if s.name in by_name:
            raise ValueError(f"duplicate step {s.name!r}")
        by_name[s.name] = s
    for s in steps:
        for d in s.deps:
            if d not in by_name:
                raise ValueError(f"step {s.name!r} depends on unknown {d!r}")
    # Kahn's algorithm for cycle detection.
    indeg = {n: len(set(s.deps)) for n, s in by_name.items()}
    ready = [n for n, d in indeg.items() if d == 0]
    seen = 0
    while ready:
        n = ready.pop()
        seen += 1
        for s in by_name.values():
            if n in s.deps:
                indeg[s.name] -= 1
                if indeg[s.name] == 0:
                    ready.append(s.name)
    if seen != len(by_name):
        raise CycleError("dependency cycle in DAG")
    return by_name


@dataclass
class DagRun:
    results: Dict[str, StepResult] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return all(r.status == "passed" for r in self.results.values())

    def junit_xml(self) -> str:
        cases = []
        for r in self.results.values():
            body = ""
            if r.status == "failed":
                body = (
                    f'<failure message="failed after {r.attempts} attempts">'
                    f"{sx.escape(r.log[-4000:])}</failure>"
                )
            elif r.status == "skipped":
                body = "<skipped/>"
            name = sx.escape(r.name, {'"': "&quot;"})
            cases.append(
                f'<testcase name="{name}" time="{r.duration:.2f}">{body}</testcase>'
            )
        failures = sum(1 for r in self.results.values() if r.status == "failed")
        return (
            '<?xml version="1.0"?>\n'
            f'<testsuite name="ci-dag" tests="{len(cases)}" failures="{failures}">\n'
            + "\n".join(cases)
            + "\n</testsuite>\n"
        )


def run_dag(
    steps: Sequence[Step],
    max_parallel: int = 4,
    log=print,
    runner=None,
) -> DagRun:
    """Execute the DAG. A step whose dependency failed is skipped. `runner`
    overrides subprocess execution for tests: fn(step) -> (returncode, log)."""
    by_name = _validate(steps)
    run = DagRun()
    lock = threading.Lock()
    done = threading.Condition(lock)

    def dep_status(step: Step) -> str:
        with lock:
            sts = [run.results.get(d) for d in step.deps]
        if any(s is not None and s.status in ("failed", "skipped") for s in sts):
            return "blocked"
        if all(s is not None for s in sts):
            return "ready"
        return "waiting"

    def execute(step: Step) -> None:
        t0 = time.monotonic()
        attempts = 0
        status, logtxt = "failed", ""
        for attempts in range(1, max(step.retries, 1) + 1):
            if runner is not None:
                code, logtxt = runner(step)
            else:
                try:
                    proc = subprocess.run(
                        list(step.command),
                        capture_output=True,
                        text=True,
                        timeout=step.timeout,
                    )
                    code, logtxt = proc.returncode, proc.stdout + proc.stderr
                except subprocess.TimeoutExpired as e:
                    code, logtxt = 124, f"timeout after {e.timeout}s"
                except Exception as e:  # missing binary etc. — a crashed
                    # worker thread must still record a result, or the DAG
                    # hangs (dependents wait forever) or reports green.
                    code, logtxt = 127, f"{type(e).__name__}: {e}"
            if code == 0:
                status = "passed"
                break
            log(f"[ci] {step.name}: attempt {attempts} failed (rc={code})")
        with done:
            run.results[step.name] = StepResult(
                step.name, status, attempts, time.monotonic() - t0, logtxt
            )
            done.notify_all()

    pending = dict(by_name)
    threads: List[threading.Thread] = []
    sem = threading.Semaphore(max_parallel)
    while pending:
        started = []
        for name, step in pending.items():
            st = dep_status(step)
            if st == "blocked":
                with done:
                    run.results[name] = StepResult(name, "skipped", 0, 0.0)
                    done.notify_all()
                started.append(name)
            elif st == "ready":
                def _wrapped(s=step):
                    with sem:
                        log(f"[ci] {s.name}: start")
                        execute(s)
                        log(f"[ci] {s.name}: {run.results[s.name].status}")

                t = threading.Thread(target=_wrapped, daemon=True)
                t.start()
                threads.append(t)
                started.append(name)
        for name in started:
            pending.pop(name)
        if not started and pending:
            with done:
                done.wait(timeout=0.5)
    for t in threads:
        t.join()
    return run


PY = sys.executable or "python3"


def default_dag() -> List[Step]:
    """The repo's CI workflow: mirror of the reference Argo step fan-out
    (workflows.libsonnet:258-291) over this repo's tiers."""
    pytest = [PY, "-m", "pytest", "-x", "-q"]
    return [
        Step("build", [PY, "-m", "compileall", "-q", "tf_operator_tpu", "examples", "ci"]),
        Step("unit-api", pytest + ["tests/test_api_defaults.py", "tests/test_api_validation.py"], deps=["build"]),
        Step("unit-controllers", pytest + ["tests/test_controller_tensorflow.py", "tests/test_controllers_frameworks.py", "tests/test_tpu_provisioning.py", "tests/test_heartbeat.py"], deps=["build"]),
        Step("operator-integration", pytest + ["tests/test_cli.py", "tests/test_metrics_latency.py", "tests/test_manifests.py"], deps=["unit-controllers"]),
        Step("e2e-process", pytest + ["tests/test_e2e_process.py"], deps=["operator-integration"], retries=2),
        # Real TF/torch consume the bootstrap contracts (VERDICT r3 #1);
        # slowest tier (a TF import costs ~20 s per pod), runs after the
        # cheap process e2e so a broken operator fails fast there first.
        Step("e2e-real-frameworks", pytest + ["tests/test_e2e_real_frameworks.py"],
             deps=["e2e-process"], retries=2),
        # The live-chip seam (VERDICT r4 #1): operator-injected env ->
        # jax-on-TPU training -> kill -> gang restart -> orbax resume on
        # the real chip. Self-skips when no TPU is reachable (probe
        # subprocess), so CI stays green off-chip. Single-tenant chip:
        # never run concurrently with bench.py.
        Step("e2e-real-tpu", pytest + ["tests/test_e2e_real_tpu.py"],
             deps=["e2e-process"], retries=2),
        Step("sdk", pytest + ["tests/test_sdk.py"], deps=["unit-api"]),
        Step("workload", pytest + ["tests/test_models.py", "tests/test_flash_pallas.py", "tests/test_workload_tier.py", "tests/test_runtime.py", "tests/test_train_pipeline.py", "tests/test_bench_check.py"], deps=["build"]),
        Step("parallelism", pytest + ["tests/test_pipeline.py"], deps=["workload"]),
        Step("native", pytest + ["tests/test_native_dataloader.py"], deps=["build"]),
        Step("examples", pytest + ["tests/test_examples.py"], deps=["workload"]),
        # Release tier (reference py/release.py exercised by release_test.py):
        # the bundle must regenerate + assemble cleanly on every change.
        Step("release-bundle", [PY, "scripts/release.py", "--version", "v0.0.0-ci",
                                "--outdir", "/tmp/ci-dist"], deps=["build"]),
        # Production-path smoke: the real operator over REST + leader
        # election against the stub apiserver (tests/test_leader_election.py
        # drives two replicas end-to-end).
        Step("kube-smoke", pytest + ["tests/test_kube_cluster.py",
                                     "tests/test_leader_election.py",
                                     "tests/test_gang_and_claims.py",
                                     "tests/test_apiserver_conformance.py"],
             deps=["operator-integration"]),
        # Race coverage (SURVEY §5.2): threaded workers + chaos under an
        # aggressive resync; retried because timing-sensitive by nature.
        Step("concurrency-stress", pytest + ["tests/test_concurrency_stress.py"],
             deps=["operator-integration"], retries=2),
        # Sync-worker-pool tier (concurrent reconciliation,
        # docs/design/control_plane_performance.md): many jobs × N workers
        # on a latency-charged cluster through the shared invariant
        # checker, workers quiescing on leadership loss, the busy-worker
        # gauge, and — the determinism half — the chaos seam pinning the
        # pool to 1 with byte-equal same-seed fault logs.
        Step("multiworker-stress", pytest + ["tests/test_multiworker_stress.py",
                                             "tests/test_workqueue.py"],
             deps=["operator-integration"], retries=2),
        # Slow-start fan-out tier (docs/design/control_plane_performance.md):
        # batch semantics, FIFO bucket fairness, the service-deletion
        # expectation protocol, and — the hard constraint — chaos/crash
        # determinism with fan-out enabled (the chaos seam serializes via
        # supports_concurrent_writes, so fault schedules stay keyed on
        # (method, call-index) byte-for-byte).
        Step("fanout", pytest + ["tests/test_fanout.py"],
             deps=["operator-integration"], retries=2),
        # Control-plane scale smoke (scripts/measure_control_plane.py
        # --mode scale): 32-replica gang bring-up, slow-start fan-out vs
        # the serial baseline at the same qps/burst. Fails if parallel
        # stops beating serial or the startup-p50 speedup (the
        # load-normalized run-over-run gate) regresses >2x
        # (build/scale_smoke_last.json); also gates concurrent
        # reconciliation — a 4-worker pool must beat 1 worker on p50
        # queue wait and makespan on a queue-wait-bound 24-job load —
        # and, since the write-coalescing PR, apiserver WRITE PRESSURE:
        # writes-per-converged-job must stay under 65% of the PR 6
        # ≈129 baseline (measured ≈68 coalesced; the 64-create
        # structural floor bounds total reduction), the coalescible
        # events+status share must stay ≥3x under its ≈66 baseline
        # (measured ≈4), parallel and serial write costs must agree
        # (no fan-out write amplification), and the writes column may
        # not regress >10% run-over-run.
        # Retried like the other timing-sensitive tiers. --skip-fleet:
        # the fleet-scale legs run in their own step below, so this one
        # keeps its pre-fleet runtime; both merge their own keys into
        # build/scale_smoke_last.json.
        Step("scale-smoke",
             [PY, "scripts/measure_control_plane.py", "--mode", "scale",
              "--smoke", "--skip-fleet"],
             deps=["operator-integration"], retries=3),
        # Fleet-scale smoke (the 10k-job item, smoke-sized): 1/2/4
        # sharded replicas over a 24-tenant 96-job load with
        # namespace-affinity placement and shard-scoped watch caches.
        # Gates: per-replica watch-cache traffic at 4 replicas <=
        # (1/4 + 25% slack) of the single-replica number, writes-per-
        # converged-job parity (scale never duplicates a write), and the
        # 2->4 replica makespan improving >=15%; ratcheted run-over-run
        # via build/scale_smoke_last.json like the PR 4/7/8 gates. The
        # full 10k-job leg is the same sweep via --replicas/--jobs.
        Step("fleet-scale-smoke",
             [PY, "scripts/measure_control_plane.py", "--mode", "scale",
              "--smoke", "--fleet-only"],
             deps=["shard-failover"], retries=3),
        # Fleet digital twin tier (docs/design/fleet_simulation.md): the
        # trace-driven discrete-event simulator that runs the REAL
        # admission/autoscaler/sharding stack on ONE virtual clock —
        # clock-injection audit, seeded trace/scenario determinism, the
        # checked-in storm corpus replaying byte-identically, and the
        # fleet-level invariants (conservation, aggregate exactly-once,
        # lost-wakeup, fleet-wide capacity). The 100k x 1k-tenant leg
        # is @slow.
        Step("fleet-sim",
             pytest + ["tests/test_fleetsim.py", "-m", "not slow"],
             deps=["admission-chaos"]),
        # The composed-storm smoke gate: 5k jobs / 64 tenants through
        # capacity revocation + slice preemption + a lease steal on a
        # 4-shard ring, 3 runs byte-equal, every invariant sweep green,
        # virtual-time compression >=100x (zero wall-clock sleeps),
        # wall time ratcheted via build/fleetsim_smoke_last.json.
        Step("fleet-sim-smoke",
             [PY, "scripts/measure_control_plane.py", "--mode",
              "fleet-sim", "--smoke"],
             deps=["fleet-sim"], retries=3),
        # Tracing tier (docs/design/tracing.md): deterministic-ID span
        # timelines + apiserver request accounting — Tracer semantics,
        # the accounting proxy's 1:1 pass-through, the /tracez and
        # /readyz handlers, and the acceptance property: a seeded chaos
        # run on fake clocks replays BOTH fault log and span sequence
        # byte-identically. The crash/chaos tiers below dump their trace
        # export into build/ on any invariant failure (post-mortem).
        Step("tracing", pytest + ["tests/test_tracing.py"],
             deps=["operator-integration"], retries=2),
        # Seeded chaos tier (docs/design/disruption_handling.md): the
        # controllers under deterministic fault schedules — write
        # conflicts/errors, watch drops, slice-host preemptions — with
        # FIXED seeds so a red run replays locally from the seed alone.
        # The long randomized sweep stays behind `-m slow` (tier-1 speed);
        # retried like the other timing-sensitive tiers (the rate-limited
        # retry waits are wall-clock-coupled under parallel CI load).
        # test_stall.py is the gang-liveness half of the tier: seeded hang
        # injection (frozen heartbeats / frozen rendezvous) with the same
        # fixed-seed / slow-sweep split.
        Step("chaos-seeded",
             pytest + ["tests/test_chaos.py", "tests/test_disruption.py",
                       "tests/test_stall.py", "-m", "not slow"],
             deps=["operator-integration"], retries=2),
        # Multislice chaos tier (docs/design/failure_modes.md §12):
        # slice-scoped failure domains under seeded schedules — a
        # preempted slice restarts ALONE (surviving slices UID-stable,
        # trace-audited teardown confinement), coordinator/quorum loss
        # escalates to exactly one counted world restart, two-slice
        # concurrent loss without a quorum bound counts each slice once
        # (the flat model's hidden suppression window), per-slice
        # admission preempts one slice on revocation, and the scheduled
        # slice preemption replays fault_log + span_sequence
        # byte-identically. Capability story: the new ScheduledSlice-
        # Preemption plan field defaults empty, so every PR 1-10 seeded
        # schedule replays unchanged.
        Step("multislice-chaos",
             pytest + ["tests/test_multislice_chaos.py", "-m", "not slow"],
             deps=["operator-integration"], retries=2),
        # Gang-admission tier (docs/design/gang_admission.md): the
        # capacity-aware admission layer under seeded contention —
        # quota'd queueing, priority preemption through the counted
        # disruption protocol (exactly-once across the crash window),
        # bounded backfill with the aging starvation bound, the seeded
        # capacity-revocation fault with byte-identical fault_log +
        # span_sequence replay, and the PodGroup/admission lifecycle
        # hygiene regressions. Plus the admissibility-index tier: the
        # mechanism unit pins (watermarks, capacity-epoch skip, the
        # version-keyed capacity cache, per-policy prune fallback) and
        # the schedule-equivalence property — randomized paired traces
        # through the indexed and full-scan arbiters for every policy,
        # byte-equal decision logs and observable state at every step.
        Step("admission-chaos",
             pytest + ["tests/test_admission.py", "tests/test_policies.py",
                       "tests/test_admission_index.py",
                       "tests/test_admission_equivalence.py",
                       "-m", "not slow"],
             deps=["operator-integration"], retries=2),
        # Contention smoke (scripts/measure_control_plane.py --mode
        # contention --smoke): under a pool sized for half the submitted
        # jobs — zero quota violations, strict priority order of
        # completions among unquota'd jobs, exactly-once seed preemption,
        # and backfill beating FIFO on makespan by >10% (the measured
        # utilization margin lands in build/contention_smoke_last.json).
        Step("contention-smoke",
             [PY, "scripts/measure_control_plane.py", "--mode", "contention",
              "--smoke"],
             deps=["admission-chaos"], retries=3),
        # Policy matrix (docs/design/gang_admission.md "Policy seam"):
        # the contention comparison scenarios once per admission policy
        # (priority / gavel / drf), each leg gating its own contract —
        # gavel >=10% better effective fleet throughput than the
        # chip-count-greedy default on the mixed-generation pool, drf
        # bounding the dominant-share spread at <=1.5x the declared
        # weight ratio while staying work-conserving vs the hard-quota
        # baseline, and check_admission_invariants green under every
        # policy. Each leg merge-writes only its own key into
        # build/contention_policies_last.json (the per-policy ratchet).
        # Depends on contention-smoke (not just admission-chaos): both
        # steps read-modify-write the same ratchet file, and the legs
        # must not interleave with the full table's write. The gavel/
        # drf legs deliberately re-run their own in-process priority
        # baselines (co-load cancels, like every other ratio gate) —
        # ~two redundant short scenarios per run, accepted for gate
        # robustness over reading a stale cross-process baseline.
        Step("policy-matrix",
             ["/bin/sh", "-c",
              f"{PY} scripts/measure_control_plane.py --mode contention"
              " --smoke --policy priority"
              f" && {PY} scripts/measure_control_plane.py --mode contention"
              " --smoke --policy gavel"
              f" && {PY} scripts/measure_control_plane.py --mode contention"
              " --smoke --policy drf"],
             deps=["contention-smoke"], retries=3),
        # Autoscaler tier (docs/design/autoscaling.md): the signal-driven
        # gang autoscaler — the pure decision function (grow watermark +
        # hold, checkpoint-coordinated shrink, scale-efficiency guard,
        # dwell/cooldown hysteresis, gavel placement-quality ordering),
        # the resize × admission no-bypass interplay, the heartbeat
        # checkpoint rider, stale-throughput pruning after shrink — plus
        # the seeded chaos half: 3-run byte-equal decision-log replay on
        # fake clocks, ScheduledCapacityRevocation mid-grow with the
        # cooldown anti-flap audited from the resize ledger, and the
        # crash-point sweep over the resize write window proving
        # exactly-once spec patches.
        Step("autoscaler-tier",
             pytest + ["tests/test_autoscaler.py",
                       "tests/test_autoscaler_chaos.py", "-m", "not slow"],
             deps=["admission-chaos"], retries=2),
        # Elasticity smoke (scripts/measure_control_plane.py --mode
        # elasticity --smoke): the seeded contention + capacity-churn
        # scenario scoring autoscaler-on against the best static sizing.
        # Gates: the autoscaler leg beats static on BOTH makespan and
        # the utilization integral, exercises both grow and shrink, and
        # finishes with zero admission/autoscaler invariant violations;
        # margins ratcheted via build/elasticity_smoke_last.json.
        # Depends on contention-smoke: the admission gates must hold
        # before the loop that drives them is scored.
        Step("elasticity-smoke",
             [PY, "scripts/measure_control_plane.py", "--mode",
              "elasticity", "--smoke"],
             deps=["contention-smoke"], retries=2),
        # Recovery tier (docs/design/checkpoint_recovery.md): the
        # fast-recovery plane. recovery-chaos runs the seeded restore-path
        # fault ladder (peer refused / hang / truncated shard / stale
        # snapshot / died mid-transfer / stale manifest / partial owner /
        # torn delta chain: delta-missing-shard and delta-corrupt-shard
        # degrading whole-tree to the newest full — byte-identical
        # fault-log replay) plus the durability barrier units: the
        # listener fires only after the async persist finalizes, a crash
        # in the persist window resumes on the previous checkpoint, the
        # autoscaler's fresh-checkpoint gate can never observe a
        # non-durable step, and the delta-persist suites (chain bound,
        # GC, flag-off layout reads, have-list transfer).
        Step("recovery-chaos",
             pytest + ["tests/test_checkpoint_recovery.py",
                       "tests/test_recovery_chaos.py", "-m", "not slow"],
             deps=["operator-integration"], retries=2),
        # Recovery smoke (scripts/measure_control_plane.py --mode recovery
        # --smoke): storage-vs-peer restore on one durable checkpoint
        # (peer must beat MODELED remote storage), the seeded
        # degraded-fallback ladder replayed byte-identically, operator
        # peer discovery with exactly-once recovery ledgers, the
        # kill->restart->step-resumed wall clock, and the sharded leg:
        # scatter-gather across two strided owners must beat the
        # single-survivor pull (NIC model), its fault scenarios replay
        # byte-equal, the warm-start restore does zero storage reads,
        # and the delta leg: on the partial-update state, delta persist
        # bytes and the have-list warm pull must each stay <= 50% of
        # their full-tree counterpart, byte-equal both ways; margins
        # (incl. delta_persist_fraction / have_list_fraction) ratcheted
        # via build/recovery_smoke_last.json.
        Step("recovery-smoke",
             [PY, "scripts/measure_control_plane.py", "--mode",
              "recovery", "--smoke"],
             deps=["recovery-chaos"], retries=3),
        # Shard-failover tier (docs/design/sharded_control_plane.md): the
        # sharded active-active control plane — ring/coordinator protocol
        # units, two-manager split/steal/handback integration, and the
        # ShardFailoverDriver seeded scenarios (replica dies mid-gang-
        # restart, survivor steals the shard, exactly-once ledgers +
        # span-order audit across the migration; lease-steal and
        # delayed-renew contested-claim windows). Fixed seeds,
        # byte-reproducible; the randomized shard sweep rides chaos-sweep.
        # (+ the shard-scoped watch-cache tier: scope filtering, claim
        # prime / release teardown, scoped serving fallbacks, live
        # resize protocol + adoption barrier, namespace-affinity ring.)
        Step("shard-failover",
             pytest + ["tests/test_sharding.py", "tests/test_shard_failover.py",
                       "tests/test_watchcache_scope.py",
                       "-m", "not slow"],
             deps=["operator-integration"], retries=2),
        # Crash tier (docs/design/crash_consistency.md): the controller
        # itself dies at seeded CrashPoints (before/after-write variants)
        # and a cold-started replacement must converge every job with the
        # structural invariants (testing/invariants.py) green and all
        # three restart ledgers exactly-once; plus the stuck-terminating
        # force-delete escalation end-to-end. Fixed seeds here,
        # byte-reproducible from the seed alone; the randomized crash
        # sweep rides chaos-sweep below.
        Step("crash-seeded",
             pytest + ["tests/test_crash_failover.py",
                       "tests/test_stuck_terminating.py", "-m", "not slow"],
             deps=["operator-integration"], retries=2),
        # The full randomized sweeps, serialized after the fixed seeds.
        Step("chaos-sweep",
             pytest + ["tests/test_chaos.py", "tests/test_stall.py",
                       "tests/test_crash_failover.py",
                       "tests/test_shard_failover.py", "-m", "slow"],
             deps=["chaos-seeded", "crash-seeded", "shard-failover"],
             retries=2),
        # Residency under sustained churn (VERDICT r4 #6): ~10 min of
        # create/churn/succeed/delete waves over the HTTP backend with two
        # leader-elected replicas; asserts the RSS plateau, reconcile p90,
        # and a mid-soak leader failover losing zero jobs. Runs after the
        # stress tier so a broken control plane fails fast there first.
        # retries=2 for the same reason as the e2e tiers: the wave-drain
        # waits (not the p90 bound, which already budgets co-load) are
        # timing-sensitive under the DAG's parallel compile storms.
        Step("soak", pytest + ["tests/test_soak.py"],
             deps=["concurrency-stress"], retries=2),
        # The llama2-7b bench branch end to end (selection via --model,
        # sharded init, timing loop) on the 8-device CPU mesh with the
        # layer-shrink knob — so the first v5e-32 run is not this code
        # path's maiden execution (VERDICT r2 weak #7). Asserts the one
        # JSON line parses and carries the 7B config name.
        Step("bench-7b-path", [PY, "ci/check_bench_7b.py"], deps=["workload"]),
        # Multi-config bench ratchet (docs/design/workload_performance.md):
        # the FULL suite (headline + native-loader + moe + bert
        # secondaries) CPU-shrunk via TF_OPERATOR_BENCH_LAYERS, checked
        # against ci/bench_floors.json with `--check` — a secondary that
        # errors or vanishes fails CI here, and the SAME check gates real
        # MFU floors per config on the TPU runner (cpu floors are 0.0:
        # CPU MFU is noise; the cpu gate is structure + error-free-ness).
        # 2 host devices so the expert-over-fsdp MoE sharding path is
        # exercised, not just single-device replication.
        Step("bench-smoke",
             ["/bin/sh", "-c",
              "JAX_PLATFORMS=cpu"
              " XLA_FLAGS=--xla_force_host_platform_device_count=2"
              " TF_OPERATOR_BENCH_LAYERS=2"
              " JAX_COMPILATION_CACHE_DIR=/tmp/jax-ci-compile-cache"
              " JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=10"
              f" {PY} bench.py --model llama-400m --suite full"
              " --steps 3 --warmup 1 --check"],
             deps=["workload"], retries=2, timeout=1800),
        # Multi-process throughput-parity e2e (tentpole (c) of the
        # overlapped-pipeline PR): a 2-process CPU world formed purely
        # from the operator-injected mesh env must hold per-chip step
        # time within the documented tolerance of single-process over
        # the same mesh — the control-plane env contract proven on the
        # measured training path (DevicePrefetch through the
        # multi-process input seam included). Timing-sensitive under
        # parallel CI load, hence retried.
        Step("throughput-parity",
             pytest + ["tests/test_throughput_parity.py", "-m", "slow"],
             deps=["workload"], retries=2),
        # Packaging (reference sdk/python/setup.py): the distribution must
        # install and expose the console script. --no-deps/--no-build-isolation
        # because CI runs air-gapped with every dependency preinstalled.
        Step("package-install",
             ["/bin/sh", "-c",
              f"{PY} -m pip install -e . --no-deps --no-build-isolation -q"
              " && tf-operator-tpu --help >/dev/null"],
             deps=["build"]),
    ]
