"""Pallas TPU kernel for rotary position embedding (RoPE).

Why a kernel for an elementwise op: the jnp formulation
(`split` on the last dim + f32 upcast + `concatenate`) forces lane-dim
shuffles and several HBM round-trips of the [b, s, h, d] activation per
application — measured at ~30% of the whole train step on v5e (rope runs
on q AND k, every layer, forward, remat-recompute, and backward). Here
each block is rotated entirely in VMEM: one HBM read + one write of x per
call, rotation math in f32 on VMEM-resident vectors, output cast back to
the input dtype. Numerics match the jnp path bit-for-bit up to bf16
rounding (same f32 math).

Backward: RoPE is a per-pair rotation matrix R(θ); its VJP is rotation by
-θ (the transpose). The custom VJP reuses the same kernel with negated
sin — no residuals beyond the (tiny) tables.

Layout contract: x [b, s, h, d] with cos/sin [s, d/2] fp32. The kernel
grid is (b, s_blocks); each program rotates a [block_s, h, d] slab.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref):
    x = x_ref[0].astype(jnp.float32)  # [block_s, h, d]
    cos = cos_ref[...][:, None, :]  # [block_s, 1, d/2]
    sin = sin_ref[...][:, None, :]
    half = x.shape[-1] // 2
    x1 = x[..., :half]
    x2 = x[..., half:]
    o_ref[0] = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(o_ref.dtype)


def _block_s(s: int, h: int, d: int, want: int) -> int:
    # VMEM budget: Mosaic materializes ~4-5 f32 copies of the slab on the
    # kernel stack (upcast, halves, products, concat) plus double-buffered
    # IO; one f32 slab copy must stay well under ~1.5MB to fit the 16MB
    # scoped limit.
    cap = max(8, (3 << 19) // (h * d * 4))
    size = min(want, s, 1 << (cap.bit_length() - 1))  # power of two <= cap
    while s % size:
        size //= 2
    return max(size, 1)


def _rope_raw(x, cos, sin, block_s, interpret):
    b, s, h, d = x.shape
    bs = _block_s(s, h, d, block_s)
    return pl.pallas_call(
        _rope_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=(b, s // bs),
        in_specs=[
            pl.BlockSpec((1, bs, h, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((bs, d // 2), lambda i, j: (j, 0)),
            pl.BlockSpec((bs, d // 2), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bs, h, d), lambda i, j: (i, j, 0, 0)),
        interpret=interpret,
    )(x, cos, sin)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def rope_pallas(x, cos, sin, block_s: int = 512, interpret: bool = False):
    """Rotate pairs (x[..., :d/2], x[..., d/2:]) by position-dependent
    angles. x: [b, s, h, d]; cos/sin: [s, d/2] fp32."""
    return _rope_raw(x, cos, sin, block_s, interpret)


def _rope_fwd(x, cos, sin, block_s, interpret):
    return _rope_raw(x, cos, sin, block_s, interpret), (cos, sin)


def _rope_bwd(block_s, interpret, res, g):
    cos, sin = res
    # R(-θ): the rotation transpose. cos/sin gradients are not needed
    # (tables are position functions, not parameters).
    return _rope_raw(g, cos, -sin, block_s, interpret), None, None


rope_pallas.defvjp(_rope_fwd, _rope_bwd)
