"""Attention ops: Pallas flash attention with an XLA fallback.

`flash_attention(q, k, v, causal=True)` takes [batch, seq, heads, head_dim]
(BSHD) and returns the same. On TPU it lowers to a Pallas kernel that
streams K/V blocks through VMEM with an online softmax (no s×s score
materialization in HBM); elsewhere it falls back to a fused XLA einsum path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _repeat_kv(q, k, v):
    groups = q.shape[2] // k.shape[2]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    return k, v


def xla_attention(q, k, v, causal: bool = True, bias=None):
    """Reference implementation: einsum + fp32 softmax (fused by XLA).
    `bias` is an optional additive fp32 score bias broadcastable to
    [b, h, s_q, s_k] (padding masks etc.)."""
    k, v = _repeat_kv(q, k, v)
    head_dim = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(head_dim).astype(jnp.float32)
    if bias is not None:
        scores = scores + bias
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@functools.cache
def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


@functools.cache
def _flash_blocks() -> tuple:
    """Kernel block sizes, env-overridable for tuning sweeps
    (TF_OPERATOR_FLASH_BLOCK_Q/K). Defaults chosen by measurement on v5e
    (llama-400m, seq 2048): see BASELINE.md perf notes."""
    import os

    return (
        int(os.environ.get("TF_OPERATOR_FLASH_BLOCK_Q", "1024")),
        int(os.environ.get("TF_OPERATOR_FLASH_BLOCK_K", "1024")),
    )


def flash_attention(q, k, v, causal: bool = True):
    """Dispatch: Pallas TPU kernel when available, XLA fallback otherwise."""
    if _on_tpu():
        try:
            from .flash_pallas import flash_attention_pallas

            block_q, block_k = _flash_blocks()
            return flash_attention_pallas(
                q, k, v, causal=causal, block_q=block_q, block_k=block_k
            )
        except ImportError:
            pass
    return xla_attention(q, k, v, causal=causal)
