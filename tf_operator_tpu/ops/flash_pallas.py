"""Pallas TPU flash attention (forward).

Blockwise attention with an online softmax: K/V stream through VMEM one
block at a time while running max/denominator/accumulator live in scratch,
so the s×s score matrix never exists in HBM. The QKᵀ and PV contractions are
MXU matmuls; accumulation is fp32 regardless of input dtype.

Grid layout: (batch, q_heads, q_blocks, k_blocks) with the K dimension
innermost — TPU grids execute the last axis sequentially on one core, which
is exactly what the online-softmax recurrence needs. GQA is free: the K/V
index maps collapse a group of query heads onto their shared KV head, so
grouped heads reread the same K/V block from HBM instead of materializing a
repeated tensor (the XLA fallback in attention.py pays that repeat).

Causal jobs skip whole blocks above the diagonal (`pl.when`), halving the
work; the diagonal block applies an iota row/col mask.

The backward pass deliberately stays with XLA: `flash_attention` in
attention.py is wrapped in `jax.checkpoint` policies by the train step, and
recomputing the XLA forward for the VJP is within a few percent of a
hand-written Pallas backward at the sizes we train (head_dim ≤ 128) —
measured via bench.py before committing to kernel complexity.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Large-but-finite mask value: exp(x - x) on a fully-masked row must not
# produce inf-inf = nan, so we avoid true -inf in the score matrix.
MASK_VALUE = -1e30

# Lane width — m/l scratch rows are padded to one full lane register.
_LANES = 128


def _block_size(want: int, total: int) -> int:
    size = min(want, total)
    while total % size:
        size //= 2
    return max(size, 1)


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_ref,
    l_ref,
    acc_ref,
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    num_k_blocks: int,
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, MASK_VALUE)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * scale
        k = k_ref[0, :, 0, :].astype(jnp.float32)
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        # (block_q, block_k) scores on the MXU.
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            col = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(row >= col, s, MASK_VALUE)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = jnp.broadcast_to(
            alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True), l_ref.shape
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        # Skip blocks strictly above the diagonal: nothing in them is
        # visible to any query row of this block.
        visible = q_start + block_q - 1 >= k_start
        pl.when(visible)(_compute)
    else:
        _compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, :, 0, :] = (acc_ref[...] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention_pallas(
    q,
    k,
    v,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
):
    """BSHD flash attention. q: [b, s_q, h, d]; k/v: [b, s_k, h_kv, d] with
    h % h_kv == 0 (GQA). Returns [b, s_q, h, d] in q.dtype."""
    batch, s_q, heads, head_dim = q.shape
    _, s_k, kv_heads, _ = k.shape
    if heads % kv_heads:
        raise ValueError(f"{heads} query heads not divisible by {kv_heads} KV heads")
    if causal and s_q != s_k:
        raise ValueError("causal flash kernel requires s_q == s_k (self-attention)")
    groups = heads // kv_heads

    block_q = _block_size(block_q, s_q)
    block_k = _block_size(block_k, s_k)
    num_q_blocks = s_q // block_q
    num_k_blocks = s_k // block_k
    grid = (batch, heads, num_q_blocks, num_k_blocks)

    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        scale=1.0 / (head_dim**0.5),
        block_q=block_q,
        block_k=block_k,
        num_k_blocks=num_k_blocks,
    )

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, block_q, 1, head_dim), lambda b, h, qi, ki: (b, qi, h, 0)
            ),
            pl.BlockSpec(
                (1, block_k, 1, head_dim),
                lambda b, h, qi, ki: (b, ki, h // groups, 0),
            ),
            pl.BlockSpec(
                (1, block_k, 1, head_dim),
                lambda b, h, qi, ki: (b, ki, h // groups, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, 1, head_dim), lambda b, h, qi, ki: (b, qi, h, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, head_dim), jnp.float32),  # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
