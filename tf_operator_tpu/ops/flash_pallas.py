"""Pallas TPU flash attention — forward AND backward kernels.

Blockwise attention with an online softmax: K/V stream through VMEM one
block at a time while running max/denominator/accumulator live in scratch,
so the s×s score matrix never exists in HBM. The QKᵀ and PV contractions are
MXU matmuls; accumulation is fp32 regardless of input dtype.

Layout: the public API is BSHD (what the model's DenseGeneral produces), but
the kernels run in BHSD — TPU block shapes must put the two tiled axes
(seq, head_dim) last so blocks are (sublane, lane) = (block_q, head_dim)
aligned; a leading-1 head axis inside the block would violate the (8, 128)
tiling rule. The wrapper transposes at the boundary (a bandwidth-bound copy
XLA fuses with neighbors, negligible next to the attention matmuls).

Forward grid: (batch, q_heads, q_blocks, k_blocks) with the K dimension
innermost — TPU grids execute the last axis sequentially on one core, which
is exactly what the online-softmax recurrence needs. GQA is free: the K/V
index maps collapse a group of query heads onto their shared KV head, so
grouped heads reread the same K/V block from HBM instead of materializing a
repeated tensor (the XLA fallback in attention.py pays that repeat).

The backward is the FlashAttention-2 recurrence, split into two kernels so
each output has a single sequential accumulation axis:

- dQ kernel: same grid as the forward (K innermost); recomputes the block's
  probabilities from the saved per-row logsumexp (no stored s×s matrix),
  then accumulates dQ += dS·K in fp32 scratch.
- dK/dV kernel: grid (batch, kv_heads, k_blocks, group, q_blocks) with the
  query-head group and Q blocks innermost — both axes accumulate into the
  same dK/dV block, which also sums GQA gradients across the grouped query
  heads without a separate reduction pass.

Residuals are (q, k, v, o, lse): O(s) extra memory, the defining flash
property. lse/delta ride along as [b, h, s, 1] so their blocks are
(block_q, 1) — trailing dim equal to the array's, sublane dim 8-aligned.
`delta = rowsum(dO∘O)` is precomputed by XLA (one fused elementwise pass)
rather than a third kernel.

Causal jobs skip whole blocks on the wrong side of the diagonal (`pl.when`),
halving the work in all three kernels; diagonal blocks apply an iota mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax 0.4.x names it TPUCompilerParams; renamed to CompilerParams in 0.5+.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

# Large-but-finite mask value: exp(x - x) on a fully-masked row must not
# produce inf-inf = nan, so we avoid true -inf in the score matrix.
MASK_VALUE = -1e30

# Lane width — m/l scratch rows are padded to one full lane register.
_LANES = 128


def _sds_like(x, shape=None, dtype=None):
    """ShapeDtypeStruct inheriting `x`'s varying-mesh-axes (vma): inside a
    shard_map region (ring attention) pallas_call outputs must declare how
    they vary across the manual axes or tracing rejects them."""
    shape = x.shape if shape is None else shape
    dtype = x.dtype if dtype is None else dtype
    try:
        vma = jax.typeof(x).vma
    except Exception:  # older jax / concrete arrays: no vma concept
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _block_size(want: int, total: int) -> int:
    size = min(want, total)
    while total % size:
        size //= 2
    return max(size, 1)


# --------------------------------------------------------------- forward
def _flash_kernel(
    q_ref,  # [1, 1, block_q, d]
    k_ref,  # [1, 1, block_k, d]
    v_ref,  # [1, 1, block_k, d]
    o_ref,  # [1, 1, block_q, d]
    lse_ref,  # [1, 1, block_q, 1]
    m_ref,
    l_ref,
    acc_ref,
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    num_k_blocks: int,
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, MASK_VALUE)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        # Keep matmul inputs in their native dtype: the MXU contracts
        # bf16 x bf16 -> f32 natively (preferred_element_type); upcasting
        # inputs to f32 first would halve MXU rate and double VMEM traffic.
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        # (block_q, block_k) scores on the MXU, scaled in f32.
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            col = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(row >= col, s, MASK_VALUE)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = jnp.broadcast_to(
            alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True), l_ref.shape
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        # P cast to the input dtype for the PV matmul (FlashAttention-2
        # practice); the accumulator stays f32.
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Skip blocks strictly above the diagonal: nothing in them is
        # visible to any query row of this block.
        visible = q_start + block_q - 1 >= k_start
        pl.when(visible)(_compute)
    else:
        _compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        # Per-row logsumexp — the only softmax statistic the backward needs.
        lse_ref[0, 0, :, :] = m_ref[:, :1] + jnp.log(l_safe)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    """BHSD forward. Returns (o [b,h,s,d], lse [b,h,s,1] fp32)."""
    batch, heads, s_q, head_dim = q.shape
    _, kv_heads, s_k, _ = k.shape
    groups = heads // kv_heads

    block_q = _block_size(block_q, s_q)
    block_k = _block_size(block_k, s_k)
    num_q_blocks = s_q // block_q
    num_k_blocks = s_k // block_k
    grid = (batch, heads, num_q_blocks, num_k_blocks)

    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        scale=1.0 / (head_dim**0.5),
        block_q=block_q,
        block_k=block_k,
        num_k_blocks=num_k_blocks,
    )

    o, lse = pl.pallas_call(
        kernel,
        out_shape=(
            _sds_like(q),
            _sds_like(q, (batch, heads, s_q, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, head_dim), lambda b, h, qi, ki: (b, h, qi, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, head_dim),
                lambda b, h, qi, ki: (b, h // groups, ki, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, head_dim),
                lambda b, h, qi, ki: (b, h // groups, ki, 0),
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, 1, block_q, head_dim), lambda b, h, qi, ki: (b, h, qi, 0)
            ),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, qi, ki: (b, h, qi, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, head_dim), jnp.float32),  # output accumulator
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------- backward
def _dq_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dq_ref,
    dq_acc_ref,
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    num_k_blocks: int,
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        # Matmul inputs stay bf16 (MXU-native, f32 accumulate); only the
        # softmax statistics and dS algebra run in f32.
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]  # (block_q, 1)
        delta = delta_ref[0, 0, :, :]

        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            col = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(row >= col, s, MASK_VALUE)
        p = jnp.exp(s - lse)
        # dP = dO Vᵀ; dS = P ∘ (dP - delta); dQ += scale · dS K.
        # `delta` arrives as rowsum(dO∘O) - dLSE: ∂lse/∂s_j = p_j, so a
        # cotangent on lse adds p∘dlse to dS — folded into the same
        # per-row subtrahend (zero dlse for the plain attention API).
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dq_acc_ref[...] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        visible = q_start + block_q - 1 >= k_start
        pl.when(visible)(_compute)
    else:
        _compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dk_ref,
    dv_ref,
    dk_acc_ref,
    dv_acc_ref,
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    groups: int,
    num_q_blocks: int,
):
    ki = pl.program_id(2)
    g = pl.program_id(3)
    qi = pl.program_id(4)

    @pl.when((g == 0) & (qi == 0))
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]

        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            col = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(row >= col, s, MASK_VALUE)
        p = jnp.exp(s - lse)  # (block_q, block_k)
        # dV += Pᵀ dO
        dv_acc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dS = P ∘ (dP - delta); dK += scale · dSᵀ Q
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk_acc_ref[...] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        visible = q_start + block_q - 1 >= k_start
        pl.when(visible)(_compute)
    else:
        _compute()

    @pl.when((g == groups - 1) & (qi == num_q_blocks - 1))
    def _finalize():
        dk_ref[0, 0, :, :] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_backward(causal, block_q, block_k, interpret, residuals, do,
                    dlse=None):
    q, k, v, o, lse = residuals  # all BHSD / [b,h,s,1]
    batch, heads, s_q, head_dim = q.shape
    _, kv_heads, s_k, _ = k.shape
    groups = heads // kv_heads
    scale = 1.0 / (head_dim**0.5)

    block_q = _block_size(block_q, s_q)
    block_k = _block_size(block_k, s_k)
    num_q_blocks = s_q // block_q
    num_k_blocks = s_k // block_k

    # delta_i = Σ_d dO ∘ O — one fused XLA elementwise pass, [b, h, s, 1].
    # A cotangent on lse (flash_attention_with_lse consumers: the ring's
    # log-sum-exp combine) folds in here: dS = p∘(dP - delta + dlse), so
    # delta := rowsum(dO∘O) - dlse reuses the kernels unchanged.
    delta = jnp.einsum(
        "bhsd,bhsd->bhs",
        do.astype(jnp.float32),
        o.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )[..., None]
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel,
            causal=causal,
            scale=scale,
            block_q=block_q,
            block_k=block_k,
            num_k_blocks=num_k_blocks,
        ),
        out_shape=_sds_like(q),
        grid=(batch, heads, num_q_blocks, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec(
                (1, 1, block_k, head_dim), lambda b, h, qi, ki: (b, h // groups, ki, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, head_dim), lambda b, h, qi, ki: (b, h // groups, ki, 0)
            ),
            pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, head_dim), lambda b, h, qi, ki: (b, h, qi, 0)
        ),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel,
            causal=causal,
            scale=scale,
            block_q=block_q,
            block_k=block_k,
            groups=groups,
            num_q_blocks=num_q_blocks,
        ),
        out_shape=(
            _sds_like(k),
            _sds_like(v),
        ),
        grid=(batch, kv_heads, num_k_blocks, groups, num_q_blocks),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, head_dim),
                lambda b, kh, ki, g, qi: (b, kh * groups + g, qi, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, head_dim), lambda b, kh, ki, g, qi: (b, kh, ki, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, head_dim), lambda b, kh, ki, g, qi: (b, kh, ki, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, head_dim),
                lambda b, kh, ki, g, qi: (b, kh * groups + g, qi, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b, kh, ki, g, qi: (b, kh * groups + g, qi, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b, kh, ki, g, qi: (b, kh * groups + g, qi, 0)
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, 1, block_k, head_dim), lambda b, kh, ki, g, qi: (b, kh, ki, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, head_dim), lambda b, kh, ki, g, qi: (b, kh, ki, 0)
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=(
                "parallel",
                "parallel",
                "parallel",
                "arbitrary",
                "arbitrary",
            ),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    return dq, dk, dv


# ------------------------------------------------------------ public api
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, block_q, block_k, interpret):
    o, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_attention_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    # Named for remat policies: saving "flash_o"/"flash_lse" (plus q/k/v,
    # which are dot outputs any dots-saveable policy keeps) lets the
    # backward replay skip re-running the forward kernel entirely — the
    # VJP's residuals are then all checkpointed (models/llama.py pairs this
    # with its "dots" policy).
    from jax.ad_checkpoint import checkpoint_name

    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


_flash_attention.defvjp(_flash_attention_fwd, _flash_backward)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_lse(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_attention_lse_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    # Same residual naming as the plain variant: under the model's
    # dots+names remat policy these are checkpointed, so the backward
    # replay never re-runs the forward kernel — per RING STEP here, so the
    # saving multiplies by the ring size.
    from jax.ad_checkpoint import checkpoint_name

    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return (o, lse), (q, k, v, o, lse)


def _flash_attention_lse_bwd(causal, block_q, block_k, interpret, residuals,
                             cotangents):
    do, dlse = cotangents
    return _flash_backward(causal, block_q, block_k, interpret, residuals,
                           do, dlse=dlse)


_flash_attention_lse.defvjp(_flash_attention_lse_fwd, _flash_attention_lse_bwd)


def flash_attention_with_lse(q, k, v, causal: bool = True,
                             block_q: int = 1024, block_k: int = 1024,
                             interpret: bool = False):
    """BSHD flash attention that also returns the per-row logsumexp
    ([b, h, s] fp32) and is differentiable in BOTH outputs — the building
    block for blockwise/ring composition, where partial results merge via
    log-sum-exp algebra and the combine weights carry lse gradients."""
    batch, s_q, heads, head_dim = q.shape
    _, s_k, kv_heads, _ = k.shape
    if heads % kv_heads:
        raise ValueError(f"{heads} query heads not divisible by {kv_heads} KV heads")
    if causal and s_q != s_k:
        raise ValueError("causal flash kernel requires s_q == s_k (self-attention)")
    o, lse = _flash_attention_lse(
        jnp.swapaxes(q, 1, 2),
        jnp.swapaxes(k, 1, 2),
        jnp.swapaxes(v, 1, 2),
        causal,
        block_q,
        block_k,
        interpret,
    )
    return jnp.swapaxes(o, 1, 2), lse[..., 0]


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention_pallas(
    q,
    k,
    v,
    causal: bool = True,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool = False,
):
    """BSHD flash attention, differentiable (custom VJP → Pallas backward).
    q: [b, s_q, h, d]; k/v: [b, s_k, h_kv, d] with h % h_kv == 0 (GQA).
    Returns [b, s_q, h, d] in q.dtype."""
    batch, s_q, heads, head_dim = q.shape
    _, s_k, kv_heads, _ = k.shape
    if heads % kv_heads:
        raise ValueError(f"{heads} query heads not divisible by {kv_heads} KV heads")
    if causal and s_q != s_k:
        raise ValueError("causal flash kernel requires s_q == s_k (self-attention)")
    out = _flash_attention(
        jnp.swapaxes(q, 1, 2),
        jnp.swapaxes(k, 1, 2),
        jnp.swapaxes(v, 1, 2),
        causal,
        block_q,
        block_k,
        interpret,
    )
    return jnp.swapaxes(out, 1, 2)
