"""TPU kernels (Pallas) and fused ops.

The compute-path hot ops: flash attention (Pallas TPU kernel), ring
attention for sequence parallelism over the ICI ring, and fused helpers.
Each op degrades to a pure-XLA implementation off-TPU so tests run on the
CPU mesh.
"""

from . import attention

__all__ = ["attention"]
