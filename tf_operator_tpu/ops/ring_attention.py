"""Ring attention: sequence-parallel causal attention over an ICI ring.

Each device holds a sequence shard [b, s_local, h, d] (the `sp` mesh axis).
K/V blocks rotate around the ring via `ppermute` while every device
accumulates its queries' attention with an online (flash-style) softmax —
s_total never materializes on one chip, so context length scales with the
ring size at constant per-device memory. Communication (neighbor ppermute)
overlaps with the block compute; on TPU the permutes ride ICI.

Use under shard_map with the sequence axis mapped to `axis_name`:

    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
        mesh=mesh,
        in_specs=P(("dp","fsdp"), "sp", None, None), ...)

Outside a mapped context (axis missing), falls back to plain causal
attention on the gathered arrays so the same model code runs unsharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .attention import NEG_INF, _repeat_kv, xla_attention


def _block_scores(q, k, scale):
    return jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale


def ring_attention(q, k, v, axis_name: str = "sp", vary_axes=None):
    """Causal ring attention. q,k,v: [b, s_local, h(_kv), d] sequence shards,
    ordered by ring index (shard i holds global positions
    [i*s_local, (i+1)*s_local)). `vary_axes`: every manual (shard_map) axis
    in scope — the loop carry must be marked varying over all of them, not
    just the ring axis, or the fori_loop carry types mismatch. Defaults to
    (axis_name,) for a shard_map mapping only the ring axis."""
    try:
        axis_size = jax.lax.psum(1, axis_name)
    except NameError:
        return xla_attention(q, k, v, causal=True)

    k, v = _repeat_kv(q, k, v)
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    my_idx = jax.lax.axis_index(axis_name)
    q_pos = my_idx * s + jnp.arange(s)  # global positions of my queries

    # Online softmax accumulators (fp32), marked as varying over the ring
    # axis (loop-carry types must match the body outputs, which depend on
    # the mapped q/k/v).
    from ..parallel.mesh import mark_varying

    axes = tuple(vary_axes) if vary_axes else (axis_name,)

    def pvary(x):
        return mark_varying(x, axes)

    o0 = pvary(jnp.zeros((b, s, h, d), jnp.float32))
    l0 = pvary(jnp.zeros((b, h, s), jnp.float32))
    m0 = pvary(jnp.full((b, h, s), NEG_INF, jnp.float32))

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(i, carry):
        o, l, m, k_blk, v_blk = carry
        # After i rotations each device holds the block that started at ring
        # position (my_idx - i) mod axis_size.
        kv_idx = (my_idx - i) % axis_size
        kv_pos = kv_idx * s + jnp.arange(s)

        scores = _block_scores(q, k_blk, scale)  # [b,h,q,k] fp32
        causal = q_pos[:, None] >= kv_pos[None, :]
        scores = jnp.where(causal[None, None], scores, NEG_INF)

        m_blk = jnp.max(scores, axis=-1)  # [b,h,q]
        m_new = jnp.maximum(m, m_blk)
        # Fully-masked blocks produce -inf rows; keep the exp argument finite.
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(causal[None, None], p, 0.0)
        corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - safe_m))

        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk,
                        preferred_element_type=jnp.float32)
        o = o * corr.transpose(0, 2, 1)[..., None] + pv

        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, l, m_new, k_next, v_next

    o, l, m, _, _ = jax.lax.fori_loop(0, axis_size, body, (o0, l0, m0, k, v))
    l = jnp.maximum(l, 1e-30)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def sharded_ring_attention(q, k, v):
    """Ring attention wrapped in its own shard_map over the scoped mesh
    (parallel.mesh.use_mesh), so model code can call it from inside a
    plain-jit train step: activations enter sequence-sharded over `sp`
    (batch over data axes, heads over tp), the ring runs per-shard, and
    XLA stitches the region into the surrounding computation. Falls back
    to full causal attention when no mesh is scoped or it has no sp axis."""
    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import current_mesh
    from ..parallel.sharding import DATA_AXES, _present

    mesh = current_mesh()
    if mesh is None or "sp" not in mesh.shape:
        return xla_attention(q, k, v, causal=True)
    # Batch over the canonical data axes (DATA_AXES includes ep — it doubles
    # as a data axis outside expert compute; a divergent hardcoded tuple
    # here would crash sp+ep meshes at trace time).
    spec = P(*_present(mesh, DATA_AXES, "sp", "tp", None))
    return shard_map(
        partial(
            ring_attention, axis_name="sp", vary_axes=tuple(mesh.axis_names)
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)
