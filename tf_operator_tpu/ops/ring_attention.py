"""Ring attention: sequence-parallel causal attention over an ICI ring.

Each device holds a sequence shard [b, s_local, h, d] (the `sp` mesh axis).
K/V blocks rotate around the ring via `ppermute` while every device
combines per-block attention results with log-sum-exp algebra — s_total
never materializes on one chip, so context length scales with the ring
size at constant per-device memory. Communication (neighbor ppermute)
overlaps with the block compute; on TPU the permutes ride ICI.

The per-block compute is the Pallas flash kernel on TPU
(flash_attention_with_lse — O(s_local) memory inside the block, MXU
matmuls, lse-differentiable for the combine weights), with a fused-XLA
einsum fallback elsewhere. Block visibility is decided per ring step
(`lax.switch`): blocks left of the diagonal are fully visible (no mask
work), the diagonal block runs the causal kernel, blocks right of it are
skipped entirely — the ring-level analog of the kernel's own
block-skipping.

Use under shard_map with the sequence axis mapped to `axis_name`:

    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
        mesh=mesh,
        in_specs=P(("dp","fsdp"), "sp", None, None), ...)

Outside a mapped context (axis missing), falls back to plain causal
attention on the gathered arrays so the same model code runs unsharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .attention import NEG_INF, _on_tpu, _repeat_kv, xla_attention


def _block_attn_xla(q, k_blk, v_blk, causal_mask):
    """Fallback per-block attention -> (o [b,s,h,d] f32 normalized,
    lse [b,h,s] f32). `causal_mask` [s_q, s_k] bool or None (= all
    visible)."""
    d = q.shape[-1]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
    ) * (1.0 / d**0.5)
    if causal_mask is not None:
        scores = jnp.where(causal_mask[None, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [b,h,q]
    safe_m = jnp.where(m == NEG_INF, 0.0, m)
    p = jnp.exp(scores - safe_m[..., None])
    if causal_mask is not None:
        p = jnp.where(causal_mask[None, None], p, 0.0)
    l = p.sum(axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk,
                   preferred_element_type=jnp.float32)
    l_safe = jnp.maximum(l, 1e-30)
    o = o / l_safe.transpose(0, 2, 1)[..., None]
    lse = jnp.where(l > 0, safe_m + jnp.log(l_safe), NEG_INF)
    return o, lse


def _combine(o, lse, o_blk, lse_blk):
    """Merge two normalized partials by log-sum-exp weights. -inf rows
    (nothing visible yet / skipped block) contribute weight zero without
    producing inf-inf NaNs."""
    lse_new = jnp.logaddexp(lse, lse_blk)
    safe = jnp.where(lse_new == NEG_INF, 0.0, lse_new)

    def weight(x):
        w = jnp.exp(jnp.where(x == NEG_INF, NEG_INF, x - safe))
        return w.transpose(0, 2, 1)[..., None]  # [b,h,s] -> [b,s,h,1]

    return o * weight(lse) + o_blk * weight(lse_blk), lse_new


def ring_attention(q, k, v, axis_name: str = "sp", vary_axes=None,
                   block_impl: str = "auto"):
    """Causal ring attention. q,k,v: [b, s_local, h(_kv), d] sequence shards,
    ordered by ring index (shard i holds global positions
    [i*s_local, (i+1)*s_local)). `vary_axes`: every manual (shard_map) axis
    in scope — the loop carry must be marked varying over all of them, not
    just the ring axis, or the fori_loop carry types mismatch. Defaults to
    (axis_name,) for a shard_map mapping only the ring axis.
    `block_impl`: "auto" (flash kernel on TPU, einsum elsewhere), "xla",
    "flash_interpret" (Pallas interpret mode — CPU numerics tests)."""
    try:
        axis_size = jax.lax.psum(1, axis_name)
    except NameError:
        return xla_attention(q, k, v, causal=True)

    k, v = _repeat_kv(q, k, v)
    b, s, h, d = q.shape
    my_idx = jax.lax.axis_index(axis_name)

    if block_impl == "auto":
        block_impl = "flash" if _on_tpu() else "xla"
    interpret = block_impl == "flash_interpret"
    use_flash = block_impl in ("flash", "flash_interpret")

    if use_flash:
        from .flash_pallas import flash_attention_with_lse

        def full_block(k_blk, v_blk):
            o_blk, lse_blk = flash_attention_with_lse(
                q, k_blk, v_blk, causal=False, interpret=interpret
            )
            return o_blk.astype(jnp.float32), lse_blk  # switch branches: one type

        def diag_block(k_blk, v_blk):
            o_blk, lse_blk = flash_attention_with_lse(
                q, k_blk, v_blk, causal=True, interpret=interpret
            )
            return o_blk.astype(jnp.float32), lse_blk
    else:
        causal_mask = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]

        def full_block(k_blk, v_blk):
            return _block_attn_xla(q, k_blk, v_blk, None)

        def diag_block(k_blk, v_blk):
            return _block_attn_xla(q, k_blk, v_blk, causal_mask)

    from ..parallel.mesh import mark_varying

    axes = tuple(vary_axes) if vary_axes else (axis_name,)

    def pvary(x):
        return mark_varying(x, axes)

    def skip_block(k_blk, v_blk):
        # Constants must still carry the manual-axes varying mark or the
        # switch branches' output types disagree with the flash branches'.
        return (
            pvary(jnp.zeros((b, s, h, d), jnp.float32)),
            pvary(jnp.full((b, h, s), NEG_INF, jnp.float32)),
        )

    o0 = pvary(jnp.zeros((b, s, h, d), jnp.float32))
    lse0 = pvary(jnp.full((b, h, s), NEG_INF, jnp.float32))
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(i, carry):
        o, lse, k_blk, v_blk = carry
        # After i rotations each device holds the block that started at ring
        # position (my_idx - i) mod axis_size.
        kv_idx = (my_idx - i) % axis_size
        # 0 = fully visible (kv block strictly left of ours), 1 = diagonal
        # (ours: causal), 2 = strictly right: invisible, skipped.
        mode = jnp.where(kv_idx < my_idx, 0, jnp.where(kv_idx == my_idx, 1, 2))
        o_blk_f, lse_blk = jax.lax.switch(
            mode,
            [full_block, diag_block, skip_block],
            k_blk,
            v_blk,
        )
        o, lse = _combine(o, lse, o_blk_f.astype(jnp.float32), lse_blk)
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, lse, k_next, v_next

    o, lse, _, _ = jax.lax.fori_loop(0, axis_size, body, (o0, lse0, k, v))
    return o.astype(q.dtype)


def sharded_ring_attention(q, k, v):
    """Ring attention wrapped in its own shard_map over the scoped mesh
    (parallel.mesh.use_mesh), so model code can call it from inside a
    plain-jit train step: activations enter sequence-sharded over `sp`
    (batch over data axes, heads over tp), the ring runs per-shard, and
    XLA stitches the region into the surrounding computation. Falls back
    to full causal attention when no mesh is scoped or it has no sp axis."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import is_legacy_shard_map, shard_map
    from ..parallel.mesh import current_mesh
    from ..parallel.sharding import DATA_AXES, _present

    mesh = current_mesh()
    if mesh is None or "sp" not in mesh.shape:
        return xla_attention(q, k, v, causal=True)
    # Batch over the canonical data axes (DATA_AXES includes ep — it doubles
    # as a data axis outside expert compute; a divergent hardcoded tuple
    # here would crash sp+ep meshes at trace time).
    spec = P(*_present(mesh, DATA_AXES, "sp", "tp", None))
    kwargs = {}
    if is_legacy_shard_map():
        # jax 0.4.x: the replication checker mis-types the ring's cond
        # carries ("branches of cond produced mismatched replication
        # types") — upstream's own suggested workaround is check_rep=False;
        # the varying-axes typing that replaces it doesn't exist there.
        kwargs["check_rep"] = False
    return shard_map(
        partial(
            ring_attention, axis_name="sp", vary_axes=tuple(mesh.axis_names)
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **kwargs,
    )(q, k, v)
