"""tf_operator_tpu — a TPU-native training operator.

A from-scratch rebuild of the capabilities of savvihub/tf-operator (the
Kubeflow TF/training operator, reference at /root/reference) designed
TPU-first:

- CRD-style job kinds (``TFJob``, ``PyTorchJob``, ``MXJob``, ``XGBoostJob``
  and the new ``JAXJob``) with the reference's defaulting + validation
  semantics (reference: pkg/apis/*/v1).
- A reconciler engine (re-owning what the reference imports from
  kubeflow/common v0.3.4: ReconcileJobs / ReconcilePods / ReconcileServices,
  expectations, run-policy enforcement — reference: §2.9 of SURVEY.md).
- TPU pod-slices as the all-or-nothing gang unit, and JAX/XLA bootstrap env
  (``jax.distributed`` coordinator, ``TPU_WORKER_ID``, mesh coordinates)
  instead of GPU-era rendezvous env.
- A JAX/Flax workload tier (models/, ops/, parallel/, train/) providing the
  example workloads and the performance-bearing compute path: SPMD over
  ``jax.sharding.Mesh`` via ``jit``/``shard_map``, Pallas TPU kernels for
  attention, ring-attention sequence parallelism for long context.

The control plane is pure Python (the reference control plane is pure Go; it
contains no native code — SURVEY.md §2), while the compute path lowers to
XLA/Pallas on TPU.
"""

__version__ = "0.1.0"
