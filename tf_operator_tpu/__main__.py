"""``python -m tf_operator_tpu`` — run the operator process."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
