"""Peer-restore shard server: survivors serve host snapshots over HTTP.

The storage round-trip dominates cold recovery: a recreated slice (PR 11
slice-local restart) or a grown gang pulls every byte of the train state
back from the checkpoint bucket even though the surviving ranks hold the
identical step in host memory (the snapshot half of snapshot-then-persist,
train/checkpoint.py). This module closes that gap: each rank runs a tiny
read-only HTTP server over its newest :class:`HostSnapshot` and advertises
``host:port`` through the heartbeat-lease peer-address rider; a restoring
rank fetches shards from any advertised survivor and only falls back to
storage when no peer can serve (train/restore.py owns that ladder).

Deliberately minimal: stdlib ``ThreadingHTTPServer``, numpy ``.npy``
encoding (self-describing dtype/shape), sha256 checksums end-to-end. Two
endpoints:

- ``GET /v1/meta``  -> ``{step, model_meta, shards: {name: {checksum,
  bytes, dtype, shape}}}``
- ``GET /v1/shard/<name>?step=N`` -> raw ``.npy`` bytes with ``X-Step`` /
  ``X-Checksum`` headers; 409 when the snapshot rotated past N mid-fetch
  (the client restarts against fresh meta), 503 when no snapshot exists.
- ``GET /v1/bundle?step=N`` -> every shard in one response, framed as
  ``[u32 name-len][name][u64 payload-len][payload]`` repeating in sorted
  name order. One request instead of one per leaf — request overhead is
  what lets the storage path catch up on small states, and the frames are
  written straight from the per-shard cache (no bundled second copy).
  ``&have=<name>:<checksum>,...`` (names URL-quoted) is the HAVE-LIST:
  the restoring rank advertises the shards it already holds warm, and
  the server omits every frame whose (name, checksum) matches — the
  transfer moves only the delta. Matching is per NAME, not per bare
  checksum: duplicate content (all-zero optimizer shards) shares a
  checksum across distinct names, and bare-checksum filtering would
  wrongly drop names the client does NOT hold. Older servers that
  predate the parameter simply ignore it and serve the full bundle —
  the client uses the frames it needs and discards the rest, so
  mixed-version fleets stay correct (bytes un-saved, bytes never wrong).
- ``GET /v1/manifest`` -> the meta payload plus ``owned``: the sorted
  shard names THIS survivor claims under the slice-scoped ownership
  partition (derived from the slice-local checkpoint topology — each
  survivor slice prefers to serve its stride of the sorted name space).
  A scatter-gather client (train/restore.py, ``sharded=True``) plans one
  fetch per shard against the claiming owners so the transfer splits
  across survivor NICs instead of serializing through one. Ownership is
  a PLANNING HINT, not an ACL: every survivor holds the full host
  snapshot (the per-slice checkpoint streams carry the whole replicated
  state), so ``/v1/shard`` serves any name — which is what lets the
  client re-plan orphaned shards onto non-owners when an owner dies
  mid-transfer, and lets a manifest-speaking client converge against a
  bundle-era peer that predates this endpoint (404 -> treated as a
  full owner).

The server reads the snapshot through a callable seam (usually
``CheckpointManager.host_snapshot``) so it always serves the newest step
without any registration dance, and snapshots are treated as immutable
once published.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import struct
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

log = logging.getLogger(__name__)


# ------------------------------------------------------------- wire format
def flatten_tree(tree: Any) -> Dict[str, Any]:
    """Name every leaf by its joined key path ("/params/dense/kernel") —
    the shard namespace both ends of the wire share. Names derive from the
    pytree structure, so identical TrainState definitions (the peer
    contract) produce identical names."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(path): leaf for path, leaf in flat}


def encode_shard(array) -> bytes:
    """numpy .npy serialization: self-describing (dtype+shape ride along),
    zero-copy-ish, and immune to pickle's cross-version hazards."""
    import numpy as np

    buf = io.BytesIO()
    np.save(buf, np.asarray(array), allow_pickle=False)
    return buf.getvalue()


def decode_shard(payload: bytes):
    import numpy as np

    return np.load(io.BytesIO(payload), allow_pickle=False)


def shard_checksum(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


def partition_shard_names(names, slice_index: int, num_slices: int):
    """The slice-scoped ownership partition: slice k of n owns every nth
    name of the SORTED shard namespace starting at k. Strided (not
    contiguous blocks) so parameter and optimizer leaves — which sort
    adjacently per layer — spread evenly across owners by bytes, and pure
    (both ends of the wire, and the restore planner, derive the identical
    partition from the same inputs)."""
    if num_slices <= 1:
        return sorted(names)
    return sorted(names)[slice_index % num_slices::num_slices]


def parse_bundle(body: bytes) -> Dict[str, bytes]:
    """Split a ``/v1/bundle`` body back into ``{name: payload}``. Raises
    OSError on any framing damage (truncation mid-frame) so the restore
    ladder classifies it like any other transport failure."""
    out: Dict[str, bytes] = {}
    off = 0
    try:
        while off < len(body):
            (nlen,) = struct.unpack_from(">I", body, off)
            off += 4
            name = body[off:off + nlen].decode("utf-8")
            off += nlen
            (plen,) = struct.unpack_from(">Q", body, off)
            off += 8
            if off + plen > len(body):
                raise OSError("bundle truncated mid-payload")
            out[name] = body[off:off + plen]
            off += plen
    except (struct.error, UnicodeDecodeError) as err:
        raise OSError(f"bundle framing damaged: {err}") from err
    return out


class _SnapshotView:
    """One snapshot, encoded + checksummed once and cached — meta requests
    and shard fetches from several restoring peers must not re-hash a
    multi-GB tree per request."""

    def __init__(self, snapshot) -> None:
        import numpy as np

        self.step = int(snapshot.step)
        self.model_meta = snapshot.model_meta
        flat = flatten_tree(snapshot.tree)
        self.payloads: Dict[str, bytes] = {
            name: encode_shard(leaf) for name, leaf in flat.items()
        }
        self.checksums = {
            name: shard_checksum(data) for name, data in self.payloads.items()
        }
        self.meta = {
            "step": self.step,
            "model_meta": self.model_meta,
            "shards": {
                name: {
                    "checksum": self.checksums[name],
                    "bytes": len(self.payloads[name]),
                    "dtype": str(np.asarray(leaf).dtype),
                    "shape": list(np.asarray(leaf).shape),
                }
                for name, leaf in flat.items()
            },
        }


class SnapshotShardServer:
    """Read-only shard server over a snapshot source callable.

    ``source()`` returns the newest HostSnapshot (or None); the view cache
    re-encodes only when the step advances. ``address`` is the
    ``host:port`` string to advertise via the heartbeat rider.

    ``owned`` is the slice-scoped ownership seam for ``/v1/manifest``: a
    pure function from the full sorted shard-name list to the subset this
    survivor claims (None = claims everything — a single-survivor or
    non-sliced topology is a full owner). It shapes only the manifest's
    ``owned`` list; serving is never restricted by it (module doc)."""

    def __init__(self, source: Callable[[], Optional[Any]],
                 host: str = "127.0.0.1", port: int = 0,
                 advertise_host: Optional[str] = None,
                 owned: Optional[Callable[[Any], Any]] = None) -> None:
        self._source = source
        self._owned = owned
        self._lock = threading.Lock()
        self._view: Optional[_SnapshotView] = None
        self._advertise_host = advertise_host
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — stdlib name
                log.debug("shard-server %s", fmt % args)

            def _send(self, code: int, body: bytes,
                      content_type: str = "application/json",
                      headers: Optional[Dict[str, str]] = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib name
                try:
                    server._handle(self)
                except BrokenPipeError:
                    pass  # restoring peer gave up mid-transfer; its retry
                    # logic owns the consequence
                except Exception:  # noqa: BLE001 — one bad request must
                    # not take down the serving thread pool
                    log.exception("shard-server request failed")
                    try:
                        self._send(500, b"{}")
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="shard-server", daemon=True
        )

    # ------------------------------------------------------------ control
    def start(self) -> "SnapshotShardServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host = self._advertise_host or self._httpd.server_address[0]
        return f"{host}:{self.port}"

    def warm(self) -> None:
        """Build the view for the current snapshot off the request path.
        Wired to the checkpoint durability listener so the encode+hash cost
        is paid once at save time, not on the critical restore path of the
        first peer that asks."""
        threading.Thread(
            target=self._current_view, name="shard-server-warm", daemon=True
        ).start()

    # ------------------------------------------------------------ serving
    def _current_view(self) -> Optional[_SnapshotView]:
        snapshot = self._source()
        if snapshot is None:
            return None
        with self._lock:
            if self._view is None or self._view.step != int(snapshot.step):
                self._view = _SnapshotView(snapshot)
            return self._view

    def _handle(self, request) -> None:
        parsed = urllib.parse.urlparse(request.path)
        view = self._current_view()
        if parsed.path == "/v1/meta":
            if view is None:
                request._send(503, json.dumps(
                    {"error": "no-snapshot"}).encode())
                return
            request._send(200, json.dumps(view.meta).encode())
            return
        if parsed.path == "/v1/manifest":
            if view is None:
                request._send(503, json.dumps(
                    {"error": "no-snapshot"}).encode())
                return
            names = sorted(view.payloads)
            owned = names if self._owned is None else sorted(
                self._owned(names))
            manifest = dict(view.meta)
            manifest["owned"] = owned
            request._send(200, json.dumps(manifest).encode())
            return
        if parsed.path.startswith("/v1/shard/"):
            if view is None:
                request._send(503, json.dumps(
                    {"error": "no-snapshot"}).encode())
                return
            name = urllib.parse.unquote(parsed.path[len("/v1/shard/"):])
            query = urllib.parse.parse_qs(parsed.query)
            want_step = query.get("step", [None])[0]
            if want_step is not None and int(want_step) != view.step:
                # Snapshot rotated while the client iterated its shard
                # list; a mixed-step reassembly would be silent corruption.
                request._send(409, json.dumps(
                    {"error": "step-rotated", "step": view.step}).encode())
                return
            payload = view.payloads.get(name)
            if payload is None:
                request._send(404, json.dumps(
                    {"error": "unknown-shard"}).encode())
                return
            request._send(
                200, payload, content_type="application/octet-stream",
                headers={"X-Step": str(view.step),
                         "X-Checksum": view.checksums[name]},
            )
            return
        if parsed.path == "/v1/bundle":
            if view is None:
                request._send(503, json.dumps(
                    {"error": "no-snapshot"}).encode())
                return
            query = urllib.parse.parse_qs(parsed.query)
            want_step = query.get("step", [None])[0]
            if want_step is not None and int(want_step) != view.step:
                request._send(409, json.dumps(
                    {"error": "step-rotated", "step": view.step}).encode())
                return
            names = sorted(view.payloads)
            have_raw = query.get("have", [None])[0]
            if have_raw:
                # Have-list filter (module doc): skip frames the client
                # already holds byte-identically. Unparseable entries are
                # ignored (never a reason to fail the transfer).
                # (parse_qs already URL-decoded the value — the client
                # quotes each name exactly once.)
                have: Dict[str, str] = {}
                for item in have_raw.split(","):
                    name, sep, checksum = item.rpartition(":")
                    if sep and name:
                        have[name] = checksum
                names = [
                    n for n in names
                    if have.get(n) != view.checksums[n]
                ]
            total = sum(
                4 + len(n.encode("utf-8")) + 8 + len(view.payloads[n])
                for n in names
            )
            request.send_response(200)
            request.send_header("Content-Type", "application/octet-stream")
            request.send_header("Content-Length", str(total))
            request.send_header("X-Step", str(view.step))
            request.end_headers()
            for n in names:
                encoded = n.encode("utf-8")
                request.wfile.write(struct.pack(">I", len(encoded)))
                request.wfile.write(encoded)
                request.wfile.write(struct.pack(">Q", len(view.payloads[n])))
                request.wfile.write(view.payloads[n])
            return
        request._send(404, json.dumps({"error": "unknown-path"}).encode())


def start_shard_server(checkpoint_manager, host: str = "127.0.0.1",
                       port: int = 0, slice_index: Optional[int] = None,
                       num_slices: Optional[int] = None) -> SnapshotShardServer:
    """Start a shard server over a CheckpointManager's host snapshot and
    return it (``.address`` is the rider payload for record_peer_address).
    Each durable save warms the view cache so restoring peers never pay
    the encode+hash cost inline. With a slice topology
    (``slice_index``/``num_slices``), the manifest's owned set is
    SLICE-DERIVED when the manager can report what its own (PR 11
    per-slice) checkpoint stream physically persisted
    (``persisted_shard_names`` — the delta-layout manifest names): a
    slice claims exactly what it holds durable, so the claim tracks
    reality through resharding instead of assuming a static stride.
    Name striding (partition_shard_names) stays the fallback for
    managers without a delta layout. Either way owned is a planning
    hint, never an ACL — serving is unrestricted (module doc)."""
    owned = None
    if slice_index is not None and num_slices is not None and num_slices > 1:
        idx, n = int(slice_index), int(num_slices)

        def owned(names, _idx=idx, _n=n, _mgr=checkpoint_manager):  # noqa: F811
            persisted = getattr(_mgr, "persisted_shard_names", None)
            if persisted is not None:
                try:
                    held = set(persisted())
                except Exception:  # noqa: BLE001 — a broken derivation
                    # must degrade to the stride, not kill the manifest
                    held = set()
                derived = [name for name in sorted(names) if name in held]
                if derived:
                    return derived
            return partition_shard_names(names, _idx, _n)

    server = SnapshotShardServer(checkpoint_manager.host_snapshot,
                                 host=host, port=port, owned=owned).start()
    try:
        checkpoint_manager.add_durability_listener(lambda _step: server.warm())
    except AttributeError:
        pass  # bare snapshot sources (tests) have no listener seam
    return server
