"""In-container runtime shim.

Tier 2 of the build (SURVEY.md §7): the analog of the reference workloads'
TF_CONFIG parsing (examples/tensorflow/dist-mnist/dist_mnist.py:102-143),
done once here instead of in every training script — injected env →
``jax.distributed.initialize`` → device mesh.
"""

from .heartbeat import record_progress
from .tpu_init import Topology, global_mesh, initialize, topology_from_env, tpu_init

__all__ = [
    "Topology",
    "global_mesh",
    "initialize",
    "record_progress",
    "topology_from_env",
    "tpu_init",
]
