"""Turn operator-injected env into a live JAX distributed runtime + mesh.

The operator's contract ends at env injection and DNS-stable service names
(SURVEY.md §3.5); this module is the in-container half. Where the reference
workload does ``json.loads(os.environ["TF_CONFIG"])`` then
``tf.train.Server(cluster, job_name, task_index)``
(examples/tensorflow/dist-mnist/dist_mnist.py:102-143), a JAXJob container
does::

    from tf_operator_tpu.runtime import tpu_init
    topo, mesh = tpu_init()          # rendezvous + mesh, one call
    ... pjit over mesh ...

Env consumed (produced by bootstrap/jaxdist.py):
  JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID,
  TPU_WORKER_ID, TPU_WORKER_HOSTNAMES, TPU_ACCELERATOR_TYPE, TPU_TOPOLOGY,
  JAX_NUM_SLICES, JAX_SLICE_INDEX, JAX_MESH_SPEC, MEGASCALE_*.

Everything degrades to single-process local mode when the env is absent, so
the same training script runs unmodified on a dev box.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..bootstrap import jaxdist

_initialized = False

# Workload-side opt-in (template env, NOT operator-injected): form one
# jax.distributed world PER SLICE instead of one global world. Each
# slice's processes rendezvous among themselves — coordinator = the
# slice's first host (TPU_WORKER_HOSTNAMES is already slice-local),
# process id = TPU_WORKER_ID — so slices train as independent worlds
# (DiLoCo-style loosely-coupled replicas, or the CPU e2e stand-in for
# megascale's DCN layer: a slice-local gang restart re-rendezvouses
# only the lost slice while the surviving slices' worlds keep running).
ENV_SLICE_LOCAL_WORLD = "JAX_SLICE_LOCAL_WORLD"


def _force_declared_platform() -> None:
    """Make an explicit JAX_PLATFORMS env choice stick.

    Some images register an out-of-process TPU PJRT plugin from
    sitecustomize that wins over a plain env override; routing the value
    through jax.config (before first device use) restores the declared
    behaviour, so a CPU dev/e2e run cannot silently grab a real chip."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax

    if jax.config.jax_platforms != plat:
        jax.config.update("jax_platforms", plat)


@dataclass(frozen=True)
class Topology:
    """The operator-declared view of this process and its slice."""

    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0
    worker_id: int = 0  # libtpu host ordinal within the slice
    worker_hostnames: tuple = ()
    accelerator_type: str = ""
    tpu_topology: str = ""
    num_slices: int = 1
    slice_index: int = 0
    mesh_axes: Dict[str, int] = field(default_factory=dict)
    # True when JAX_SLICE_LOCAL_WORLD remapped this topology to a
    # per-slice world: num_processes/process_id/coordinator_address are
    # slice-scoped, and the mesh gets no DCN `slice` axis.
    slice_world: bool = False

    @property
    def distributed(self) -> bool:
        return self.num_processes > 1 and self.coordinator_address is not None

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def topology_from_env(env: Optional[Dict[str, str]] = None) -> Topology:
    """Parse the injected env; absent vars mean single-process local mode."""
    env = os.environ if env is None else env

    def _int(key: str, default: int) -> int:
        raw = env.get(key)
        try:
            return int(raw) if raw is not None else default
        except ValueError:
            return default

    mesh_axes: Dict[str, int] = {}
    raw_mesh = env.get(jaxdist.ENV_MESH_SPEC)
    if raw_mesh:
        try:
            parsed = json.loads(raw_mesh)
            if isinstance(parsed, dict):
                mesh_axes = {str(k): int(v) for k, v in parsed.items()}
        except (ValueError, TypeError):
            mesh_axes = {}

    hostnames = tuple(
        h for h in env.get(jaxdist.ENV_TPU_WORKER_HOSTNAMES, "").split(",") if h
    )
    coordinator = env.get(jaxdist.ENV_COORDINATOR_ADDRESS) or None
    num_processes = _int(jaxdist.ENV_NUM_PROCESSES, 1)
    process_id = _int(jaxdist.ENV_PROCESS_ID, 0)
    worker_id = _int(jaxdist.ENV_TPU_WORKER_ID, 0)
    num_slices = _int(jaxdist.ENV_NUM_SLICES, 1)
    slice_world = (
        str(env.get(ENV_SLICE_LOCAL_WORLD, "")).lower() in ("1", "true", "yes")
        and num_slices > 1
        and bool(hostnames)
        and coordinator is not None
    )
    if slice_world:
        # Per-slice world: this slice's processes rendezvous among
        # themselves. TPU_WORKER_HOSTNAMES already lists exactly the
        # slice's hosts in rank order, so the slice coordinator is its
        # first entry, and the in-slice process id is the libtpu worker
        # ordinal. The port is offset by the slice index: jax's
        # coordinator service binds ALL interfaces, so N slice
        # coordinators sharing one dev host (the CPU e2e tier) would
        # otherwise contend for one port and cross-wire the worlds'
        # barriers; on a real fleet each coordinator has its own host
        # and the offset is merely unused port space.
        slice_index = _int(jaxdist.ENV_SLICE_INDEX, 0)
        try:
            port = int(coordinator.rsplit(":", 1)[-1]) + slice_index
        except ValueError:
            port = coordinator.rsplit(":", 1)[-1]
        coordinator = f"{hostnames[0]}:{port}"
        num_processes = len(hostnames)
        process_id = worker_id
    return Topology(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        worker_id=worker_id,
        worker_hostnames=hostnames,
        accelerator_type=env.get(jaxdist.ENV_TPU_ACCELERATOR_TYPE, ""),
        tpu_topology=env.get(jaxdist.ENV_TPU_TOPOLOGY, ""),
        num_slices=num_slices,
        slice_index=_int(jaxdist.ENV_SLICE_INDEX, 0),
        mesh_axes=mesh_axes,
        slice_world=slice_world,
    )


def initialize(
    topology: Optional[Topology] = None,
    *,
    timeout_seconds: Optional[int] = None,
) -> Topology:
    """Rendezvous this process: ``jax.distributed.initialize`` against the
    coordinator the operator published. Idempotent; a no-op single-process.

    Must run before first device use — JAX's backend is frozen at first
    touch, same constraint the reference's TF gRPC server has at
    tf.train.Server construction time.
    """
    global _initialized
    _force_declared_platform()
    # Gang liveness: start renewing this pod's heartbeat BEFORE the
    # rendezvous blocks — a worker wedged inside
    # jax.distributed.initialize must still prove the process is alive
    # (rendezvousDeadlineSeconds measures first-heartbeat, not first
    # step). No-op without the operator-injected heartbeat env.
    from . import heartbeat as _heartbeat

    _heartbeat.start_from_env()
    topo = topology or topology_from_env()
    # Local mode must NOT latch: a pre-env probe call (import-time init, a
    # notebook) would otherwise make the later real rendezvous a silent no-op.
    if not topo.distributed or _initialized:
        return topo

    import jax

    # CPU dev/e2e federation: multi-process computations on the CPU
    # backend need the gloo collectives implementation selected BEFORE
    # backend init, or every cross-process collective dies with
    # "Multiprocess computations aren't implemented on the CPU backend"
    # (jax 0.4.x; newer versions default to gloo and drop the knob —
    # hence best-effort).
    if (os.environ.get("JAX_PLATFORMS") or "").startswith("cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass

    kwargs = dict(
        coordinator_address=topo.coordinator_address,
        num_processes=topo.num_processes,
        process_id=topo.process_id,
    )
    if timeout_seconds is not None:
        kwargs["initialization_timeout"] = timeout_seconds
    jax.distributed.initialize(**kwargs)
    _initialized = True
    return topo


def global_mesh(topology: Optional[Topology] = None):
    """Build the Mesh the job declared (JAX_MESH_SPEC), over all devices.

    Falls back to a pure-FSDP mesh (the LLM-training default) when the job
    declared no axes. A multislice job gets its leading DCN ``slice`` axis
    whether declared or not.
    """
    import jax

    from ..parallel.mesh import MeshSpec, make_mesh, standard_mesh

    topo = topology or topology_from_env()
    n = jax.device_count()
    axes = dict(topo.mesh_axes)
    # A slice-local world never gets the DCN axis: its devices are ONE
    # slice's, and a declared global mesh falls back via the size check.
    if topo.num_slices > 1 and not topo.slice_world and "slice" not in axes:
        axes["slice"] = topo.num_slices
    if not axes:
        return standard_mesh(n)
    declared = 1
    for size in axes.values():
        declared *= size
    if declared != n:
        if jax.devices()[0].platform == "tpu":
            # On real hardware a size mismatch is a misconfigured job
            # (e.g. per-slice axes on a multislice spec), not a dev run —
            # training on a silently different layout would be a sharding
            # regression, so refuse.
            raise ValueError(
                f"declared mesh {axes} has {declared} devices but the TPU "
                f"backend sees {n}; fix the job's mesh/numSlices"
            )
        # CPU dev run of a TPU-sized spec: fall back rather than crash.
        import warnings

        warnings.warn(
            f"declared mesh {axes} needs {declared} devices, backend has {n}; "
            f"falling back to a pure-FSDP mesh (CPU dev mode)",
            stacklevel=2,
        )
        return standard_mesh(n)
    return make_mesh(MeshSpec(axes))


def tpu_init(*, timeout_seconds: Optional[int] = None):
    """One-call bootstrap: returns (Topology, Mesh)."""
    topo = initialize(timeout_seconds=timeout_seconds)
    return topo, global_mesh(topo)
