"""Workload-side TPU profiling hooks.

The reference operator exposes Go pprof on its monitoring port
(cmd/tf-operator.v1/main.go:21,39-50) but offers nothing for the training
processes themselves (SURVEY.md §5.1: "no per-job profiling"). On TPU the
valuable trace is the XLA one — jax.profiler captures device timelines,
HLO cost attribution, and host<->device transfers viewable in TensorBoard
or Perfetto.

Two triggers, both zero-cost when unused:

- Step window (env-driven): the operator (or user) sets
  ``TPU_PROFILE_DIR`` [+ ``TPU_PROFILE_START_STEP`` / ``TPU_PROFILE_NUM_STEPS``]
  on the pod; the train loop calls ``step_profiler(step)`` once per step.
- On-demand: ``install_sigusr1_handler()`` arms SIGUSR1; signaling the
  process (kubectl exec kill -USR1 1) captures a fixed-duration trace —
  the moral analog of hitting pprof on a live server.
"""

from __future__ import annotations

import logging
import os
import signal
import threading

_log = logging.getLogger(__name__)

ENV_PROFILE_DIR = "TPU_PROFILE_DIR"
ENV_PROFILE_START_STEP = "TPU_PROFILE_START_STEP"
ENV_PROFILE_NUM_STEPS = "TPU_PROFILE_NUM_STEPS"

_state = threading.Lock()
# Which trigger owns the live jax trace (only one can exist process-wide):
# None, "window" (env-driven step window), or "capture" (SIGUSR1). Separate
# ownership, not a bare bool — otherwise the step loop's stop branch would
# truncate an on-demand capture in flight (and vice versa).
_owner: str | None = None


def profile_window() -> tuple:
    """(dir, start_step, num_steps) from env, or (None, 0, 0)."""
    out_dir = os.environ.get(ENV_PROFILE_DIR)
    if not out_dir:
        return None, 0, 0
    start = int(os.environ.get(ENV_PROFILE_START_STEP, "10"))
    num = int(os.environ.get(ENV_PROFILE_NUM_STEPS, "5"))
    return out_dir, start, num


def step_profiler(step: int) -> None:
    """Call once per train step; starts/stops the env-declared window.
    No-op (one int compare) when TPU_PROFILE_DIR is unset."""
    global _owner
    out_dir, start, num = profile_window()
    if out_dir is None:
        return
    import jax

    with _state:
        if step == start and _owner is None:
            _log.info("profiler: starting trace -> %s (steps %d..%d)", out_dir, start, start + num)
            jax.profiler.start_trace(out_dir)
            _owner = "window"
        elif _owner == "window" and step >= start + num:
            jax.profiler.stop_trace()
            _owner = None
            _log.info("profiler: trace written to %s", out_dir)


def capture(out_dir: str, seconds: float = 3.0) -> None:
    """Fixed-duration trace, usable from any thread. Skipped (not queued)
    if any trace is already live."""
    import time

    import jax

    global _owner
    with _state:
        if _owner is not None:
            return
        _owner = "capture"
        jax.profiler.start_trace(out_dir)
    try:
        time.sleep(seconds)
    finally:
        with _state:
            jax.profiler.stop_trace()
            _owner = None
        _log.info("profiler: on-demand trace written to %s", out_dir)


def install_sigusr1_handler(out_dir: str = "/tmp/tpu-profile", seconds: float = 3.0) -> None:
    """SIGUSR1 -> capture a trace in a background thread (signal-safe:
    the handler only spawns the thread)."""

    def _handler(signum, frame):
        threading.Thread(
            target=capture, args=(out_dir, seconds), daemon=True, name="tpu-profile"
        ).start()

    signal.signal(signal.SIGUSR1, _handler)
