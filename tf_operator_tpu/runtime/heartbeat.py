"""In-container gang-liveness heartbeat (the worker half of stall detection).

The dominant unhandled failure on TPU pod-slices is the replica that wedges
*silently*: every pod reports Running while a collective is deadlocked, an
ICI link is dead under a live kubelet, or the gang never leaves rendezvous.
The kubelet cannot see any of that — only the process can prove its own
liveness. This module is that proof: a daemon thread started from
``tpu_init()`` renews a per-pod heartbeat Lease, and training loops may
additionally call :func:`record_progress` so the control plane (and
debuggers reading the Lease) see the last completed step.

The renewal runs through the same ``Cluster`` seam leader election uses
(``core/leaderelection.py``): full-object optimistic-concurrency writes on a
``coordination.k8s.io/v1`` Lease, so the identical protocol works against
KubeCluster (a real apiserver or the HTTP stub), the in-memory cluster, and
— via the ``TPU_HEARTBEAT_FILE`` bridge the process cluster's kubelet-analog
translates — live subprocesses in the e2e tier. A Conflict means a
concurrent writer touched OUR lease (nothing else should); the round is
simply dropped and the next tick re-reads.

Everything degrades to a no-op when the env is absent: a dev-box run starts
no thread, exactly like the rest of the bootstrap contracts.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

from ..bootstrap.heartbeat import (
    ENV_HEARTBEAT_FILE,
    ENV_HEARTBEAT_INTERVAL,
    ENV_HEARTBEAT_LEASE,
    ENV_HEARTBEAT_NAMESPACE,
)
from ..core.constants import (
    ANNOTATION_HEARTBEAT_CKPT,
    ANNOTATION_HEARTBEAT_PEER,
    ANNOTATION_HEARTBEAT_RESTORE,
    ANNOTATION_HEARTBEAT_STEP,
    ANNOTATION_HEARTBEAT_TPS,
)

log = logging.getLogger(__name__)


# ------------------------------------------------------------- publication
def _progress_annotations(step: Optional[int],
                          tokens_per_sec: Optional[float],
                          checkpoint_step: Optional[int] = None,
                          peer_addr: Optional[str] = None,
                          restore: Optional[str] = None
                          ) -> Dict[str, str]:
    """Lease annotations for the workload-reported progress payload."""
    out: Dict[str, str] = {}
    if step is not None:
        out[ANNOTATION_HEARTBEAT_STEP] = str(step)
    if tokens_per_sec is not None:
        out[ANNOTATION_HEARTBEAT_TPS] = f"{float(tokens_per_sec):.1f}"
    if checkpoint_step is not None:
        out[ANNOTATION_HEARTBEAT_CKPT] = str(int(checkpoint_step))
    if peer_addr is not None:
        out[ANNOTATION_HEARTBEAT_PEER] = str(peer_addr)
    if restore is not None:
        out[ANNOTATION_HEARTBEAT_RESTORE] = str(restore)
    return out


def publish_heartbeat(cluster, namespace: str, name: str, identity: str,
                      step: Optional[int] = None,
                      tokens_per_sec: Optional[float] = None,
                      checkpoint_step: Optional[int] = None,
                      peer_addr: Optional[str] = None,
                      restore: Optional[str] = None,
                      clock=time.time) -> bool:
    """One heartbeat renewal through the Cluster seam. True iff the beat
    landed; False on a lost optimistic-concurrency round (retry next tick).

    Same idiom as ClusterLeaseLock.try_acquire: GET (NotFound -> create),
    mutate the read object carrying its resourceVersion, full-object PUT —
    a concurrent writer's bump turns ours into a Conflict. Transient API
    errors also just skip the beat: the operator's staleness clock is
    generous (several intervals per deadline) precisely so one blip never
    reads as a stall.
    """
    from ..cluster.base import Conflict, NotFound
    from ..core.leaderelection import _format_microtime

    now = clock()
    try:
        lease = cluster.get_lease(namespace, name)
    except NotFound:
        lease = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"namespace": namespace, "name": name},
            "spec": {
                "holderIdentity": identity,
                "acquireTime": _format_microtime(now),
                "renewTime": _format_microtime(now),
                "leaseDurationSeconds": 0,
            },
        }
        annotations = _progress_annotations(step, tokens_per_sec,
                                            checkpoint_step, peer_addr,
                                            restore)
        if annotations:
            lease["metadata"]["annotations"] = annotations
        try:
            cluster.create_lease(lease)
            return True
        except Conflict:
            return False  # racing first beat; the winner's renewal stands
        except Exception:
            log.debug("heartbeat create failed", exc_info=True)
            return False
    except Exception:
        log.debug("heartbeat read failed", exc_info=True)
        return False

    spec = lease.setdefault("spec", {})
    spec["holderIdentity"] = identity
    spec["renewTime"] = _format_microtime(now)
    new_annotations = _progress_annotations(step, tokens_per_sec,
                                            checkpoint_step, peer_addr,
                                            restore)
    if new_annotations:
        meta = lease.setdefault("metadata", {})
        annotations = meta.get("annotations") or {}
        annotations.update(new_annotations)
        meta["annotations"] = annotations
    try:
        cluster.update_lease(lease)
        return True
    except Conflict:
        return False
    except Exception:
        log.debug("heartbeat renew failed", exc_info=True)
        return False


def write_heartbeat_file(path: str, seq: int, step: Optional[int],
                         tokens_per_sec: Optional[float] = None,
                         checkpoint_step: Optional[int] = None,
                         peer_addr: Optional[str] = None,
                         restore: Optional[str] = None) -> None:
    """The file half of the process-tier bridge: one JSON object, replaced
    wholesale each beat (write-to-temp + rename so the reader never sees a
    torn write). ``seq`` strictly increases so the bridge can tell a fresh
    beat from a re-read."""
    tmp = f"{path}.tmp"
    payload = {"seq": seq, "step": step, "ts": time.time()}
    if tokens_per_sec is not None:
        payload["tokens_per_sec"] = float(tokens_per_sec)
    if checkpoint_step is not None:
        payload["checkpoint_step"] = int(checkpoint_step)
    if peer_addr is not None:
        payload["peer_addr"] = str(peer_addr)
    if restore is not None:
        payload["restore"] = str(restore)
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


def read_heartbeat_file(path: str) -> Optional[dict]:
    """Reader half (LocalProcessCluster's kubelet-analog). None when the
    file is absent or torn — never raises into the reaper loop."""
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) and "seq" in data else None


# --------------------------------------------------------------- publisher
class HeartbeatPublisher:
    """Daemon renewal loop around one sink. ``record_progress`` updates the
    step (and, optionally, the workload-reported throughput) AND wakes the
    loop so a long sleep never delays the proof of the step that just
    completed; ``record_checkpoint`` rides the same wake path for the
    checkpoint-landed signal the autoscaler's coordinated shrink waits on."""

    def __init__(self, sink: Callable[[int, Optional[int], Optional[float]], None],
                 interval: float):
        self._sink = sink
        # Sink arity resolved ONCE here, not per beat via TypeError
        # probing: a wider-arity sink that raises TypeError internally
        # must not be re-invoked with its side effects doubled. Legacy
        # 3-arg (pre-checkpoint-rider) and 4-arg (pre-recovery-rider)
        # sinks keep working, minus the riders they predate. The full
        # payload is 6 positional: (seq, step, tokens_per_sec,
        # checkpoint_step, peer_addr, restore).
        import inspect

        try:
            params = inspect.signature(sink).parameters.values()
            positional = [
                p for p in params
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            ]
            var_positional = any(p.kind == p.VAR_POSITIONAL for p in params)
            if var_positional or len(positional) >= 6:
                self._sink_args = 6
            elif len(positional) >= 4:
                self._sink_args = 4
            else:
                self._sink_args = 3
        except (TypeError, ValueError):  # builtins/C callables: assume current
            self._sink_args = 6
        self.interval = max(0.05, float(interval))
        self._step: Optional[int] = None
        self._tokens_per_sec: Optional[float] = None
        self._checkpoint_step: Optional[int] = None
        self._peer_addr: Optional[str] = None
        self._restore: Optional[str] = None
        self._seq = 0
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HeartbeatPublisher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tpu-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def record_progress(self, step: Optional[int] = None,
                        tokens_per_sec: Optional[float] = None) -> None:
        if step is not None:
            self._step = int(step)
        if tokens_per_sec is not None:
            self._tokens_per_sec = float(tokens_per_sec)
        self._wake.set()

    def record_checkpoint(self, step: int) -> None:
        """A checkpoint for ``step`` is DURABLE (call only after the save
        returns): published as the checkpoint-step lease annotation. The
        autoscaler treats a strictly increasing value as 'a fresh
        checkpoint landed' — the precondition for applying a proposed
        elastic shrink."""
        self._checkpoint_step = int(step)
        self._wake.set()

    def record_peer_address(self, addr: Optional[str]) -> None:
        """This rank's shard-server ``host:port`` (runtime/shard_server.py):
        published as the peer-restore lease annotation so the operator can
        hand survivor addresses to a recreated slice. None clears nothing —
        the last advertised address stands until the lease is GC'd with
        the pod."""
        if addr is not None:
            self._peer_addr = str(addr)
        self._wake.set()

    def record_restore(self, path: str, cause: str, seconds: float,
                       bytes_moved: Optional[int] = None) -> None:
        """Which restore-ladder leg won and why (train/restore.py outcome):
        published as the compact ``path:cause:seconds[:bytes]`` annotation
        the controller turns into training_restore_total/seconds (and
        training_restore_bytes_total when the 4th field rides — peer
        paths that metered their wire bytes)."""
        rider = f"{path}:{cause}:{float(seconds):.3f}"
        if bytes_moved is not None:
            rider += f":{int(bytes_moved)}"
        self._restore = rider
        self._wake.set()

    def beat_once(self) -> None:
        """One synchronous beat (also the loop body): never raises — a
        broken sink must not take the training process down with it."""
        self._seq += 1
        try:
            if self._sink_args >= 6:
                self._sink(self._seq, self._step, self._tokens_per_sec,
                           self._checkpoint_step, self._peer_addr,
                           self._restore)
            elif self._sink_args >= 4:
                self._sink(self._seq, self._step, self._tokens_per_sec,
                           self._checkpoint_step)
            else:
                self._sink(self._seq, self._step, self._tokens_per_sec)
        except Exception:  # noqa: BLE001 — liveness must never kill training
            log.debug("heartbeat sink failed", exc_info=True)

    def stop(self) -> None:
        self._stopped.set()
        self._wake.set()

    def _run(self) -> None:
        while not self._stopped.is_set():
            self.beat_once()
            self._wake.wait(self.interval)
            self._wake.clear()


# ------------------------------------------------------------- module API
_active: Optional[HeartbeatPublisher] = None
_lock = threading.Lock()


def start_from_env(cluster=None,
                   env: Optional[Dict[str, str]] = None) -> Optional[HeartbeatPublisher]:
    """Start (once) the heartbeat thread the injected env describes.

    Sink resolution, most-specific first:
    - ``TPU_HEARTBEAT_FILE`` -> file bridge (process e2e tier);
    - explicit ``cluster`` -> direct Lease renewals through that seam
      (unit tests; embedded runtimes);
    - in-cluster (``KUBERNETES_SERVICE_HOST``) -> a KubeCluster against the
      real apiserver, service-account auth;
    - anything else -> no-op (dev box).

    Returns the active publisher, or None when the env opts out. Idempotent:
    repeated calls (tpu_init() then an explicit initialize()) share one
    thread.
    """
    global _active
    env = os.environ if env is None else env
    lease = env.get(ENV_HEARTBEAT_LEASE)
    if not lease:
        return None
    with _lock:
        if _active is not None:
            return _active
        namespace = env.get(ENV_HEARTBEAT_NAMESPACE, "default")
        try:
            interval = float(env.get(ENV_HEARTBEAT_INTERVAL, "5"))
        except ValueError:
            interval = 5.0
        identity = env.get("HOSTNAME") or lease
        file_path = env.get(ENV_HEARTBEAT_FILE)
        if file_path:
            def sink(seq: int, step: Optional[int],
                     tokens_per_sec: Optional[float] = None,
                     checkpoint_step: Optional[int] = None,
                     peer_addr: Optional[str] = None,
                     restore: Optional[str] = None,
                     _path=file_path) -> None:
                write_heartbeat_file(_path, seq, step,
                                     tokens_per_sec=tokens_per_sec,
                                     checkpoint_step=checkpoint_step,
                                     peer_addr=peer_addr,
                                     restore=restore)
        else:
            if cluster is None and "KUBERNETES_SERVICE_HOST" in env:
                try:
                    from ..cluster.kube import KubeCluster

                    cluster = KubeCluster(namespace=namespace)
                except Exception:  # no creds/unreachable: stay silent
                    log.debug("in-cluster heartbeat setup failed",
                              exc_info=True)
                    return None
            if cluster is None:
                return None

            def sink(seq: int, step: Optional[int],
                     tokens_per_sec: Optional[float] = None,
                     checkpoint_step: Optional[int] = None,
                     peer_addr: Optional[str] = None,
                     restore: Optional[str] = None, _c=cluster,
                     _ns=namespace, _name=lease, _id=identity) -> None:
                publish_heartbeat(_c, _ns, _name, _id, step=step,
                                  tokens_per_sec=tokens_per_sec,
                                  checkpoint_step=checkpoint_step,
                                  peer_addr=peer_addr,
                                  restore=restore)

        _active = HeartbeatPublisher(sink, interval).start()
        return _active


def record_progress(step: Optional[int] = None,
                    tokens_per_sec: Optional[float] = None) -> None:
    """Training-loop API: prove liveness now (and record the step; and,
    optionally, the measured training throughput — exported by the
    operator as the ``training_workload_tokens_per_sec`` gauge, the
    utilization signal autoscaling consumes). A no-op when no publisher is
    active, so workloads can call it unconditionally — the same script
    runs with and without the operator."""
    publisher = _active
    if publisher is not None:
        publisher.record_progress(step, tokens_per_sec=tokens_per_sec)


def record_checkpoint(step: int) -> None:
    """Training-loop API: a checkpoint for ``step`` is durable on disk.
    Published as the checkpoint-step lease annotation (mirrored into the
    file bridge on the process tier) — the signal a checkpoint-coordinated
    elastic shrink waits for before any worker is taken away. A no-op
    without an active publisher, like record_progress."""
    publisher = _active
    if publisher is not None:
        publisher.record_checkpoint(step)


def record_peer_address(addr: Optional[str]) -> None:
    """Training-loop API: this rank serves peer-restore shards at ``addr``
    ("host:port"). Published as the peer-address lease annotation the
    operator reads when building a recreated slice's pods. A no-op without
    an active publisher, like record_progress."""
    publisher = _active
    if publisher is not None:
        publisher.record_peer_address(addr)


def record_restore(path: str, cause: str, seconds: float,
                   bytes_moved: Optional[int] = None) -> None:
    """Training-loop API: this rank restored via ``path`` ("peer" /
    "storage" / "none") for ``cause`` in ``seconds``, moving
    ``bytes_moved`` wire bytes when the peer path metered them. Published
    as the restore-outcome lease annotation for operator metrics. A no-op
    without an active publisher, like record_progress."""
    publisher = _active
    if publisher is not None:
        publisher.record_restore(path, cause, seconds, bytes_moved)


def stop() -> None:
    """Tear down the active publisher (tests; graceful shutdown)."""
    global _active
    with _lock:
        if _active is not None:
            _active.stop()
            _active = None
