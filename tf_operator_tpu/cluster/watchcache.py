"""Shared, delta-fed watch cache: the informer-store seam for backends
without their own reflector.

The reference serves every hot-path read from client-go informer caches;
KubeCluster reproduces that with its reflector + store. The in-memory
backend (the scale benchmark's fabric, most test tiers, and the chaos
substrate) had no equivalent: every sync paid a fresh LIST for pods and
services and a GET for the job — pressure the accounting proxy shows
scaling linearly with sync count. This module closes that gap:

- `SharedWatchCache` subscribes to the backend's watch streams ONCE
  (pods, services, plus each job kind a controller registers) and
  maintains a store per resource, fed purely by deltas, with a
  resourceVersion bookmark per resource (the highest rv applied — the
  resume watermark a reconnecting reflector would use).
- `WatchCacheCluster` is the per-controller proxy: list_pods /
  list_services / get_pod / get_service / get_job are served from the
  shared store (deep-copied, claim-view filtered); every write — and
  every read the cache does not model, get_job_uncached above all —
  passes through to the inner chain untouched.

Shared by design: one manager's N framework controllers fan their syncs
over ONE store, so the backend sees one initial LIST per resource per
process instead of one per controller per sync.

Ordering contract: the cache registers its watch handlers BEFORE any
controller registers its own (the manager builds the cache first; the
per-kind registration happens inside FrameworkController.__init__ before
_watch()), and backends dispatch handlers in registration order — so by
the time an expectation is observed or a sync is enqueued for an event,
the store already reflects it. That is what lets the expectations gate
keep its exact meaning over cache-served lists.

Priming uses the reflector's watch-before-list trick: handlers are live
before the initial LIST, the merge keeps whichever copy carries the
higher resourceVersion, and deletions observed mid-prime leave
tombstones so the LIST snapshot can never resurrect an object the
deltas already removed.

Capability-gated via `Cluster.supports_watch_cache`: only backends whose
watch delivery is ordered and lossless opt in (the in-memory simulator).
The chaos seam pins it off — its seeded watch-drop injection would
poison a delta-fed store permanently — which also keeps every seeded
fault tier's read sequence byte-identical to the pre-cache engine.
"""

from __future__ import annotations

import copy
import threading
from typing import Dict, List, Optional, Tuple

from . import base
from .base import ADDED, DELETED, MODIFIED, NotFound, SYNC

_UPSERTS = (ADDED, MODIFIED, SYNC)


def _meta(obj) -> Tuple[str, str, int]:
    """(namespace, name, rv) of a typed object or a job dict."""
    if isinstance(obj, dict):
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace", "default")
        name = meta.get("name", "")
        raw = meta.get("resourceVersion") or "0"
    else:
        ns = obj.metadata.namespace
        name = obj.metadata.name
        raw = obj.metadata.resource_version or "0"
    try:
        rv = int(raw)
    except ValueError:
        rv = 0
    return ns, name, rv


def _copy(obj):
    return obj.deep_copy() if hasattr(obj, "deep_copy") else copy.deepcopy(obj)


class SharedWatchCache:
    """Delta-fed store over one backend, shared by every controller of a
    process. Construct it ONCE, before any controller registers watches
    of its own (the manager does; see the module docstring's ordering
    contract)."""

    def __init__(self, backend, namespace: Optional[str] = None):
        self.backend = backend
        # Cache scope (None = every namespace): the LIST that primes a
        # resource uses it, and reads outside the scope fall through.
        self.namespace = namespace or None
        self._lock = threading.Lock()
        self._stores: Dict[str, Dict[Tuple[str, str], object]] = {}
        self._bookmarks: Dict[str, int] = {}
        self._primed: set = set()
        # (resource, ns, name) -> rv of a DELETED delta observed before
        # that resource finished priming: the merge must not resurrect.
        self._tombstones: Dict[Tuple[str, str, str], int] = {}
        self._registered: set = set()
        for resource in ("pods", "services"):
            self._register(resource)

    # -------------------------------------------------------------- feeds
    def _register(self, resource: str) -> None:
        with self._lock:
            if resource in self._registered:
                return
            self._registered.add(resource)
            self._stores.setdefault(resource, {})
        self.backend.watch(resource, self._handler(resource))

    def register_kind(self, kind: str) -> None:
        """Subscribe + prime the store for one job kind's CR objects
        (idempotent; each FrameworkController registers its own kind)."""
        self._register(kind)
        self._prime(kind, lambda: self.backend.list_jobs(kind, self.namespace))

    def _handler(self, resource: str):
        def on_event(event_type: str, obj) -> None:
            ns, name, rv = _meta(obj)
            if self.namespace is not None and ns != self.namespace:
                # Out-of-scope delta: covers() guarantees it could never
                # be served, so storing it would only grow the store with
                # other tenants' churn, unbounded.
                return
            with self._lock:
                store = self._stores[resource]
                if event_type == DELETED:
                    store.pop((ns, name), None)
                    if resource not in self._primed:
                        self._tombstones[(resource, ns, name)] = rv
                elif event_type in _UPSERTS:
                    current = store.get((ns, name))
                    if current is None or _meta(current)[2] <= rv:
                        store[(ns, name)] = obj
                self._bookmarks[resource] = max(
                    self._bookmarks.get(resource, 0), rv
                )

        return on_event

    def _prime(self, resource: str, lister) -> None:
        """Initial LIST, merged under the watch-before-list rule: deltas
        already flowing win on rv, tombstoned deletions never resurrect."""
        with self._lock:
            if resource in self._primed:
                return
        listed = lister()
        with self._lock:
            if resource in self._primed:
                return
            store = self._stores[resource]
            for obj in listed:
                ns, name, rv = _meta(obj)
                if self._tombstones.get((resource, ns, name), -1) >= rv:
                    continue
                current = store.get((ns, name))
                if current is None or _meta(current)[2] < rv:
                    store[(ns, name)] = obj
                self._bookmarks[resource] = max(
                    self._bookmarks.get(resource, 0), rv
                )
            self._primed.add(resource)
            self._tombstones = {
                k: v for k, v in self._tombstones.items() if k[0] != resource
            }

    def ensure_primed(self, resource: str) -> None:
        if resource == "pods":
            self._prime(resource, lambda: self.backend.list_pods(
                namespace=self.namespace))
        elif resource == "services":
            self._prime(resource, lambda: self.backend.list_services(
                namespace=self.namespace))
        else:
            self._prime(resource, lambda: self.backend.list_jobs(
                resource, self.namespace))

    # -------------------------------------------------------------- reads
    def bookmark(self, resource: str) -> int:
        """Highest resourceVersion applied to `resource`'s store — the
        watermark a resuming watch would start from."""
        with self._lock:
            return self._bookmarks.get(resource, 0)

    def primed(self, resource: str) -> bool:
        with self._lock:
            return resource in self._primed

    def covers(self, namespace: Optional[str]) -> bool:
        """Whether a read scoped to `namespace` can be served from this
        cache's scope (an all-namespace cache covers everything; a scoped
        cache only its own namespace)."""
        return self.namespace is None or (
            namespace is not None and namespace == self.namespace
        )

    def list_objects(self, resource: str, namespace=None, labels=None,
                     owner_uid=None) -> list:
        self.ensure_primed(resource)
        with self._lock:
            snapshot = list(self._stores[resource].values())
        out = []
        for obj in snapshot:
            ns, _, _ = _meta(obj)
            if namespace is not None and ns != namespace:
                continue
            if not isinstance(obj, dict) and not base.matches_claim_view(
                obj, labels, owner_uid
            ):
                continue
            out.append(_copy(obj))
        return out

    def get_object(self, resource: str, namespace: str, name: str):
        self.ensure_primed(resource)
        with self._lock:
            obj = self._stores[resource].get((namespace, name))
        if obj is None:
            raise NotFound(f"{resource} {namespace}/{name}")
        return _copy(obj)


class WatchCacheCluster:
    """Per-controller proxy serving the hot-path reads from a
    SharedWatchCache; everything else — writes, watches, uncached reads —
    delegates to `inner` (the controller's accounted/throttled chain), so
    a cache hit costs zero apiserver requests, exactly like an informer
    read in the reference."""

    def __init__(self, inner, cache: SharedWatchCache, kind: str):
        self._inner = inner
        self._cache = cache
        self._kind = kind
        cache.register_kind(kind)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    # ------------------------------------------------------------- reads
    def list_pods(self, namespace=None, labels=None, owner_uid=None):
        if not self._cache.covers(namespace):
            return self._inner.list_pods(
                namespace=namespace, labels=labels, owner_uid=owner_uid)
        return self._cache.list_objects(
            "pods", namespace=namespace, labels=labels, owner_uid=owner_uid)

    def list_services(self, namespace=None, labels=None, owner_uid=None):
        if not self._cache.covers(namespace):
            return self._inner.list_services(
                namespace=namespace, labels=labels, owner_uid=owner_uid)
        return self._cache.list_objects(
            "services", namespace=namespace, labels=labels,
            owner_uid=owner_uid)

    def get_pod(self, namespace: str, name: str):
        if not self._cache.covers(namespace):
            return self._inner.get_pod(namespace, name)
        return self._cache.get_object("pods", namespace, name)

    def get_service(self, namespace: str, name: str):
        if not self._cache.covers(namespace):
            return self._inner.get_service(namespace, name)
        return self._cache.get_object("services", namespace, name)

    def get_job(self, kind: str, namespace: str, name: str) -> dict:
        # Only the proxy's own kind is cached (each controller registers
        # exactly its kind); a cross-kind read (SDK helpers) delegates.
        if kind != self._kind or not self._cache.covers(namespace):
            return self._inner.get_job(kind, namespace, name)
        return self._cache.get_object(kind, namespace, name)

    def list_jobs(self, kind: str, namespace=None):
        if kind != self._kind or not self._cache.covers(namespace):
            return self._inner.list_jobs(kind, namespace)
        return self._cache.list_objects(kind, namespace=namespace)

    # get_job_uncached deliberately NOT overridden: the adoption UID
    # recheck depends on bypassing every cache layer (__getattr__ hands
    # it straight to the inner chain).
