"""Shared, delta-fed watch cache: the informer-store seam for backends
without their own reflector.

The reference serves every hot-path read from client-go informer caches;
KubeCluster reproduces that with its reflector + store. The in-memory
backend (the scale benchmark's fabric, most test tiers, and the chaos
substrate) had no equivalent: every sync paid a fresh LIST for pods and
services and a GET for the job — pressure the accounting proxy shows
scaling linearly with sync count. This module closes that gap:

- `SharedWatchCache` subscribes to the backend's watch streams ONCE
  (pods, services, plus each job kind a controller registers) and
  maintains a store per resource, fed purely by deltas, with a
  resourceVersion bookmark per resource (the highest rv applied — the
  resume watermark a reconnecting reflector would use).
- `WatchCacheCluster` is the per-controller proxy: list_pods /
  list_services / get_pod / get_service / get_job are served from the
  shared store (deep-copied, claim-view filtered); every write — and
  every read the cache does not model, get_job_uncached above all —
  passes through to the inner chain untouched.

Shared by design: one manager's N framework controllers fan their syncs
over ONE store, so the backend sees one initial LIST per resource per
process instead of one per controller per sync.

Ordering contract: the cache registers its watch handlers BEFORE any
controller registers its own (the manager builds the cache first; the
per-kind registration happens inside FrameworkController.__init__ before
_watch()), and backends dispatch handlers in registration order — so by
the time an expectation is observed or a sync is enqueued for an event,
the store already reflects it. That is what lets the expectations gate
keep its exact meaning over cache-served lists.

Priming uses the reflector's watch-before-list trick: handlers are live
before the initial LIST, the merge keeps whichever copy carries the
higher resourceVersion, and deletions observed mid-prime leave
tombstones so the LIST snapshot can never resurrect an object the
deltas already removed.

Capability-gated via `Cluster.supports_watch_cache`: only backends whose
watch delivery is ordered and lossless opt in (the in-memory simulator).
The chaos seam pins it off — its seeded watch-drop injection would
poison a delta-fed store permanently — which also keeps every seeded
fault tier's read sequence byte-identical to the pre-cache engine.

Shard scoping (the 10k-job fleet-scale piece): with `--shards > 1` the
manager passes its ShardCoordinator as `scope`, and the cache keeps only
objects whose OWNING-JOB key (the job's ns/name for CR objects; the
`job-name` label for pods/services) lands in an owned shard. Every other
delta is dropped at this boundary — counted in
`watch_cache_events_filtered_total` against `..._served_total` — so
per-replica cache maintenance falls ~1/N instead of staying fleet-wide.
The scope set follows ownership live: `prime_shard` merges a freshly
claimed shard's objects from one backend LIST (called BEFORE the claim
resync enqueues keys, so the first post-claim syncs are cache-warm —
zero accounted reads even right after a steal), and `drop_shard` tears a
released shard's slice down so a long-lived replica's memory tracks its
share of the fleet, not all of it. Scoped reads that cannot be
attributed to a job key (a list without a job-name selector, a get of an
object the store lacks) fall through to the inner chain — a scoped store
is authoritative only for owned keys.
"""

from __future__ import annotations

import copy
import threading
from typing import Dict, List, Optional, Tuple

from . import base
from .base import ADDED, DELETED, MODIFIED, NotFound, SYNC
from ..core.constants import LABEL_JOB_NAME

_UPSERTS = (ADDED, MODIFIED, SYNC)

# resident_bytes walk depth bound: a stored object is a job dict or a
# typed Pod/Service (metadata/spec/status nesting ~4-5 deep); 8 levels
# covers every real shape, and the bound keeps a pathological
# self-referencing payload from recursing forever.
_BYTES_MAX_DEPTH = 8


def _approx_bytes(obj, depth: int = 0) -> int:
    """Approximate deep size of one stored object (see
    SharedWatchCache.resident_bytes). sys.getsizeof covers the shallow
    container/scalar; children are walked for dicts, sequences, and
    typed objects with a __dict__. Unknown/opaque leaves cost their
    shallow size (64 bytes when even that is unavailable)."""
    import sys

    size = sys.getsizeof(obj, 64)
    if depth >= _BYTES_MAX_DEPTH:
        return size
    if isinstance(obj, dict):
        for key, value in obj.items():
            size += _approx_bytes(key, depth + 1)
            size += _approx_bytes(value, depth + 1)
    elif isinstance(obj, (list, tuple, set, frozenset)):
        for value in obj:
            size += _approx_bytes(value, depth + 1)
    elif isinstance(obj, (str, bytes, int, float, bool)) or obj is None:
        pass  # getsizeof already counted the payload
    else:
        attrs = getattr(obj, "__dict__", None)
        if attrs:
            size += _approx_bytes(attrs, depth + 1)
    return size


def _meta(obj) -> Tuple[str, str, int]:
    """(namespace, name, rv) of a typed object or a job dict."""
    if isinstance(obj, dict):
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace", "default")
        name = meta.get("name", "")
        raw = meta.get("resourceVersion") or "0"
    else:
        ns = obj.metadata.namespace
        name = obj.metadata.name
        raw = obj.metadata.resource_version or "0"
    try:
        rv = int(raw)
    except ValueError:
        rv = 0
    return ns, name, rv


def _copy(obj):
    return obj.deep_copy() if hasattr(obj, "deep_copy") else copy.deepcopy(obj)


def _job_key(resource: str, obj) -> Optional[Tuple[str, str]]:
    """(namespace, owning-job name) of one cached object — the shard
    placement identity. CR objects ARE the job; pods/services carry the
    operator's `job-name` label. None = unattributable (an object the
    operator did not stamp): a scoped store neither keeps nor serves it,
    the proxy delegates such reads."""
    if isinstance(obj, dict):
        meta = obj.get("metadata") or {}
        return meta.get("namespace", "default"), meta.get("name", "")
    if resource in ("pods", "services"):
        name = obj.metadata.labels.get(LABEL_JOB_NAME)
        if not name:
            return None
        return obj.metadata.namespace, name
    return obj.metadata.namespace, obj.metadata.name


class SharedWatchCache:
    """Delta-fed store over one backend, shared by every controller of a
    process. Construct it ONCE, before any controller registers watches
    of its own (the manager does; see the module docstring's ordering
    contract).

    `scope` (optional) is the shard-ownership view — any object with
    `shard_of(ns, name)` and `owns(shard)`; the manager passes its
    ShardCoordinator. None (single-replica) keeps the store fleet-wide,
    byte-identical to the unscoped PR 7 cache. `metrics` feeds the
    watch_cache_events_{served,filtered}_total pair either way."""

    def __init__(self, backend, namespace: Optional[str] = None,
                 metrics=None, scope=None):
        self.backend = backend
        # Cache scope (None = every namespace): the LIST that primes a
        # resource uses it, and reads outside the scope fall through.
        self.namespace = namespace or None
        self.scope = scope
        self._metrics = metrics
        self._lock = threading.Lock()
        self._stores: Dict[str, Dict[Tuple[str, str], object]] = {}
        self._bookmarks: Dict[str, int] = {}
        self._primed: set = set()
        # (resource, ns, name) -> rv of a DELETED delta observed before
        # that resource finished priming (or while a shard re-prime is in
        # flight): the merge must not resurrect.
        self._tombstones: Dict[Tuple[str, str, str], int] = {}
        # >0 while prime_shard is re-listing: the handler then records
        # every deletion as a tombstone so the merge cannot resurrect an
        # object deleted between the LIST snapshot and the merge.
        self._repriming = 0
        self._registered: set = set()
        for resource in ("pods", "services"):
            self._register(resource)

    # -------------------------------------------------------------- feeds
    def _register(self, resource: str) -> None:
        with self._lock:
            if resource in self._registered:
                return
            self._registered.add(resource)
            self._stores.setdefault(resource, {})
        self.backend.watch(resource, self._handler(resource))

    def register_kind(self, kind: str) -> None:
        """Subscribe + prime the store for one job kind's CR objects
        (idempotent; each FrameworkController registers its own kind)."""
        self._register(kind)
        self._prime(kind, lambda: self.backend.list_jobs(kind, self.namespace))

    # -------------------------------------------------------------- scope
    def scope_allows_key(self, namespace: str, job_name: str) -> bool:
        """Whether the (ns, job) key lies in this replica's owned shards
        (True when unscoped)."""
        if self.scope is None:
            return True
        return self.scope.owns(self.scope.shard_of(namespace, job_name))

    def _in_scope(self, resource: str, obj) -> bool:
        if self.scope is None:
            return True
        key = _job_key(resource, obj)
        if key is None:
            return False
        return self.scope.owns(self.scope.shard_of(*key))

    def _count(self, resource: str, served: bool) -> None:
        if self._metrics is None:
            return
        if served:
            self._metrics.watch_cache_served_inc(resource)
        else:
            self._metrics.watch_cache_filtered_inc(resource)

    def _handler(self, resource: str):
        def on_event(event_type: str, obj) -> None:
            ns, name, rv = _meta(obj)
            if self.namespace is not None and ns != self.namespace:
                # Out-of-scope delta: covers() guarantees it could never
                # be served, so storing it would only grow the store with
                # other tenants' churn, unbounded.
                self._count(resource, served=False)
                return
            if not self._in_scope(resource, obj):
                # Out-of-shard delta (scoped fleet): dropped here, which
                # is exactly the ~(N-1)/N of fleet watch traffic this
                # replica no longer pays to index. A DELETED still clears
                # any stale store entry (scope may have shrunk after the
                # object was stored) and tombstones while a re-prime is
                # in flight.
                with self._lock:
                    if event_type == DELETED:
                        self._stores[resource].pop((ns, name), None)
                        if resource not in self._primed or self._repriming:
                            self._tombstones[(resource, ns, name)] = rv
                self._count(resource, served=False)
                return
            with self._lock:
                store = self._stores[resource]
                if event_type == DELETED:
                    store.pop((ns, name), None)
                    if resource not in self._primed or self._repriming:
                        self._tombstones[(resource, ns, name)] = rv
                elif event_type in _UPSERTS:
                    current = store.get((ns, name))
                    if current is None or _meta(current)[2] <= rv:
                        store[(ns, name)] = obj
                self._bookmarks[resource] = max(
                    self._bookmarks.get(resource, 0), rv
                )
            self._count(resource, served=True)

        return on_event

    def _prime(self, resource: str, lister) -> None:
        """Initial LIST, merged under the watch-before-list rule: deltas
        already flowing win on rv, tombstoned deletions never resurrect.
        Scoped caches merge only in-scope objects — the store must track
        this replica's share of the fleet from the very first LIST."""
        with self._lock:
            if resource in self._primed:
                return
        listed = lister()
        with self._lock:
            if resource in self._primed:
                return
            store = self._stores[resource]
            for obj in listed:
                ns, name, rv = _meta(obj)
                if not self._in_scope(resource, obj):
                    continue
                if self._tombstones.get((resource, ns, name), -1) >= rv:
                    continue
                current = store.get((ns, name))
                if current is None or _meta(current)[2] < rv:
                    store[(ns, name)] = obj
                self._bookmarks[resource] = max(
                    self._bookmarks.get(resource, 0), rv
                )
            self._primed.add(resource)
            if not self._repriming:
                self._tombstones = {
                    k: v for k, v in self._tombstones.items()
                    if k[0] != resource
                }

    def ensure_primed(self, resource: str) -> None:
        self._prime(resource, lambda: self._list_backend(resource))

    def _list_backend(self, resource: str) -> list:
        if resource == "pods":
            return self.backend.list_pods(namespace=self.namespace)
        if resource == "services":
            return self.backend.list_services(namespace=self.namespace)
        return self.backend.list_jobs(resource, self.namespace)

    def prime_shard(self, shard: int) -> None:
        """Scope grew (shard claimed): merge the shard's objects from one
        backend LIST per registered resource, so the store is warm BEFORE
        the claim resync enqueues the shard's keys — the first post-claim
        syncs (even right after a steal) read entirely from cache, zero
        accounted apiserver reads. Deletions racing the LIST are guarded
        by the same tombstone rule the initial prime uses (the handler
        records every DELETED while `_repriming` is up).

        Cost note: one full backend LIST per registered resource per
        claimed shard, filtered client-side — the same accepted
        amplification as the claim resync (claims are rare control-plane
        events), and a real apiserver pages these. A resize re-claims
        the whole ring, so if --shards grows large enough to matter,
        batch one LIST per resource across a tick's claims (the
        coordinator would need to aggregate its on_claim notifications
        per tick)."""
        if self.scope is None:
            return
        with self._lock:
            resources = sorted(self._registered)
            self._repriming += 1
        try:
            for resource in resources:
                with self._lock:
                    primed = resource in self._primed
                if not primed:
                    # Never base-primed: the full prime (scope-filtered,
                    # and the claimed shard is owned by the time on_claim
                    # fires) covers this shard's slice too.
                    self.ensure_primed(resource)
                    continue
                listed = self._list_backend(resource)
                with self._lock:
                    store = self._stores[resource]
                    for obj in listed:
                        ns, name, rv = _meta(obj)
                        if self.namespace is not None and ns != self.namespace:
                            continue
                        key = _job_key(resource, obj)
                        if key is None or self.scope.shard_of(*key) != shard:
                            continue
                        if self._tombstones.get(
                                (resource, ns, name), -1) >= rv:
                            continue
                        current = store.get((ns, name))
                        if current is None or _meta(current)[2] < rv:
                            store[(ns, name)] = obj
                        self._bookmarks[resource] = max(
                            self._bookmarks.get(resource, 0), rv
                        )
        finally:
            with self._lock:
                self._repriming -= 1
                if not self._repriming:
                    self._tombstones = {
                        k: v for k, v in self._tombstones.items()
                        if k[0] not in self._primed
                    }

    def drop_shard(self, shard: int) -> None:
        """Scope shrank (shard released/lost): tear the shard's slice out
        of every store, so a replica's cache memory tracks what it OWNS —
        at 10k jobs, holding the whole fleet's objects on every replica
        is exactly the constant this module exists to break."""
        if self.scope is None:
            return
        with self._lock:
            for resource, store in self._stores.items():
                doomed = []
                for key, obj in store.items():
                    jk = _job_key(resource, obj)
                    if jk is not None and self.scope.shard_of(*jk) == shard:
                        doomed.append(key)
                for key in doomed:
                    store.pop(key, None)

    def resident_objects(self) -> int:
        """Total objects resident across every store — the cache-memory
        hot-path column the fleet simulator reports at 100k objects (the
        constant drop_shard exists to bound)."""
        with self._lock:
            return sum(len(store) for store in self._stores.values())

    def resident_bytes(self) -> int:
        """Approximate resident memory of every store's objects, in
        bytes — the companion column to resident_objects at 100k-object
        fleet depth (an object COUNT hides a pod spec ballooning 10x).
        A recursive getsizeof walk over the stored dicts/typed objects:
        an approximation by design (no sharing analysis, bounded depth)
        but a consistent one, so trends and ratchets are meaningful.
        O(resident set) per call — callers sample it at sweep cadence
        (the fleet simulator's epoch sweep), never per sync. Also
        published as the training_operator_watch_cache_resident_bytes
        gauge when a metrics sink is attached."""
        with self._lock:
            total = 0
            for store in self._stores.values():
                for obj in store.values():
                    total += _approx_bytes(obj)
        if self._metrics is not None:
            self._metrics.set_gauge(
                "training_operator_watch_cache_resident_bytes", float(total))
        return total

    # -------------------------------------------------------------- reads
    def bookmark(self, resource: str) -> int:
        """Highest resourceVersion applied to `resource`'s store — the
        watermark a resuming watch would start from."""
        with self._lock:
            return self._bookmarks.get(resource, 0)

    def primed(self, resource: str) -> bool:
        with self._lock:
            return resource in self._primed

    def covers(self, namespace: Optional[str]) -> bool:
        """Whether a read scoped to `namespace` can be served from this
        cache's scope (an all-namespace cache covers everything; a scoped
        cache only its own namespace)."""
        return self.namespace is None or (
            namespace is not None and namespace == self.namespace
        )

    def list_objects(self, resource: str, namespace=None, labels=None,
                     owner_uid=None) -> list:
        self.ensure_primed(resource)
        with self._lock:
            snapshot = list(self._stores[resource].values())
        out = []
        for obj in snapshot:
            ns, _, _ = _meta(obj)
            if namespace is not None and ns != namespace:
                continue
            if not isinstance(obj, dict) and not base.matches_claim_view(
                obj, labels, owner_uid
            ):
                continue
            out.append(_copy(obj))
        return out

    def get_object(self, resource: str, namespace: str, name: str):
        self.ensure_primed(resource)
        with self._lock:
            obj = self._stores[resource].get((namespace, name))
        if obj is None:
            raise NotFound(f"{resource} {namespace}/{name}")
        return _copy(obj)

    def get_object_or_none(self, resource: str, namespace: str, name: str):
        """Store lookup WITHOUT NotFound semantics — the scoped proxy's
        read path: a scoped store's miss is ambiguous (deleted vs never
        in scope), so the caller must fall through to the inner chain
        rather than conclude the object is gone."""
        self.ensure_primed(resource)
        with self._lock:
            obj = self._stores[resource].get((namespace, name))
        return None if obj is None else _copy(obj)


class WatchCacheCluster:
    """Per-controller proxy serving the hot-path reads from a
    SharedWatchCache; everything else — writes, watches, uncached reads —
    delegates to `inner` (the controller's accounted/throttled chain), so
    a cache hit costs zero apiserver requests, exactly like an informer
    read in the reference.

    Under a SHARD-SCOPED cache the serving rule tightens: a read is
    served from the store only when it is attributable to an owned job
    key (a job get/list keyed by ns/name, a pod/service list selected by
    the `job-name` label) or when the store simply has the object (gets).
    Everything ambiguous — unselected lists, store misses — delegates:
    the scoped store is a subset of the world and must never masquerade
    as all of it."""

    def __init__(self, inner, cache: SharedWatchCache, kind: str):
        self._inner = inner
        self._cache = cache
        self._kind = kind
        cache.register_kind(kind)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _scoped(self) -> bool:
        return self._cache.scope is not None

    # ------------------------------------------------------------- reads
    def _list_dependents(self, resource, namespace, labels, owner_uid,
                         inner_list):
        if not self._cache.covers(namespace):
            return inner_list(
                namespace=namespace, labels=labels, owner_uid=owner_uid)
        if self._scoped():
            job = (labels or {}).get(LABEL_JOB_NAME)
            if (namespace is None or not job
                    or not self._cache.scope_allows_key(namespace, job)):
                # Unattributable (no job-name selector) or out-of-shard:
                # the scoped store is not authoritative — delegate.
                return inner_list(
                    namespace=namespace, labels=labels, owner_uid=owner_uid)
        return self._cache.list_objects(
            resource, namespace=namespace, labels=labels,
            owner_uid=owner_uid)

    def list_pods(self, namespace=None, labels=None, owner_uid=None):
        return self._list_dependents(
            "pods", namespace, labels, owner_uid, self._inner.list_pods)

    def list_services(self, namespace=None, labels=None, owner_uid=None):
        return self._list_dependents(
            "services", namespace, labels, owner_uid,
            self._inner.list_services)

    def get_pod(self, namespace: str, name: str):
        if not self._cache.covers(namespace):
            return self._inner.get_pod(namespace, name)
        if self._scoped():
            obj = self._cache.get_object_or_none("pods", namespace, name)
            return obj if obj is not None else self._inner.get_pod(
                namespace, name)
        return self._cache.get_object("pods", namespace, name)

    def get_service(self, namespace: str, name: str):
        if not self._cache.covers(namespace):
            return self._inner.get_service(namespace, name)
        if self._scoped():
            obj = self._cache.get_object_or_none("services", namespace, name)
            return obj if obj is not None else self._inner.get_service(
                namespace, name)
        return self._cache.get_object("services", namespace, name)

    def get_job(self, kind: str, namespace: str, name: str) -> dict:
        # Only the proxy's own kind is cached (each controller registers
        # exactly its kind); a cross-kind read (SDK helpers) delegates.
        if kind != self._kind or not self._cache.covers(namespace):
            return self._inner.get_job(kind, namespace, name)
        if self._scoped():
            if not self._cache.scope_allows_key(namespace, name):
                return self._inner.get_job(kind, namespace, name)
            obj = self._cache.get_object_or_none(kind, namespace, name)
            # Owned key, store miss: the job is genuinely gone OR it was
            # created in the claim-prime race window — the inner read is
            # the authority either way (a NotFound here drives _forget).
            return obj if obj is not None else self._inner.get_job(
                kind, namespace, name)
        return self._cache.get_object(kind, namespace, name)

    def list_jobs(self, kind: str, namespace=None):
        if (kind != self._kind or not self._cache.covers(namespace)
                or self._scoped()):
            # A scoped store holds only owned shards — never serve it as
            # a full listing (resyncs and SDK helpers want the world).
            return self._inner.list_jobs(kind, namespace)
        return self._cache.list_objects(kind, namespace=namespace)

    # get_job_uncached deliberately NOT overridden: the adoption UID
    # recheck depends on bypassing every cache layer (__getattr__ hands
    # it straight to the inner chain).
