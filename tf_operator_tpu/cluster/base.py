"""The cluster interface the operator programs against.

The reference reaches its cluster through client-go clientsets + informers
(L0 in SURVEY.md §1). This interface is the equivalent seam: everything the
engine needs — typed CRUD for jobs/pods/services/podgroups, events, and watch
callbacks — with no Kubernetes dependency, so the same engine drives the
in-memory simulator and a real API server.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ..api.k8s import POD_FAILED, POD_SUCCEEDED, Event, Pod, Service


class NotFound(KeyError):
    """Object does not exist (k8s 404 analog)."""


class Conflict(Exception):
    """Stale resourceVersion on a full-object write (k8s 409 analog)."""


class Gone(RuntimeError):
    """Requested history no longer available (k8s 410 analog): an expired
    list continue token or a watch resourceVersion older than the server's
    watch cache. Recoverable by restarting the list/watch from scratch."""


class ServerError(RuntimeError):
    """Transient apiserver failure (k8s 5xx analog): the request may or
    may not have taken effect; safe to retry through the rate-limited
    queue. Raised by HTTP backends on 5xx and injected by the chaos proxy
    (cluster/chaos.py)."""


# Watch event types
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
# Informer relist replay: the object existed before the (re)list — handlers
# must treat it as "state, not news" (no created-counter increments, no
# expectation observations). Emitted by cache-backed backends (KubeCluster)
# when a watch reconnect replays current state; the reference gets the same
# effect from client-go's informer DeltaFIFO Sync deltas.
SYNC = "SYNC"

WatchHandler = Callable[[str, object], None]  # (event_type, object) -> None


def matches_claim_view(obj, labels, owner_uid) -> bool:
    """The claim protocol's listing predicate, single-sourced: label-match
    OR controller-owned-by-uid (an owned object whose labels were mutated
    away must still be visible, or it could never be released)."""
    selected = not labels or all(
        obj.metadata.labels.get(k) == v for k, v in labels.items()
    )
    if selected:
        return True
    return owner_uid is not None and any(
        r.uid == owner_uid and r.controller
        for r in obj.metadata.owner_references
    )


class Cluster:
    """Abstract cluster backend."""

    # Capability flag for the engine's slow-start fan-out (core/control.py
    # slow_start_batch): True means write methods tolerate concurrent
    # callers AND nothing downstream keys behavior on per-method call
    # ORDER, so the engine may issue a batch's writes in parallel. False
    # (the conservative default) serializes every batch in work-list
    # order — required by the chaos proxy, whose fault schedule is a pure
    # function of (method, per-method call index) and must stay
    # byte-reproducible, and by backends that are not thread-safe.
    # Proxies that delegate via __getattr__ (throttled, failover gate)
    # inherit the inner backend's verdict automatically.
    supports_concurrent_writes: bool = False

    # Whether the backend tolerates N sync WORKERS reconciling different
    # jobs at once (the controller's MaxConcurrentReconciles pool). The
    # workqueue already guarantees one key is never synced by two workers
    # simultaneously, so this flag is about the backend only: False (the
    # conservative default) pins the pool to one worker — required by the
    # chaos seam (its fault schedule is keyed on per-method call order,
    # which interleaved syncs of DIFFERENT jobs would scramble) and by
    # backends whose writes are not thread-safe. Distinct from
    # supports_concurrent_writes (parallelism WITHIN one sync's fan-out);
    # the two are gated independently but every seam today answers both
    # the same way. Proxies inherit via __getattr__, like the write flag.
    supports_concurrent_syncs: bool = False

    # Whether the backend supports the coalesced status-write path
    # (patch_job_status + rate-limited flush + batched create/delete
    # events). False (the conservative default) keeps the engine on the
    # legacy one-synchronous-update_job_status-per-sync path with
    # per-replica events — required by the chaos/crash seams, whose fault
    # schedules are keyed on (method, per-method call index) and must
    # replay byte-identically, and by the process tier for the same
    # reason. Proxies inherit via __getattr__, like the other two flags.
    supports_write_coalescing: bool = False

    # Whether list/get reads may be served from a delta-fed shared watch
    # cache (cluster/watchcache.py) instead of hitting the backend per
    # sync. True only for backends whose watch delivery is ordered and
    # lossless (the in-memory simulator). KubeCluster keeps False: its
    # reflector already serves lists from an informer store, and a second
    # cache layer would double-buffer staleness. Chaos keeps False — its
    # watch-drop injection would poison a delta-fed cache permanently
    # (a real informer heals via relist; the proxy cache has no resync).
    supports_watch_cache: bool = False

    # ---- jobs (CR objects, stored as dicts keyed by kind) ----
    def create_job(self, job_dict: dict) -> dict:
        raise NotImplementedError

    def get_job(self, kind: str, namespace: str, name: str) -> dict:
        raise NotImplementedError

    def get_job_uncached(self, kind: str, namespace: str, name: str) -> dict:
        """Authoritative read that MUST bypass any informer cache (the
        reference's delegating uncached reader, pkg/common/util/client.go):
        the adoption UID recheck depends on it. Backends whose get_job is
        already authoritative (memory/process) inherit this default."""
        return self.get_job(kind, namespace, name)

    def list_jobs(self, kind: str, namespace: Optional[str] = None) -> List[dict]:
        raise NotImplementedError

    def update_job(self, job_dict: dict) -> dict:
        raise NotImplementedError

    def update_job_status(self, kind: str, namespace: str, name: str, status: dict) -> dict:
        raise NotImplementedError

    def patch_job_status(self, kind: str, namespace: str, name: str, status: dict) -> dict:
        """Apply `status` to the job's status subresource in ONE request —
        the server-side-apply/merge-patch idiom the coalescing writer
        uses. `status` is the ENTIRE intended status (not a partial
        delta): fields it omits must clear on the server, exactly like
        update_job_status's replace semantics, but without the
        read-modify-write round trip or resourceVersion Conflict surface.
        Backends that predate the verb inherit this fallback (two
        requests, same end state), so the writer never needs a
        capability check of its own — supports_write_coalescing already
        gates whether the coalesced path runs at all."""
        return self.update_job_status(kind, namespace, name, status)

    def delete_job(self, kind: str, namespace: str, name: str) -> None:
        raise NotImplementedError

    # ---- pods ----
    def create_pod(self, pod: Pod) -> Pod:
        raise NotImplementedError

    def get_pod(self, namespace: str, name: str) -> Pod:
        raise NotImplementedError

    def list_pods(self, namespace: Optional[str] = None, labels: Optional[Dict[str, str]] = None,
                  owner_uid: Optional[str] = None) -> List[Pod]:
        """Label-selected pods; `owner_uid` widens the match to label-match
        OR controller-owned-by-uid (the claim protocol's release view)."""
        raise NotImplementedError

    def update_pod(self, pod: Pod) -> Pod:
        raise NotImplementedError

    def get_pod_log(self, namespace: str, name: str) -> str:
        """Container log text for a pod (SDK get_logs; kube `pods/log`)."""
        raise NotImplementedError

    def stream_pod_log(self, namespace: str, name: str, follow: bool = False,
                       poll_interval: float = 0.2, stop=None):
        """Yield log text chunks; with ``follow``, keep yielding as the log
        grows until the pod reaches a terminal phase, vanishes, or is
        REPLACED (same name, new UID — the stream follows one pod
        incarnation, like `kubectl logs -f` ending when its pod goes away) —
        kube `pods/log?follow=true`. ``stop`` (a threading.Event) cancels a
        follow promptly so abandoned consumers don't leak pollers.

        Default implementation polls get_pod_log/get_pod (correct for the
        in-memory backend); the HTTP and process backends override."""
        try:
            uid = self.get_pod(namespace, name).metadata.uid
        except NotFound:
            return
        offset = 0
        while not (stop is not None and stop.is_set()):
            try:
                text = self.get_pod_log(namespace, name)
            except NotFound:
                return
            if len(text) > offset:
                yield text[offset:]
                offset = len(text)
            if not follow:
                return
            try:
                pod = self.get_pod(namespace, name)
            except NotFound:
                return
            if pod.metadata.uid != uid:
                return  # replaced by a same-name pod: this stream is over
            if pod.status.phase in (POD_SUCCEEDED, POD_FAILED):
                # One final read: flush anything written between the log
                # read above and the phase observation.
                try:
                    final = self.get_pod_log(namespace, name)
                except NotFound:
                    return
                if len(final) > offset:
                    yield final[offset:]
                return
            time.sleep(poll_interval)

    def delete_pod(self, namespace: str, name: str, force: bool = False) -> None:
        """Delete a pod. ``force`` requests grace-period-0 semantics (the
        ``kubectl delete --force --grace-period=0`` analog): the apiserver
        removes the object immediately instead of waiting for the kubelet
        to confirm termination. The escalation path for pods wedged
        Terminating on a dead host (docs/design/failure_modes.md §9) —
        a kubelet that will never ack holds the graceful window open
        forever, and the object's continued existence blocks gang
        recovery. Backends that predate the flag ignore it (their deletes
        were always immediate)."""
        raise NotImplementedError

    # ---- services ----
    def create_service(self, service: Service) -> Service:
        raise NotImplementedError

    def get_service(self, namespace: str, name: str) -> Service:
        raise NotImplementedError

    def list_services(self, namespace: Optional[str] = None, labels: Optional[Dict[str, str]] = None,
                      owner_uid: Optional[str] = None) -> List[Service]:
        raise NotImplementedError

    def update_service(self, service: Service) -> Service:
        raise NotImplementedError

    def delete_service(self, namespace: str, name: str) -> None:
        raise NotImplementedError

    # ---- pod groups (gang scheduling unit; volcano PodGroup analog) ----
    def create_pod_group(self, group: dict) -> dict:
        raise NotImplementedError

    def get_pod_group(self, namespace: str, name: str) -> dict:
        raise NotImplementedError

    def list_pod_groups(self, namespace: Optional[str] = None,
                        labels: Optional[Dict[str, str]] = None) -> List[dict]:
        raise NotImplementedError

    def delete_pod_group(self, namespace: str, name: str) -> None:
        raise NotImplementedError

    # ---- leases (coordination.k8s.io/v1 analog; leader election) ----
    def get_lease(self, namespace: str, name: str) -> dict:
        """Fetch a Lease object ({metadata, spec{holderIdentity, renewTime,
        leaseDurationSeconds, leaseTransitions}}). NotFound if absent."""
        raise NotImplementedError

    def create_lease(self, lease: dict) -> dict:
        """Create a Lease; Conflict if it already exists (apiserver 409)."""
        raise NotImplementedError

    def update_lease(self, lease: dict) -> dict:
        """Full-object Lease replace with optimistic concurrency: a stale
        metadata.resourceVersion raises Conflict — the mechanism that makes
        two replicas racing for the lock safe."""
        raise NotImplementedError

    def delete_lease(self, namespace: str, name: str) -> None:
        """Delete a Lease (heartbeat GC at job termination). NotFound if
        absent. Backends that predate this method inherit the
        NotImplementedError default; callers treat it as best-effort."""
        raise NotImplementedError

    def list_leases(self, namespace: Optional[str] = None,
                    name_prefix: str = "",
                    labels: Optional[Dict[str, str]] = None) -> List[dict]:
        """List Lease objects, optionally restricted to one namespace,
        a name prefix, and an equality label selector (the shard
        coordinator's member-roster discovery: every replica renews a
        labeled `<lock>-member-<identity>` lease and lists the selector
        to rank the live fleet — core/sharding.py). `labels` is the
        filter that keeps membership observation O(members): HTTP
        backends push it server-side as a labelSelector, so the response
        stops scaling with the fleet-wide lease count (per-job heartbeat
        leases outnumber members ~jobs:replicas). The prefix remains a
        client-side convenience filter. Backends that predate the verb
        inherit this NotImplementedError default — sharding requires a
        backend that can enumerate leases."""
        raise NotImplementedError

    # ---- events ----
    def record_event(self, event: Event) -> None:
        raise NotImplementedError

    def list_events(self, involved_object: Optional[str] = None) -> List[Event]:
        raise NotImplementedError

    # ---- watches ----
    def watch(self, kind: str, handler: WatchHandler) -> None:
        """Register a callback for ADDED/MODIFIED/DELETED events on `kind`
        ("pods", "services", or a job kind like "TFJob")."""
        raise NotImplementedError
