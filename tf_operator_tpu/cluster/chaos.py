"""Seeded fault-injection cluster proxy.

Same proxy idiom as `cluster/throttled.py`: wraps any `Cluster` and
delegates everything, but — driven by a deterministic seeded plan —
injects the apiserver's unhappy paths between the controller and the
backend:

- write `Conflict`s (stale-resourceVersion 409s),
- transient `ServerError`s (5xx),
- added write latency,
- watch-stream event drops (the informer's lost-event failure mode),
- node-scoped batch pod kills that simulate TPU slice-host preemption
  (every matching pod flips to Failed/137 with a `DisruptionTarget`
  condition in one batch, the way a reclaimed host takes all its pods
  at once),
- seeded hang injection (`ScheduledHang` / `freeze_heartbeats`): heartbeat
  Lease writes for chosen workers are silently dropped, so a pod looks
  Running while its liveness proof stops — the silent-wedge failure mode
  the gang-liveness deadlines exist to catch (frozen-rendezvous mode is
  `after_writes=0`: the first heartbeat never lands).

Determinism is the point: every decision is a pure function of
(seed, method, per-method call index), via SHA-256 — no `random` state,
no wall clock — so the SAME seed over the SAME operation sequence yields
the SAME fault schedule byte-for-byte (`fault_log`). That is what lets a
chaos-tier failure be replayed locally from nothing but its seed.

Faults are injected on WRITES only (plus watch delivery): reads are
retried freely by the sync loop, so read-side faults would make the
per-method call counts — and with them the schedule — depend on sync
timing rather than on the controller's actual actions.
"""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api.k8s import (
    POD_CONDITION_DISRUPTION_TARGET,
    POD_FAILED,
    ContainerState,
    ContainerStateTerminated,
    ContainerStatus,
    PodCondition,
)
from ..core.constants import (
    HEARTBEAT_LEASE_SUFFIX,
    LABEL_JOB_NAME,
    LABEL_SLICE_INDEX,
)
from .base import Cluster, Conflict, ServerError

# Writes eligible for fault injection — the same surface ThrottledCluster
# throttles (every apiserver mutation the engine performs).
_WRITE_METHODS = (
    "create_job",
    "update_job",
    "update_job_status",
    # The coalescing writer's verb: faultable like every other write so a
    # test that OPTS a chaos seam into coalescing (instance-level
    # supports_write_coalescing=True, the crash-window regressions) can
    # plant CrashPoints on the counted patch. Not conflict-eligible —
    # a merge patch carries no resourceVersion to go stale.
    "patch_job_status",
    "delete_job",
    "create_pod",
    "update_pod",
    "delete_pod",
    "create_service",
    "update_service",
    "delete_service",
    "record_event",
    "create_pod_group",
    "delete_pod_group",
    # Lease writes (heartbeats, leader election) are faultable like every
    # other write — but handled by explicit methods below (hang check +
    # inject, NO _note_write: lease traffic must not advance the write
    # clock, or heartbeat cadence would shift PR-1 preemption schedules).
    "create_lease",
    "update_lease",
)

# Conflict only makes sense where the apiserver would 409: optimistic-
# concurrency writes and name-collision creates.
_CONFLICT_METHODS = tuple(
    m for m in _WRITE_METHODS if m.startswith(("update_", "create_"))
)

# Lease writes are issued by WORKLOAD heartbeat threads and the leader
# elector, not by the reconcile loop — a "controller crash" planted there
# would kill the wrong process. Rate-based crash decisions skip them
# (explicit CrashPoints may still target them deliberately).
_CRASH_EXEMPT_METHODS = ("create_lease", "update_lease")


class SimulatedCrash(BaseException):
    """A planted controller crash (chaos CrashPoint): the process dies at
    this exact write. BaseException ON PURPOSE — the controller's blanket
    `except Exception` recovery paths (process_next, best-effort event
    recording, teardown continue-past-errors) must NOT absorb it, exactly
    as none of them would survive a real SIGKILL. Only the failover
    harness (testing/failover.py) catches it, discards the controller
    instance wholesale, and cold-starts a fresh one."""


@dataclass
class ScheduledPreemption:
    """A slice-host preemption planted in the schedule: after the proxy
    has seen `after_writes` total write calls, every pod matching
    (namespace, labels) is batch-killed. Fires at most once."""

    after_writes: int
    namespace: Optional[str] = None
    labels: Optional[Dict[str, str]] = None
    reason: str = "Preempted"
    exit_code: int = 137


@dataclass
class ScheduledSlicePreemption:
    """A whole-SLICE preemption planted in the schedule — the multislice
    analog of ScheduledPreemption, selecting by `slice_index`: after the
    proxy has seen `after_writes` total writes, every pod of `job_name`
    carrying the matching tpu-slice-index label is batch-killed in one
    event (a reclaimed slice takes all its hosts at once). The
    slice-scoped failure-domain machinery must restart THAT slice only;
    the other slices' pods must keep their UIDs. Fires at most once."""

    after_writes: int
    job_name: str = ""
    slice_index: int = 0
    namespace: Optional[str] = None
    reason: str = "Preempted"
    exit_code: int = 137


@dataclass
class ScheduledHang:
    """A silent-wedge injection planted in the schedule: while active,
    heartbeat Lease writes (create_lease/update_lease) whose lease name
    matches are DROPPED — the worker looks Running while its liveness
    proof stops, exactly the failure mode progressDeadlineSeconds exists
    to catch. `after_writes=0` is frozen-rendezvous mode (the worker
    never lands a first heartbeat); a positive value freezes a previously
    healthy worker mid-training. `until_writes` bounds the hang so a
    converge-after-restart scenario stays schedulable. Lease writes do
    not advance the write clock (PR-1 schedules stay byte-identical)."""

    after_writes: int = 0
    until_writes: Optional[int] = None
    namespace: Optional[str] = None
    # Substring of the lease name ("<pod>-hb"), e.g. "worker-2" to wedge
    # one worker or "job-worker" to wedge a whole slice-host's pods.
    name_contains: str = ""


@dataclass
class CrashPoint:
    """An explicit controller crash planted in the schedule: the
    `call_index`-th call of `method` (per-method 0-based counter, the same
    clock every other fault uses) raises SimulatedCrash. Two variants,
    both of which a crash-consistent controller must survive:

    - before_write=True: the crash lands BEFORE the write reaches the
      backend — the write dies with the process (the controller decided
      but never acted);
    - before_write=False: the write LANDED, then the process died before
      observing the response — "did my write land?" is unanswerable to
      the next incarnation except through a fresh read.

    Deterministic by construction: per-method call indices are a pure
    function of the operation sequence, so a fixed (seed, crash_points)
    replays the identical crash byte-for-byte."""

    method: str
    call_index: int
    before_write: bool = True


@dataclass
class ScheduledLeaseSteal:
    """A contested lease claim planted in the schedule: on the
    `at_renew`-th update_lease call whose lease name matches (per-entry
    0-based match counter), a rival identity is written over the current
    holder FIRST — so the legitimate caller's own write lands on a stale
    resourceVersion and takes the 409 a real losing racer takes. The
    rival never renews, so the victim's skew-safe observation timer
    re-arms and it steals back after a full duration: the contested-claim
    window of the shard handoff protocol (core/sharding.py), explored
    byte-reproducibly. The rival's renewTime is copied from the CALLER's
    intended write — "freshly renewed" without the proxy needing a clock
    of its own."""

    at_renew: int
    name_contains: str = ""
    namespace: Optional[str] = None
    rival: str = "chaos-rival"


@dataclass
class ScheduledRenewDelay:
    """Silently dropped lease renewals (the slow-renewer failure mode —
    a GC pause or apiserver brownout between a holder and its lease):
    matching update_lease calls with per-entry match index in
    [after_renews, after_renews + drop_renews) are swallowed — the
    holder believes each renewal landed while peers watch the lease age
    toward expiry and steal it. The delayed-renew half of the contested
    window: the stale holder still THINKS it leads until its next
    successful read shows the thief. Deterministic: indices count
    matching calls, no clocks involved.

    `name_contains` matches the lease NAME (one specific lock);
    `holder_contains` matches the WRITER's holderIdentity — the
    per-client partition shape: every renewal one replica issues (its
    member lease AND its shard leases) vanishes, while a peer that later
    steals the same lease renews it normally."""

    after_renews: int
    drop_renews: int = 1
    name_contains: str = ""
    holder_contains: str = ""
    namespace: Optional[str] = None


@dataclass
class ScheduledCapacityRevocation:
    """A capacity revocation planted in the schedule: after the proxy has
    seen `after_writes` total writes, the backend's schedulable-capacity
    pool is REPLACED with `capacity` (normally smaller — a reservation
    reclaimed, a maintenance window fencing hosts). Already-bound pods
    keep running; reconciling the admitted set down to the shrunk pool is
    the gang-admission layer's job (preempt-lowest-band-to-fit,
    core/admission.py). Fires at most once; requires a backend with
    set_schedulable_capacity (the in-memory simulator)."""

    after_writes: int
    capacity: Dict[str, str] = None  # type: ignore[assignment]


@dataclass
class ScheduledStuckTermination:
    """A dead-kubelet event planted in the schedule: after the proxy has
    seen `after_writes` total writes, graceful deletes of matching pods
    wedge Terminating (the memory backend's hold lever) until force
    deleted. Fires at most once; requires a backend with
    hold_pod_termination (the in-memory simulator)."""

    after_writes: int
    namespace: Optional[str] = None
    name_contains: str = ""


@dataclass
class ScheduledRestoreFault:
    """A restore-path fault planted in the schedule (the fast-recovery
    plane's adversary, consumed by train/restore.py through a
    :class:`RestoreFaultInjector`). Keys on per-(op, kind-match) consult
    counters, not the write clock — restore traffic never advances the
    cluster write clock, so PR 1-15 schedules are untouched by this
    field's existence.

    Kinds: ``refuse`` (connection refused), ``hang`` (per-peer timeout),
    ``truncate`` (shard body cut in half — fails sha256 verification),
    ``stale-meta`` (peer advertises a step one behind storage — loses the
    staleness arbitration), ``die-mid-transfer`` (the peer process dies at
    this consult: the connection resets immediately and EVERY later
    consult for that peer refuses silently — logged once at death, the
    injector remembers the dead set; the scatter-gather client re-plans
    the peer's unfetched shards), ``stale-manifest`` (the manifest analog
    of stale-meta — one step behind storage), ``partial-owner`` (the
    manifest claims only the front half of its owned stride, orphaning
    the rest for the planner's all-peers fallback), ``delta-missing-shard``
    (a delta-manifest shard payload is absent from the content-addressed
    store — the STORAGE rung's torn-chain fault; the whole tree degrades
    to the newest full step with cause ``delta-chain-broken``),
    ``delta-corrupt-shard`` (a delta payload truncated to half — fails
    sha256 against the manifest, cause ``delta-checksum-mismatch``).
    ``op`` scopes the fault to the client's ``meta`` / ``manifest``
    probes, ``shard`` fetch, the post-fetch ``shard-body`` /
    ``meta-body`` / ``manifest-body`` mutation points, or the storage
    rung's ``delta-shard`` manifest resolution (consulted with peer
    index 0 — storage has no discovery order); ``peer`` targets one peer
    INDEX in the client's discovery order (indices, not addresses —
    ephemeral ports would break byte-equal replay). ``at_call``/``count``
    window the fault over the Nth..N+count-1th matching consults, so a
    fault can refuse one attempt and let the retry through, or outlive
    the retry budget."""

    kind: str
    # meta | manifest | shard | meta-body | manifest-body | shard-body |
    # delta-shard | *
    op: str = "*"
    peer: Optional[int] = None    # discovery-order index; None = any peer
    at_call: int = 1              # 1-based index of the first faulted consult
    count: int = 1


class RestoreFaultInjector:
    """Deterministic restore-fault oracle: the client consults
    ``fault_for(op, peer_index)`` at every fetch attempt/mutation point and
    applies whatever kind comes back. Consult counters are pure functions
    of the call sequence (the client iterates peers in discovery order and
    shards in sorted order), so a seeded run logs — and replays —
    byte-identically. Standalone-usable; ChaosCluster binds one to its
    fault_log via :meth:`ChaosCluster.restore_fault_injector`."""

    def __init__(self, faults: Tuple[ScheduledRestoreFault, ...] = (),
                 log: Optional[List[str]] = None) -> None:
        self.faults = tuple(faults)
        self.fault_log = log if log is not None else []
        self._lock = threading.Lock()
        self._consults: Dict[int, int] = {}
        self._dead: set = set()  # peers killed by die-mid-transfer

    def fault_for(self, op: str, peer_index: int) -> Optional[str]:
        """The fault kind (or None) for this consult. Every matching
        entry's counter advances (so same-op entries with disjoint
        at_call windows compose); the first entry whose window covers the
        consult fires. A peer a ``die-mid-transfer`` fault has killed
        stays dead: every later consult for it refuses silently (logged
        once at the death — an unbounded refusal stream would bloat the
        byte-equal log) without advancing any counters, so the remaining
        schedule plays out against the survivors exactly as authored."""
        fired: Optional[str] = None
        with self._lock:
            if peer_index in self._dead:
                return "refuse"
            for i, fault in enumerate(self.faults):
                if fault.op not in ("*", op):
                    continue
                if fault.peer is not None and fault.peer != peer_index:
                    continue
                n = self._consults.get(i, 0) + 1
                self._consults[i] = n
                if fired is None and fault.at_call <= n < fault.at_call + fault.count:
                    self.fault_log.append(
                        f"restore:{op}#{n}:{fault.kind}:peer{peer_index}"
                    )
                    fired = fault.kind
            if fired == "die-mid-transfer":
                self._dead.add(peer_index)
        return fired


@dataclass
class ChaosSpec:
    """The seeded plan. Rates are probabilities in [0, 1] evaluated per
    call from the deterministic hash stream."""

    seed: int = 0
    conflict_rate: float = 0.0
    error_rate: float = 0.0
    latency_rate: float = 0.0
    latency_seconds: float = 0.0
    drop_watch_rate: float = 0.0
    # Kinds whose watch events may be dropped; empty tuple = all kinds.
    drop_watch_kinds: Tuple[str, ...] = ()
    preemptions: Tuple[ScheduledPreemption, ...] = ()
    # Slice-targeted preemptions (slice-scoped failure domains): a new
    # plan field, default empty — every pre-existing seeded schedule is
    # untouched by its existence (nothing fires, the write clock and
    # fault_log are byte-identical).
    slice_preemptions: Tuple[ScheduledSlicePreemption, ...] = ()
    hangs: Tuple[ScheduledHang, ...] = ()
    # Controller-crash plan: hash-driven crashes at `crash_rate` per
    # eligible write (variant — before/after the write lands — drawn from
    # the same hash stream), bounded by `max_crashes` so a failover run
    # can converge; `crash_points` plants explicit (method, call-index)
    # crashes for targeted crash-window tests. Lease writes are exempt
    # from the rate (they belong to workload threads, not the controller).
    crash_rate: float = 0.0
    crash_methods: Tuple[str, ...] = ()  # empty = every faultable write
    max_crashes: int = 8
    crash_points: Tuple[CrashPoint, ...] = ()
    # Dead-kubelet plan: write-clock-scheduled stuck-terminating holds.
    stuck_terminations: Tuple[ScheduledStuckTermination, ...] = ()
    # Capacity-revocation plan (the gang-admission layer's adversary):
    # write-clock-scheduled shrinks of the backend's schedulable pool.
    # The admission layer observes the new bound through its capacity_fn
    # and must preempt lowest-band gangs until the admitted set fits.
    capacity_revocations: Tuple[ScheduledCapacityRevocation, ...] = ()
    # Lease-contention plan (the sharded control plane's adversary):
    # rival writes forcing contested claims, and silently dropped
    # renewals opening the delayed-renew steal window. Both key on
    # per-entry MATCH counters (not the write clock — lease traffic does
    # not advance it), so PR 1-7 schedules are untouched by the fields'
    # existence and a sharded test replays byte-identically from its
    # seed + plan.
    lease_steals: Tuple[ScheduledLeaseSteal, ...] = ()
    renew_delays: Tuple[ScheduledRenewDelay, ...] = ()
    # Restore-path plan (the fast-recovery plane's adversary): seeded
    # faults the peer-restore client applies at its fetch hooks. Keys on
    # per-entry consult counters, not the write clock — default empty, so
    # every pre-existing seeded schedule replays byte-identically.
    restore_faults: Tuple[ScheduledRestoreFault, ...] = ()
    # Methods exempt from error/conflict injection (latency still
    # applies). Default: none — every write, record_event included, is
    # faultable; the engine's best-effort event recording is itself a
    # property the chaos tier regression-tests (by exempting everything
    # EXCEPT record_event and asserting reconciles survive).
    exempt_methods: Tuple[str, ...] = ()


class ChaosCluster:
    """Delegates everything to `inner`; write methods run the fault plan
    first. `fault_log` records every injected fault in order — the
    byte-for-byte reproducibility artifact."""

    # The fault schedule is a pure function of (method, per-method call
    # index): concurrent writers would make those indices — and with them
    # the entire schedule — depend on thread scheduling. Declaring the
    # seam serial makes the engine's slow-start fan-out degrade to
    # strictly-ordered sequential writes, which is exactly what keeps a
    # seeded chaos run byte-reproducible with fan-out enabled
    # (docs/design/control_plane_performance.md). The same argument pins
    # the sync-worker pool to one worker: interleaved syncs of different
    # jobs would scramble per-method call indices just as thoroughly as
    # parallel writes within one sync.
    supports_concurrent_writes = False
    supports_concurrent_syncs = False
    # Coalescing would change WHICH status writes are issued (deferred
    # churn never reaches the backend) and event batching would change
    # record_event counts — both scramble the write clock every
    # after_writes-scheduled fault keys on. Pinned off so every seeded
    # tier replays byte-identically; crash-window regressions that need
    # coalescing ON over a chaos seam opt in per instance. The watch
    # cache is pinned off because drop_watch_rate would poison a
    # delta-fed store permanently (no relist heals the proxy cache).
    supports_write_coalescing = False
    supports_watch_cache = False

    def __init__(self, inner: Cluster, spec: ChaosSpec):
        self._inner = inner
        self.spec = spec
        self.fault_log: List[str] = []
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._writes_seen = 0
        self._preempted = [False] * len(spec.preemptions)
        self._slice_preempted = [False] * len(spec.slice_preemptions)
        self._stuck_fired = [False] * len(spec.stuck_terminations)
        self._capacity_fired = [False] * len(spec.capacity_revocations)
        self._crashes_fired = 0
        # Direct-lever hangs (freeze_heartbeats) appended at test-chosen
        # points, beside the write-clock-scheduled spec.hangs.
        self._manual_hangs: List[ScheduledHang] = []
        self._restore_injector: Optional[RestoreFaultInjector] = None

    def restore_fault_injector(self) -> RestoreFaultInjector:
        """The injector for this plan's restore_faults, sharing this
        cluster's fault_log so restore-path faults interleave with the
        write-clock faults in one byte-comparable artifact. One instance
        per cluster (consult counters must survive across restores)."""
        if self._restore_injector is None:
            self._restore_injector = RestoreFaultInjector(
                self.spec.restore_faults, log=self.fault_log
            )
        return self._restore_injector

    # ------------------------------------------------------------- plan
    def next_call_index(self, method: str) -> int:
        """The per-method call index the NEXT call of `method` will draw —
        lets a test plant a CrashPoint at 'the controller's next status
        write' at a chosen scenario moment without hand-counting the whole
        schedule. Deterministic: the counters are a pure function of the
        operation sequence so far."""
        with self._lock:
            return self._counters.get(method, 0)

    def _next_index(self, stream: str) -> int:
        with self._lock:
            n = self._counters.get(stream, 0)
            self._counters[stream] = n + 1
            return n

    def _fraction(self, stream: str, index: int, salt: str) -> float:
        """Deterministic uniform [0, 1): SHA-256 of (seed, stream, call
        index, fault kind). Independent per salt so e.g. the latency and
        conflict decisions of one call don't correlate."""
        digest = hashlib.sha256(
            f"{self.spec.seed}:{stream}:{index}:{salt}".encode()
        ).digest()
        return int.from_bytes(digest[:8], "big") / 2**64

    def _log(self, entry: str) -> None:
        with self._lock:
            self.fault_log.append(entry)

    def _crash_decision(self, method: str, index: int) -> Optional[str]:
        """Crash verdict for one write call: None, "before", or "after".
        Explicit CrashPoints always fire; rate-based crashes draw from the
        hash stream, bounded by max_crashes so a failover scenario can
        converge once the schedule's budget is spent."""
        spec = self.spec
        for cp in spec.crash_points:
            if cp.method == method and cp.call_index == index:
                with self._lock:
                    self._crashes_fired += 1
                return "before" if cp.before_write else "after"
        if spec.crash_rate <= 0 or method in _CRASH_EXEMPT_METHODS:
            return None
        if spec.crash_methods and method not in spec.crash_methods:
            return None
        with self._lock:
            if self._crashes_fired >= spec.max_crashes:
                return None
        if self._fraction(method, index, "crash") >= spec.crash_rate:
            return None
        with self._lock:
            self._crashes_fired += 1
        return (
            "before"
            if self._fraction(method, index, "crash-variant") < 0.5
            else "after"
        )

    def _inject(self, method: str) -> Optional[int]:
        """Run the fault plan for one write call; raises the injected
        fault, sleeps the injected latency, or returns clean. Returns the
        call index when an AFTER-write crash is due (the caller raises it
        once the inner write has landed), else None."""
        index = self._next_index(method)
        spec = self.spec
        if spec.latency_rate > 0 and spec.latency_seconds > 0:
            if self._fraction(method, index, "latency") < spec.latency_rate:
                self._log(f"{method}#{index}:latency")
                time.sleep(spec.latency_seconds)
        if method in spec.exempt_methods:
            return None
        # Error/conflict injection decided BEFORE the crash decision: a
        # call that draws an injected fault never arms a crash, so the
        # crash budget is never silently consumed by a write that raised
        # without the SimulatedCrash ever firing.
        if spec.error_rate > 0 and self._fraction(method, index, "error") < spec.error_rate:
            self._log(f"{method}#{index}:error")
            raise ServerError(f"chaos: injected transient error on {method}")
        if (
            method in _CONFLICT_METHODS
            and spec.conflict_rate > 0
            and self._fraction(method, index, "conflict") < spec.conflict_rate
        ):
            self._log(f"{method}#{index}:conflict")
            raise Conflict(f"chaos: injected conflict on {method}")
        crash = self._crash_decision(method, index)
        if crash == "before":
            self._log(f"{method}#{index}:crash-before")
            raise SimulatedCrash(
                f"chaos: controller crash before {method}#{index}"
            )
        return index if crash == "after" else None

    def _note_write(self) -> None:
        """Advance the write clock and fire any scheduled preemption or
        stuck-termination hold it crossed. Fired OUTSIDE the inner call,
        after it returns, so the event lands between operations like a
        real node event."""
        with self._lock:
            self._writes_seen += 1
            due = [
                i for i, p in enumerate(self.spec.preemptions)
                if not self._preempted[i] and self._writes_seen >= p.after_writes
            ]
            for i in due:
                self._preempted[i] = True
            slice_due = [
                i for i, p in enumerate(self.spec.slice_preemptions)
                if not self._slice_preempted[i]
                and self._writes_seen >= p.after_writes
            ]
            for i in slice_due:
                self._slice_preempted[i] = True
            stuck_due = [
                i for i, s in enumerate(self.spec.stuck_terminations)
                if not self._stuck_fired[i] and self._writes_seen >= s.after_writes
            ]
            for i in stuck_due:
                self._stuck_fired[i] = True
            capacity_due = [
                i for i, c in enumerate(self.spec.capacity_revocations)
                if not self._capacity_fired[i]
                and self._writes_seen >= c.after_writes
            ]
            for i in capacity_due:
                self._capacity_fired[i] = True
        for i in due:
            p = self.spec.preemptions[i]
            self.preempt_pods(
                namespace=p.namespace, labels=p.labels,
                reason=p.reason, exit_code=p.exit_code,
            )
        for i in slice_due:
            p = self.spec.slice_preemptions[i]
            self.preempt_slice(
                job_name=p.job_name, slice_index=p.slice_index,
                namespace=p.namespace, reason=p.reason,
                exit_code=p.exit_code,
            )
        for i in stuck_due:
            s = self.spec.stuck_terminations[i]
            self.stick_terminating(
                name_contains=s.name_contains, namespace=s.namespace,
            )
        for i in capacity_due:
            self.revoke_capacity(self.spec.capacity_revocations[i].capacity)

    # ------------------------------------------------------------ proxy
    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in _WRITE_METHODS and callable(attr):
            def chaotic(*args, _method=name, _attr=attr, **kwargs):
                crash_after = self._inject(_method)
                try:
                    out = _attr(*args, **kwargs)
                except BaseException:
                    if crash_after is not None:
                        # The write itself raised: the armed after-write
                        # crash never fires (there is no "after the write
                        # landed"), so give its budget back — the schedule
                        # must not silently thin out.
                        with self._lock:
                            self._crashes_fired -= 1
                    raise
                self._note_write()
                if crash_after is not None:
                    # After-write variant: the write is durable in the
                    # backend; the process dies before seeing the response.
                    self._log(f"{_method}#{crash_after}:crash-after")
                    raise SimulatedCrash(
                        f"chaos: controller crash after {_method}#{crash_after}"
                    )
                return out

            return chaotic
        return attr

    def watch(self, kind: str, handler) -> None:
        """Register the handler behind a seeded drop filter: a dropped
        delivery is the lost-watch-event failure mode informers suffer on
        reconnects — the expectations machinery (fallback requeue, 5-min
        expiry + timeout metric) is what must absorb it."""
        spec = self.spec
        if spec.drop_watch_rate <= 0 or (
            spec.drop_watch_kinds and kind not in spec.drop_watch_kinds
        ):
            self._inner.watch(kind, handler)
            return
        registration = self._next_index(f"watch-reg:{kind}")
        stream = f"watch:{kind}:{registration}"

        def dropping(event_type, obj) -> None:
            index = self._next_index(stream)
            if self._fraction(stream, index, "drop") < spec.drop_watch_rate:
                self._log(f"{stream}#{index}:drop:{event_type}")
                return
            handler(event_type, obj)

        self._inner.watch(kind, dropping)

    # ------------------------------------------------------------- hangs
    def freeze_heartbeats(self, name_contains: str = "",
                          namespace: Optional[str] = None) -> None:
        """Direct hang lever (the preempt_pods analog): from now on, drop
        heartbeat-lease writes whose name matches — the worker wedges
        silently. Deterministic given a deterministic call point."""
        with self._lock:
            self._manual_hangs.append(ScheduledHang(
                after_writes=0, namespace=namespace,
                name_contains=name_contains,
            ))
        self._log(f"hang:freeze:{namespace or '*'}:{name_contains}")

    def thaw_heartbeats(self) -> None:
        """Release every manual hang (scheduled ones obey until_writes)."""
        with self._lock:
            self._manual_hangs.clear()
        self._log("hang:thaw")

    # -------------------------------------------------- stuck terminating
    def stick_terminating(self, name_contains: str = "",
                          namespace: Optional[str] = None) -> None:
        """Direct dead-kubelet lever (the preempt_pods analog): from now
        on, graceful deletes of matching pods wedge Terminating —
        deletionTimestamp set, object held — until force-deleted. Goes
        through the inner backend's hold_pod_termination (the in-memory
        simulator's graceful-deletion window); backends without one
        cannot host this injection."""
        hold = getattr(self._inner, "hold_pod_termination", None)
        if hold is None:
            raise TypeError(
                "chaos stuck_terminating needs a backend with "
                "hold_pod_termination (the in-memory simulator)"
            )
        hold(name_contains=name_contains, namespace=namespace)
        self._log(f"stuck-terminating:{namespace or '*'}:{name_contains}")

    def revoke_capacity(self, capacity: Optional[Dict[str, str]]) -> None:
        """Direct capacity-revocation lever (the preempt_pods analog):
        replace the backend's schedulable pool — normally with a smaller
        one. The gang-admission layer observes the shrink through its
        capacity_fn and must preempt lowest-band gangs until the
        admitted set fits again. Requires a backend with
        set_schedulable_capacity (the in-memory simulator)."""
        setter = getattr(self._inner, "set_schedulable_capacity", None)
        if setter is None:
            raise TypeError(
                "chaos revoke_capacity needs a backend with "
                "set_schedulable_capacity (the in-memory simulator)"
            )
        setter(capacity)
        self._log(
            "capacity-revoke:"
            + ",".join(f"{k}={v}" for k, v in sorted((capacity or {}).items()))
        )

    def unstick_terminating(self) -> None:
        """Release every termination hold (the kubelet coming back): held
        deletions complete, pods go away."""
        release = getattr(self._inner, "release_pod_terminations", None)
        if release is not None:
            release()
        self._log("stuck-terminating:release")

    def _hang_matches(self, namespace: str, name: str) -> bool:
        # Hangs target HEARTBEAT leases only (the documented contract): a
        # bare freeze_heartbeats() must wedge workers, never swallow the
        # operator's own leader-election Lease renewals — that would fake
        # a leadership loss and misattribute the resulting failover.
        if not name.endswith(HEARTBEAT_LEASE_SUFFIX):
            return False
        with self._lock:
            writes = self._writes_seen
            hangs = list(self.spec.hangs) + self._manual_hangs
        for h in hangs:
            if writes < h.after_writes:
                continue
            if h.until_writes is not None and writes >= h.until_writes:
                continue
            if h.namespace is not None and h.namespace != namespace:
                continue
            if h.name_contains and h.name_contains not in name:
                continue
            return True
        return False

    def create_lease(self, lease: dict) -> dict:
        meta = lease.get("metadata") or {}
        ns, name = meta.get("namespace", "default"), meta.get("name", "")
        if self._hang_matches(ns, name):
            self._log(f"hang:{ns}/{name}:drop-create")
            return lease  # swallowed: the beat never reaches the cluster
        self._inject("create_lease")
        return self._inner.create_lease(lease)

    def update_lease(self, lease: dict) -> dict:
        meta = lease.get("metadata") or {}
        ns, name = meta.get("namespace", "default"), meta.get("name", "")
        if self._hang_matches(ns, name):
            self._log(f"hang:{ns}/{name}:drop-renew")
            return lease
        if self._renew_dropped(ns, name, lease):
            return lease  # swallowed: the holder believes it renewed
        self._maybe_steal(ns, name, lease)
        self._inject("update_lease")
        return self._inner.update_lease(lease)

    def _renew_dropped(self, ns: str, name: str, lease: dict) -> bool:
        """Delayed-renew injection: matching renewals inside a planted
        window vanish without an error — the holder's lock records a
        successful renew while the stored lease ages toward stealability."""
        holder = str((lease.get("spec") or {}).get("holderIdentity") or "")
        dropped = False
        for i, delay in enumerate(self.spec.renew_delays):
            if delay.name_contains and delay.name_contains not in name:
                continue
            if delay.holder_contains and delay.holder_contains not in holder:
                continue
            if delay.namespace is not None and delay.namespace != ns:
                continue
            idx = self._next_index(f"renew-delay:{i}")
            if delay.after_renews <= idx < delay.after_renews + delay.drop_renews:
                self._log(f"renew-delay:{ns}/{name}#{idx}:drop")
                dropped = True
        return dropped

    def _maybe_steal(self, ns: str, name: str, lease: dict) -> None:
        """Lease-steal injection: write the rival over the stored lease
        BEFORE the caller's matching renew, so the caller pays the same
        Conflict a real losing racer pays and must re-observe the (now
        foreign, freshly-renewed) lease for a full duration before it can
        steal back."""
        for i, steal in enumerate(self.spec.lease_steals):
            if steal.name_contains and steal.name_contains not in name:
                continue
            if steal.namespace is not None and steal.namespace != ns:
                continue
            idx = self._next_index(f"lease-steal:{i}")
            if idx != steal.at_renew:
                continue
            try:
                current = self._inner.get_lease(ns, name)
            except Exception:  # noqa: BLE001 — nothing to steal
                continue
            cspec = current.setdefault("spec", {})
            cspec["holderIdentity"] = steal.rival
            cspec["leaseTransitions"] = int(cspec.get("leaseTransitions") or 0) + 1
            caller_renew = (lease.get("spec") or {}).get("renewTime")
            if caller_renew:
                cspec["renewTime"] = caller_renew
            try:
                self._inner.update_lease(current)
            except Exception:  # noqa: BLE001 — raced away; the log stays honest
                continue
            self._log(f"lease-steal:{ns}/{name}#{idx}:{steal.rival}")

    # ------------------------------------------------------- preemption
    def preempt_pods(
        self,
        namespace: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
        reason: str = "Preempted",
        exit_code: int = 137,
    ) -> int:
        """Node-scoped batch kill: every matching pod gets a
        DisruptionTarget condition + disruption status reason and flips to
        Failed with a SIGKILL-class exit code, in one batch — a simulated
        TPU slice-host preemption/maintenance event. Goes through the
        public get/update surface so it works against ANY backend, and
        bypasses the fault plan (the infrastructure doing the preempting
        is not subject to it). Returns the number of pods killed."""
        killed = 0
        for pod in self._inner.list_pods(namespace=namespace, labels=labels):
            if pod.metadata.deletion_timestamp is not None:
                continue
            if pod.status.phase == POD_FAILED:
                continue
            pod.status.phase = POD_FAILED
            pod.status.reason = reason
            pod.status.conditions.append(
                PodCondition(
                    type=POD_CONDITION_DISRUPTION_TARGET,
                    status="True",
                    reason=reason,
                    message="chaos: simulated slice-host preemption",
                )
            )
            cname = pod.spec.containers[0].name if pod.spec.containers else ""
            pod.status.container_statuses = [
                ContainerStatus(
                    name=cname,
                    state=ContainerState(
                        terminated=ContainerStateTerminated(
                            exit_code=exit_code, reason=reason
                        )
                    ),
                )
            ]
            self._inner.update_pod(pod)
            self._log(
                f"preempt:{pod.metadata.namespace}/{pod.metadata.name}"
                f":{reason}:{exit_code}"
            )
            killed += 1
        return killed

    def preempt_slice(
        self,
        job_name: str,
        slice_index: int,
        namespace: Optional[str] = None,
        reason: str = "Preempted",
        exit_code: int = 137,
    ) -> int:
        """Slice-targeted batch kill (the ScheduledSlicePreemption lever):
        every pod of `job_name` stamped with the matching tpu-slice-index
        label dies in one event — a reclaimed slice takes all its hosts
        at once, and ONLY its hosts. Selection is by the label the
        controllers stamp on every slice-shaped pod, so the kill set is
        exactly the restart domain the engine must scope its teardown
        to. Fault-log entries ride the same `preempt:` prefix with a
        slice marker, so replay diffs show which slice went."""
        killed = self.preempt_pods(
            namespace=namespace,
            labels={
                LABEL_JOB_NAME: job_name,
                LABEL_SLICE_INDEX: str(slice_index),
            },
            reason=reason,
            exit_code=exit_code,
        )
        self._log(
            f"preempt-slice:{namespace or '*'}/{job_name}"
            f":slice-{slice_index}:killed={killed}"
        )
        return killed
