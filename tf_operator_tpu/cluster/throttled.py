"""Write-throttling cluster proxy.

The reference enforces --qps/--burst at the rest-client layer
(options.go:73-83, client-go rate limiter), so EVERY apiserver write —
pods, services, events, status patches, pod groups — draws from one
budget. This proxy reproduces that: controllers talk to the cluster
through it, and each write acquires from the shared TokenBucket before
delegating. Reads and watches pass through unthrottled (informer traffic
is cache-backed in both worlds).
"""

from __future__ import annotations

import time

from ..core.control import TokenBucket
from .base import Cluster

_WRITE_METHODS = (
    "create_job",
    "update_job",
    "update_job_status",
    # The coalesced single-request status apply pays the same budget
    # token as the two-request read-modify-write it replaces.
    "patch_job_status",
    "delete_job",
    "create_pod",
    "update_pod",
    # delete_pod's kwargs (force=True grace-period-0 escalation) pass
    # through untouched — a force delete pays the same budget token as
    # any other write.
    "delete_pod",
    "create_service",
    "update_service",
    "delete_service",
    "record_event",
    "create_pod_group",
    "delete_pod_group",
)


class ThrottledCluster:
    """Delegates everything to `inner`; write methods pay the bucket.
    `supports_concurrent_writes` passes through untouched (__getattr__
    reaches the inner backend's verdict): throttling changes WHEN a write
    may go, never whether concurrent callers are safe — the bucket itself
    is FIFO-fair under contention."""

    def __init__(self, inner: Cluster, limiter: TokenBucket):
        self._inner = inner
        self._limiter = limiter

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in _WRITE_METHODS and callable(attr):
            limiter = self._limiter

            def throttled(*args, **kwargs):
                limiter.acquire()
                return attr(*args, **kwargs)

            return throttled
        return attr


class LatencyCluster:
    """Per-write latency proxy: every write sleeps `latency_seconds`
    before delegating — a dependency-free stand-in for the apiserver
    round trip the in-memory backend doesn't charge. This is what makes
    serial-vs-parallel fan-out measurable on `InMemoryCluster` (the
    scale benchmark and the concurrency-stress large-gang test): with
    free writes, 32 sequential creates and 6 slow-start waves cost the
    same; with a round trip, parallelism overlaps it.

    Sleeps happen OUTSIDE any lock and the proxy keeps no mutable state,
    so it is exactly as concurrency-safe as its inner backend."""

    def __init__(self, inner: Cluster, latency_seconds: float):
        self._inner = inner
        self._latency = latency_seconds
        self.supports_concurrent_writes = getattr(
            inner, "supports_concurrent_writes", False
        )
        self.supports_concurrent_syncs = getattr(
            inner, "supports_concurrent_syncs", False
        )

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in _WRITE_METHODS and callable(attr):
            latency = self._latency

            def delayed(*args, **kwargs):
                time.sleep(latency)
                return attr(*args, **kwargs)

            return delayed
        return attr
