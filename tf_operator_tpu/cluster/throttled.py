"""Write-throttling cluster proxy.

The reference enforces --qps/--burst at the rest-client layer
(options.go:73-83, client-go rate limiter), so EVERY apiserver write —
pods, services, events, status patches, pod groups — draws from one
budget. This proxy reproduces that: controllers talk to the cluster
through it, and each write acquires from the shared TokenBucket before
delegating. Reads and watches pass through unthrottled (informer traffic
is cache-backed in both worlds).
"""

from __future__ import annotations

from ..core.control import TokenBucket
from .base import Cluster

_WRITE_METHODS = (
    "create_job",
    "update_job",
    "update_job_status",
    "delete_job",
    "create_pod",
    "update_pod",
    # delete_pod's kwargs (force=True grace-period-0 escalation) pass
    # through untouched — a force delete pays the same budget token as
    # any other write.
    "delete_pod",
    "create_service",
    "update_service",
    "delete_service",
    "record_event",
    "create_pod_group",
    "delete_pod_group",
)


class ThrottledCluster:
    """Delegates everything to `inner`; write methods pay the bucket."""

    def __init__(self, inner: Cluster, limiter: TokenBucket):
        self._inner = inner
        self._limiter = limiter

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in _WRITE_METHODS and callable(attr):
            limiter = self._limiter

            def throttled(*args, **kwargs):
                limiter.acquire()
                return attr(*args, **kwargs)

            return throttled
        return attr
