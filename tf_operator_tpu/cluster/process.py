"""Process-backed cluster: pods run as real OS subprocesses.

This is the e2e tier the reference gets from a kind/EKS cluster (SURVEY.md
§4 T3, §7 stage 3): the operator's full output — pod specs with injected
bootstrap env, headless services, gang groups — is materialized for real.
Each Pod's first container is launched as a local subprocess with exactly
the env the controller injected, so `jax.distributed` rendezvous, exit-code
restart policies, and log collection are exercised against live processes,
not simulated phases.

Networking: headless-service DNS ("<job>-<type>-<i>.<ns>.svc[:port]") cannot
resolve on a dev box, so every env value is rewritten through a loopback
alias map — each service host gets its own stable 127.0.0.0/8 address
(bindable and dialable on Linux with no configuration) and keeps its
declared port, the same mapping for every pod that references it. The
coordinator address all replicas agree on therefore points at the address
worker-0 actually binds. Tests reach a workload (e.g. the controllable
test-server) through ``resolve(host, port)``.

Scheduling follows InMemoryCluster semantics: pods stay Pending until their
gang (pod-slice) is complete, then launch; a background reaper promotes
started pods to Running and rolls exit codes into containerStatuses exactly
as a kubelet would.
"""

from __future__ import annotations

import logging
import os
import re
import signal
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..api.k8s import (
    POD_FAILED,
    POD_PENDING,
    POD_RUNNING,
    POD_SUCCEEDED,
    ContainerState,
    ContainerStateTerminated,
    ContainerStatus,
    Pod,
)
from ..bootstrap import heartbeat as hb_bootstrap
from ..runtime import heartbeat as hb_runtime
from .base import NotFound
from .memory import InMemoryCluster

_log = logging.getLogger(__name__)

# "<name>.<ns>.svc[.<domain>]" with an optional ":<port>", the shape
# bootstrap/tf_config.replica_service_host emits.
_SVC_RE = re.compile(
    r"\b([a-z0-9]([a-z0-9-]*[a-z0-9])?\.[a-z0-9-]+\.svc(?:\.[a-z0-9.-]+)?)(?::(\d+))?"
)

# "<job>-<replicatype>-<index>", the gen_general_name shape.
_BARE_NAME_RE = re.compile(r"[a-z0-9][a-z0-9-]*-[a-z0-9]+-\d+")

# Env vars whose values are known to carry bare service hostnames (the
# c10d/DMLC/Rabit/libtpu contracts). Only these get the shape-heuristic
# rewrite — a user variable that merely looks like "<a>-<b>-<N>" must not
# be corrupted.
_HOST_ENV_VARS = {
    "MASTER_ADDR",
    "DMLC_PS_ROOT_URI",
    "WORKER_ADDRS",
    "TPU_WORKER_HOSTNAMES",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "MX_CONFIG",  # JSON: cluster urls are bare generated names
}


# Loopback alias pool: every 127.0.0.0/8 address is bindable/dialable on
# Linux without configuration, so each service host gets its OWN IP and
# keeps its declared port — no cross-host port collisions, and env vars
# that carry host and port separately (MASTER_ADDR / MASTER_PORT) stay
# consistent after rewriting.
_IP_BASE = (127, 0, 10, 1)


class LocalProcessCluster(InMemoryCluster):
    # Pod creates fork real subprocesses and juggle per-pod log file
    # handles outside the store lock; keep the engine's fan-out
    # sequential here (the e2e tier's determinism also leans on stable
    # launch order for the loopback-alias IP assignment). Same verdict
    # for the sync-worker pool: it must override the InMemoryCluster
    # base's True, or the e2e tier would launch subprocesses from
    # concurrent syncs.
    supports_concurrent_writes = False
    supports_concurrent_syncs = False
    # Must override the InMemoryCluster base's True: the e2e tier's
    # assertions read job status straight off the store between steps
    # (coalesced deferral would make those reads racy), and its launch
    # ordering leans on the strictly-serial write sequence.
    supports_write_coalescing = False
    supports_watch_cache = False

    def __init__(
        self,
        clock=time.time,
        log_dir: Optional[str] = None,
        poll_interval: float = 0.05,
        child_env: Optional[Dict[str, str]] = None,
    ):
        super().__init__(clock)
        self._log_dir = log_dir or tempfile.mkdtemp(prefix="tpu-operator-pods-")
        self._poll_interval = poll_interval
        # Extra env overlaid on every child (after the pod's own env).
        self._child_env = dict(child_env or {})
        self._procs: Dict[Tuple[str, str], subprocess.Popen] = {}
        self._launching: set = set()
        self._log_fhs: Dict[Tuple[str, str], object] = {}
        self._log_paths: Dict[Tuple[str, str], str] = {}
        self._attempts: Dict[Tuple[str, str], int] = {}
        self._ip_map: Dict[Tuple[str, str], str] = {}
        # Heartbeat file bridge (gang liveness): pod key -> (file path,
        # lease name, lease namespace, last seq seen). The reaper reads
        # each live pod's beat file and replays fresh beats as Lease
        # renewals through the Cluster seam — this process is the
        # kubelet-analog, so the operator sees the identical protocol it
        # sees on a real cluster.
        self._hb_bridge: Dict[Tuple[str, str], list] = {}
        self._stopped = threading.Event()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()

    # ----------------------------------------------------------- ip mapping
    def resolve(self, host: str, port: int = 0, namespace: str = "default") -> Tuple[str, int]:
        """Loopback address a service DNS name maps to. Stable per
        (service, namespace); the declared port is preserved. A FQDN carries
        its own namespace; `namespace` disambiguates bare names."""
        with self._lock:
            return self._mapped_ip_locked(host, namespace), int(port)

    def _mapped_ip_locked(self, host: str, namespace: str) -> str:
        # Short name and FQDN of the same service must agree; same-named
        # services in different namespaces must NOT.
        labels = host.split(".")
        if len(labels) >= 3 and labels[2] == "svc":
            key = (labels[0], labels[1])
        else:
            key = (labels[0], namespace)
        if key not in self._ip_map:
            n = len(self._ip_map)
            a, b, c, d = _IP_BASE
            self._ip_map[key] = f"{a}.{b}.{c + (d + n) // 256}.{(d + n) % 256}"
        return self._ip_map[key]

    def _rewrite_locked(self, value: str, namespace: str, allow_bare: bool) -> str:
        def sub(m: re.Match) -> str:
            host, _, port = m.groups()
            ip = self._mapped_ip_locked(host, namespace)
            return ip if port is None else f"{ip}:{port}"

        value = _SVC_RE.sub(sub, value)
        # Known services referenced by bare name — including inside JSON
        # payloads like MX_CONFIG — rewritten with word boundaries so
        # "j-worker-0" cannot clobber "j-worker-01".
        for (svc_ns, name) in list(self._services):
            if svc_ns == namespace and name in value:
                value = re.sub(
                    rf"\b{re.escape(name)}\b",
                    self._mapped_ip_locked(name, namespace),
                    value,
                )
        if not allow_bare:
            return value
        if value.lstrip().startswith("{"):
            # JSON payload (MX_CONFIG): bare generated-name-shaped hosts sit
            # inside quoted "url" strings, possibly before their service
            # object exists — rewrite them in place with word boundaries.
            return _BARE_NAME_RE.sub(
                lambda m: self._mapped_ip_locked(m.group(0), namespace), value
            )
        # Host-carrying env vars (c10d/DMLC/Rabit contracts emit
        # "<job>-<type>-<idx>" relying on the namespace DNS search path —
        # reference pytorch.go:46-53): rewrite generated-name-shaped items
        # even before their service object exists.
        items = []
        for item in value.split(","):
            host, sep, port = item.partition(":")
            if host != "localhost" and _BARE_NAME_RE.fullmatch(host):
                item = self._mapped_ip_locked(host, namespace) + sep + port
            items.append(item)
        return ",".join(items)

    # ----------------------------------------------------------- scheduling
    def create_pod(self, pod: Pod) -> Pod:
        out = super().create_pod(pod)
        self._schedule_pass()
        return out

    def create_pod_group(self, group: dict) -> dict:
        out = super().create_pod_group(group)
        self._schedule_pass()
        return out

    def _schedule_pass(self) -> None:
        """Launch every Pending pod whose gang is complete.

        fork/exec happens OUTSIDE the cluster lock (it is tens of ms per
        pod; holding the lock would stall every watch/list during an N-pod
        gang launch): decide + reserve under the lock, spawn unlocked, then
        commit the result under the lock again.
        """
        plans = []  # (key, cmd, env, cwd, log_path)
        with self._lock:
            for key, pod in list(self._pods.items()):
                if (
                    pod.status.phase != POD_PENDING
                    or key in self._procs
                    or key in self._launching
                ):
                    continue
                if not self._gang_schedulable(pod):
                    continue
                container = pod.spec.containers[0] if pod.spec.containers else None
                cmd = (
                    (list(container.command) + list(container.args))
                    if container
                    else []
                )
                if not cmd:
                    self._mark_start_error_locked(pod, "no container command to execute")
                    continue
                env = dict(os.environ)
                for e in container.env:
                    env[e.name] = self._rewrite_locked(
                        e.value,
                        pod.metadata.namespace,
                        allow_bare=e.name in _HOST_ENV_VARS,
                    )
                env.update(self._child_env)
                env.setdefault("PYTHONUNBUFFERED", "1")
                attempt = self._attempts.get(key, 0) + 1
                self._attempts[key] = attempt
                log_path = os.path.join(
                    self._log_dir, f"{key[0]}__{key[1]}.{attempt}.log"
                )
                if env.get(hb_bootstrap.ENV_HEARTBEAT_LEASE):
                    # Heartbeat-enabled pod: point the child at a beat
                    # file (real apiserver auth doesn't exist here) and
                    # arm the reaper's file->Lease bridge for it.
                    hb_path = os.path.join(
                        self._log_dir, f"{key[0]}__{key[1]}.{attempt}.hb"
                    )
                    env[hb_bootstrap.ENV_HEARTBEAT_FILE] = hb_path
                    self._hb_bridge[key] = [
                        hb_path,
                        env[hb_bootstrap.ENV_HEARTBEAT_LEASE],
                        env.get(hb_bootstrap.ENV_HEARTBEAT_NAMESPACE, key[0]),
                        None,
                    ]
                self._launching.add(key)
                plans.append((key, cmd, env, container.working_dir or None, log_path))

        for key, cmd, env, cwd, log_path in plans:
            fh = open(log_path, "ab")
            proc = None
            error = None
            try:
                proc = subprocess.Popen(
                    cmd,
                    env=env,
                    stdout=fh,
                    stderr=subprocess.STDOUT,
                    cwd=cwd,
                    start_new_session=True,  # own pgid: kill takes the whole tree
                )
            except OSError as exc:
                error = str(exc)
            with self._lock:
                self._launching.discard(key)
                pod = self._pods.get(key)
                if pod is None or pod.status.phase != POD_PENDING:
                    # Deleted (or force-phased by a test) while we forked.
                    fh.close()
                    if proc is not None:
                        _kill_tree(proc)
                    continue
                if error is not None:
                    fh.close()
                    self._mark_start_error_locked(pod, error)
                    continue
                self._procs[key] = proc
                self._log_fhs[key] = fh
                self._log_paths[key] = log_path
                pod.status.phase = POD_RUNNING
                pod.status.start_time = self._clock()
                pod.metadata.resource_version = str(next(self._rv))
                self._publish_locked("pods", "MODIFIED", pod.deep_copy())
        self._drain_events()

    def _mark_start_error_locked(self, pod: Pod, message: str) -> None:
        pod.status.phase = POD_FAILED
        pod.status.reason = "StartError"
        pod.status.message = message
        pod.metadata.resource_version = str(next(self._rv))
        self._publish_locked("pods", "MODIFIED", pod.deep_copy())

    # --------------------------------------------------------------- reaper
    def _reap_loop(self) -> None:
        while not self._stopped.wait(self._poll_interval):
            try:
                self._schedule_pass()
                self._reap_once()
                self._bridge_heartbeats()
            except Exception:
                if self._stopped.is_set():  # teardown race: expected
                    return
                _log.exception("process-cluster reaper pass failed")

    def _reap_once(self) -> None:
        with self._lock:
            for key, proc in list(self._procs.items()):
                code = proc.poll()
                if code is None:
                    continue
                pod = self._pods.get(key)
                self._procs.pop(key, None)
                fh = self._log_fhs.pop(key, None)
                if fh is not None:
                    fh.close()
                if pod is None or pod.status.phase not in (POD_RUNNING, POD_PENDING):
                    continue
                # Negative returncode = killed by signal; kubelet reports
                # 128+signum for signal deaths.
                exit_code = code if code >= 0 else 128 - code
                pod.status.phase = POD_SUCCEEDED if exit_code == 0 else POD_FAILED
                cname = pod.spec.containers[0].name if pod.spec.containers else ""
                pod.status.container_statuses = [
                    ContainerStatus(
                        name=cname,
                        state=ContainerState(
                            terminated=ContainerStateTerminated(
                                exit_code=exit_code, finished_at=self._clock()
                            )
                        ),
                    )
                ]
                pod.metadata.resource_version = str(next(self._rv))
                self._publish_locked("pods", "MODIFIED", pod.deep_copy())
        self._drain_events()

    def _bridge_heartbeats(self) -> None:
        """Replay fresh file beats as Lease renewals (the kubelet-analog
        half of the heartbeat contract). Only pods with a LIVE process are
        bridged: a SIGSTOPped child stops writing and therefore stops
        renewing — precisely the silent wedge the operator must detect."""
        with self._lock:
            entries = [
                (key, state) for key, state in self._hb_bridge.items()
                if key in self._procs
            ]
        for key, state in entries:
            path, lease_name, lease_ns, last_seq = state
            beat = hb_runtime.read_heartbeat_file(path)
            if beat is None or beat.get("seq") == last_seq:
                continue
            state[3] = beat.get("seq")
            step = beat.get("step")
            tps = beat.get("tokens_per_sec")
            ckpt = beat.get("checkpoint_step")
            peer = beat.get("peer_addr")
            restore = beat.get("restore")
            hb_runtime.publish_heartbeat(
                self, lease_ns, lease_name, identity=key[1],
                step=int(step) if isinstance(step, (int, float)) else None,
                tokens_per_sec=(
                    float(tps) if isinstance(tps, (int, float)) else None
                ),
                checkpoint_step=(
                    int(ckpt) if isinstance(ckpt, (int, float)) else None
                ),
                peer_addr=peer if isinstance(peer, str) else None,
                restore=restore if isinstance(restore, str) else None,
            )

    def kill_pod(self, namespace: str, name: str, sig: int = signal.SIGKILL) -> None:
        """Fault injection: signal the pod's process WITHOUT deleting the
        pod object — the reaper then observes the death exactly as a kubelet
        would a preempted container (SIGKILL -> exit 137, retryable under
        ExitCode policy). This is the e2e lever for restart-MTTR and
        resume-from-checkpoint scenarios."""
        with self._lock:
            proc = self._procs.get((namespace, name))
        if proc is None:
            raise NotFound(f"pod {namespace}/{name} has no live process")
        proc.send_signal(sig)

    # ------------------------------------------------------------- deletion
    def delete_pod(self, namespace: str, name: str, force: bool = False) -> None:
        key = (namespace, name)
        with self._lock:
            proc = self._procs.pop(key, None)
            fh = self._log_fhs.pop(key, None)
            # NotFound contract: a deleted pod has no log (a same-name
            # recreation gets a fresh attempt file at launch).
            self._log_paths.pop(key, None)
            self._hb_bridge.pop(key, None)
        if proc is not None:
            if force:
                # Grace-period-0: no SIGTERM courtesy window — straight
                # SIGKILL, like a kubelet executing a force delete.
                _kill_tree(proc, grace=False)
            else:
                _kill_tree(proc)
        if fh is not None:
            fh.close()
        super().delete_pod(namespace, name, force=force)

    def get_pod_log(self, namespace: str, name: str) -> str:
        key = (namespace, name)
        with self._lock:
            path = self._log_paths.get(key)
        if path and os.path.exists(path):
            with open(path, "rb") as f:
                return f.read().decode("utf-8", errors="replace")
        return super().get_pod_log(namespace, name)

    def stream_pod_log(self, namespace: str, name: str, follow: bool = False,
                       poll_interval: float = 0.2, stop=None):
        """Seek-based tail of the pod's log file: each poll reads only the
        appended bytes (the generic base implementation re-reads the whole
        log every poll — O(n^2) over a long follow). The stream is bound to
        one pod incarnation: a same-name replacement (restart flow) has a
        new log file, so a UID change ends this stream rather than silently
        tailing the dead file forever. Multibyte UTF-8 split across read
        boundaries decodes incrementally, not per-chunk."""
        import codecs
        import time as time_mod

        key = (namespace, name)
        with self._lock:
            path = self._log_paths.get(key)
        if not (path and os.path.exists(path)):
            yield from super().stream_pod_log(
                namespace, name, follow=follow, poll_interval=poll_interval,
                stop=stop,
            )
            return
        try:
            uid = self.get_pod(namespace, name).metadata.uid
        except NotFound:
            return
        decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")
        with open(path, "rb") as f:
            while not (stop is not None and stop.is_set()):
                chunk = f.read()
                if chunk:
                    text = decoder.decode(chunk)
                    if text:
                        yield text
                if not follow:
                    return
                try:
                    pod = self.get_pod(namespace, name)
                except NotFound:
                    return
                if pod.metadata.uid != uid:
                    return  # replaced: its output lives in a new file
                if pod.status.phase in ("Succeeded", "Failed"):
                    final = decoder.decode(f.read(), final=True)
                    if final:
                        yield final
                    return
                time_mod.sleep(poll_interval)

    def step(self) -> None:
        """Manual tick: trigger a scheduling pass + reap (the background
        reaper usually does both)."""
        self._schedule_pass()
        self._reap_once()

    def shutdown(self) -> None:
        """Kill every child process and stop the reaper. Call in teardown."""
        self._stopped.set()
        with self._lock:
            procs = list(self._procs.values())
            fhs = list(self._log_fhs.values())
            self._procs.clear()
            self._log_fhs.clear()
        for proc in procs:
            _kill_tree(proc)
        for fh in fhs:
            fh.close()
        self._reaper.join(timeout=2.0)


def _kill_tree(proc: subprocess.Popen, grace: bool = True) -> None:
    """SIGTERM-then-SIGKILL (grace=True, the kubelet's normal teardown) or
    straight SIGKILL (grace=False, a force delete). Either way the SIGKILL
    is followed by a bounded reap so the Popen doesn't linger as a zombie
    (the proc was already popped from the cluster's tables, so no reaper
    thread will ever wait() it)."""
    if grace:
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError, OSError):
            pass
        try:
            proc.wait(timeout=2.0)
            return
        except subprocess.TimeoutExpired:
            pass
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        pass
    try:
        proc.wait(timeout=2.0)
    except subprocess.TimeoutExpired:
        pass  # D-state straggler: nothing more a SIGKILL sender can do
