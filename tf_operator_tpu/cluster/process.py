"""Process-backed cluster: pods run as real OS subprocesses.

This is the e2e tier the reference gets from a kind/EKS cluster (SURVEY.md
§4 T3, §7 stage 3): the operator's full output — pod specs with injected
bootstrap env, headless services, gang groups — is materialized for real.
Each Pod's first container is launched as a local subprocess with exactly
the env the controller injected, so `jax.distributed` rendezvous, exit-code
restart policies, and log collection are exercised against live processes,
not simulated phases.

Networking: headless-service DNS ("<job>-<type>-<i>.<ns>.svc[:port]") cannot
resolve on a dev box, so every env value is rewritten through a loopback
port map — each (service-host, port) pair gets a stable 127.0.0.1 port, the
same mapping for every pod that references it. The coordinator address all
replicas agree on therefore points at the port worker-0 actually binds.
Tests reach a workload (e.g. the controllable test-server) through
``resolve(host, port)``.

Scheduling follows InMemoryCluster semantics: pods stay Pending until their
gang (pod-slice) is complete, then launch; a background reaper promotes
started pods to Running and rolls exit codes into containerStatuses exactly
as a kubelet would.
"""

from __future__ import annotations

import logging
import os
import re
import signal
import socket
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..api.k8s import (
    POD_FAILED,
    POD_PENDING,
    POD_RUNNING,
    POD_SUCCEEDED,
    ContainerState,
    ContainerStateTerminated,
    ContainerStatus,
    Pod,
)
from .memory import InMemoryCluster

_log = logging.getLogger(__name__)

# "<name>.<ns>.svc[.<domain>]" with an optional ":<port>", the shape
# bootstrap/tf_config.replica_service_host emits.
_SVC_RE = re.compile(
    r"\b([a-z0-9]([a-z0-9-]*[a-z0-9])?\.[a-z0-9-]+\.svc(?:\.[a-z0-9.-]+)?)(?::(\d+))?"
)


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class LocalProcessCluster(InMemoryCluster):
    def __init__(
        self,
        clock=time.time,
        log_dir: Optional[str] = None,
        poll_interval: float = 0.05,
        child_env: Optional[Dict[str, str]] = None,
    ):
        super().__init__(clock)
        self._log_dir = log_dir or tempfile.mkdtemp(prefix="tpu-operator-pods-")
        self._poll_interval = poll_interval
        # Extra env overlaid on every child (after the pod's own env).
        self._child_env = dict(child_env or {})
        self._procs: Dict[Tuple[str, str], subprocess.Popen] = {}
        self._launching: set = set()
        self._log_fhs: Dict[Tuple[str, str], object] = {}
        self._log_paths: Dict[Tuple[str, str], str] = {}
        self._attempts: Dict[Tuple[str, str], int] = {}
        self._port_map: Dict[Tuple[str, int], int] = {}
        self._stopped = threading.Event()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()

    # --------------------------------------------------------- port mapping
    def resolve(self, host: str, port: int) -> Tuple[str, int]:
        """Loopback address a service DNS name maps to. Stable per
        (host, port); allocates on first use."""
        with self._lock:
            return "127.0.0.1", self._mapped_port_locked(host, port)

    def _mapped_port_locked(self, host: str, port: int) -> int:
        key = (host, int(port))
        if key not in self._port_map:
            self._port_map[key] = _free_port()
        return self._port_map[key]

    def _rewrite_locked(self, value: str) -> str:
        def sub(m: re.Match) -> str:
            host, _, port = m.groups()
            if port is None:
                return "127.0.0.1"
            return f"127.0.0.1:{self._mapped_port_locked(host, int(port))}"

        return _SVC_RE.sub(sub, value)

    # ----------------------------------------------------------- scheduling
    def create_pod(self, pod: Pod) -> Pod:
        out = super().create_pod(pod)
        self._schedule_pass()
        return out

    def create_pod_group(self, group: dict) -> dict:
        out = super().create_pod_group(group)
        self._schedule_pass()
        return out

    def _schedule_pass(self) -> None:
        """Launch every Pending pod whose gang is complete.

        fork/exec happens OUTSIDE the cluster lock (it is tens of ms per
        pod; holding the lock would stall every watch/list during an N-pod
        gang launch): decide + reserve under the lock, spawn unlocked, then
        commit the result under the lock again.
        """
        plans = []  # (key, cmd, env, cwd, log_path)
        with self._lock:
            for key, pod in list(self._pods.items()):
                if (
                    pod.status.phase != POD_PENDING
                    or key in self._procs
                    or key in self._launching
                ):
                    continue
                if not self._gang_schedulable(pod):
                    continue
                container = pod.spec.containers[0] if pod.spec.containers else None
                cmd = (
                    (list(container.command) + list(container.args))
                    if container
                    else []
                )
                if not cmd:
                    self._mark_start_error_locked(pod, "no container command to execute")
                    continue
                env = dict(os.environ)
                for e in container.env:
                    env[e.name] = self._rewrite_locked(e.value)
                env.update(self._child_env)
                env.setdefault("PYTHONUNBUFFERED", "1")
                attempt = self._attempts.get(key, 0) + 1
                self._attempts[key] = attempt
                log_path = os.path.join(
                    self._log_dir, f"{key[0]}__{key[1]}.{attempt}.log"
                )
                self._launching.add(key)
                plans.append((key, cmd, env, container.working_dir or None, log_path))

        started: List[Pod] = []
        for key, cmd, env, cwd, log_path in plans:
            fh = open(log_path, "ab")
            proc = None
            error = None
            try:
                proc = subprocess.Popen(
                    cmd,
                    env=env,
                    stdout=fh,
                    stderr=subprocess.STDOUT,
                    cwd=cwd,
                    start_new_session=True,  # own pgid: kill takes the whole tree
                )
            except OSError as exc:
                error = str(exc)
            with self._lock:
                self._launching.discard(key)
                pod = self._pods.get(key)
                if pod is None or pod.status.phase != POD_PENDING:
                    # Deleted (or force-phased by a test) while we forked.
                    fh.close()
                    if proc is not None:
                        _kill_tree(proc)
                    continue
                if error is not None:
                    fh.close()
                    self._mark_start_error_locked(pod, error)
                    started.append(pod.deep_copy())
                    continue
                self._procs[key] = proc
                self._log_fhs[key] = fh
                self._log_paths[key] = log_path
                pod.status.phase = POD_RUNNING
                pod.status.start_time = self._clock()
                pod.metadata.resource_version = str(next(self._rv))
                started.append(pod.deep_copy())
        for pod in started:
            self._emit("pods", "MODIFIED", pod)

    def _mark_start_error_locked(self, pod: Pod, message: str) -> None:
        pod.status.phase = POD_FAILED
        pod.status.reason = "StartError"
        pod.status.message = message
        pod.metadata.resource_version = str(next(self._rv))

    # --------------------------------------------------------------- reaper
    def _reap_loop(self) -> None:
        while not self._stopped.wait(self._poll_interval):
            try:
                self._schedule_pass()
                self._reap_once()
            except Exception:
                if self._stopped.is_set():  # teardown race: expected
                    return
                _log.exception("process-cluster reaper pass failed")

    def _reap_once(self) -> None:
        finished: List[Pod] = []
        with self._lock:
            for key, proc in list(self._procs.items()):
                code = proc.poll()
                if code is None:
                    continue
                pod = self._pods.get(key)
                self._procs.pop(key, None)
                fh = self._log_fhs.pop(key, None)
                if fh is not None:
                    fh.close()
                if pod is None or pod.status.phase not in (POD_RUNNING, POD_PENDING):
                    continue
                # Negative returncode = killed by signal; kubelet reports
                # 128+signum for signal deaths.
                exit_code = code if code >= 0 else 128 - code
                pod.status.phase = POD_SUCCEEDED if exit_code == 0 else POD_FAILED
                cname = pod.spec.containers[0].name if pod.spec.containers else ""
                pod.status.container_statuses = [
                    ContainerStatus(
                        name=cname,
                        state=ContainerState(
                            terminated=ContainerStateTerminated(
                                exit_code=exit_code, finished_at=self._clock()
                            )
                        ),
                    )
                ]
                pod.metadata.resource_version = str(next(self._rv))
                finished.append(pod.deep_copy())
        for pod in finished:
            self._emit("pods", "MODIFIED", pod)

    # ------------------------------------------------------------- deletion
    def delete_pod(self, namespace: str, name: str) -> None:
        key = (namespace, name)
        with self._lock:
            proc = self._procs.pop(key, None)
            fh = self._log_fhs.pop(key, None)
            # NotFound contract: a deleted pod has no log (a same-name
            # recreation gets a fresh attempt file at launch).
            self._log_paths.pop(key, None)
        if proc is not None:
            _kill_tree(proc)
        if fh is not None:
            fh.close()
        super().delete_pod(namespace, name)

    def get_pod_log(self, namespace: str, name: str) -> str:
        key = (namespace, name)
        with self._lock:
            path = self._log_paths.get(key)
        if path and os.path.exists(path):
            with open(path, "rb") as f:
                return f.read().decode("utf-8", errors="replace")
        return super().get_pod_log(namespace, name)

    def step(self) -> None:
        """Manual tick: trigger a scheduling pass + reap (the background
        reaper usually does both)."""
        self._schedule_pass()
        self._reap_once()

    def shutdown(self) -> None:
        """Kill every child process and stop the reaper. Call in teardown."""
        self._stopped.set()
        with self._lock:
            procs = list(self._procs.values())
            fhs = list(self._log_fhs.values())
            self._procs.clear()
            self._log_fhs.clear()
        for proc in procs:
            _kill_tree(proc)
        for fh in fhs:
            fh.close()
        self._reaper.join(timeout=2.0)


def _kill_tree(proc: subprocess.Popen) -> None:
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError, OSError):
        pass
    try:
        proc.wait(timeout=2.0)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass
        proc.wait(timeout=2.0)
