"""Cluster backends.

The operator talks to a cluster through the small interface in `base.py`.
`memory.py` provides an in-process cluster (API-server + scheduler + kubelet
simulation) used by unit tests (replacing the reference's fake clients +
seeded informer indexers, SURVEY.md §4 T1) and by the e2e harness (replacing
the reference's real EKS cluster, §4 T3). `kube.py` speaks to a real
Kubernetes API server for production deployments.
"""

from .base import Cluster, NotFound
from .chaos import ChaosCluster, ChaosSpec, ScheduledPreemption
from .memory import InMemoryCluster

__all__ = [
    "ChaosCluster",
    "ChaosSpec",
    "Cluster",
    "InMemoryCluster",
    "NotFound",
    "ScheduledPreemption",
]
