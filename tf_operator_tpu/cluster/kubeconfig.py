"""KUBECONFIG resolution for the HTTP apiserver client.

The reference resolves its client config from KUBECONFIG/--kubeconfig or
falls back to in-cluster (cmd/tf-operator.v1/app/server.go:97-107 via
clientcmd). This module reads the same YAML shape — clusters/users/contexts
with `current-context` — and reduces the selected context to the keyword
arguments `KubeCluster` takes: server URL, bearer token (inline or file),
CA bundle (path or inline base64 data), client certificate pair, TLS skip,
and context namespace.

Inline `*-data` fields are materialized to private temp files (the ssl
module only loads from paths); they live for the process lifetime.
"""

from __future__ import annotations

import base64
import os
import tempfile
from typing import Optional

__all__ = ["load_kubeconfig", "resolve_kubeconfig_path", "KubeconfigError"]


class KubeconfigError(ValueError):
    """Malformed or unusable kubeconfig."""


def resolve_kubeconfig_path(explicit: Optional[str] = None) -> Optional[str]:
    """--kubeconfig flag > $KUBECONFIG (first entry) > ~/.kube/config.
    Returns None when nothing exists (caller falls back to in-cluster)."""
    if explicit:
        return explicit
    env = os.environ.get("KUBECONFIG", "")
    if env:
        # Path-list semantics: kubectl merges; we take the first existing
        # entry (merging multiple configs is out of scope for an operator
        # that selects exactly one context).
        for part in env.split(os.pathsep):
            if part and os.path.exists(part):
                return part
        return None
    default = os.path.expanduser("~/.kube/config")
    return default if os.path.exists(default) else None


def _named(entries, name: str, section: str) -> dict:
    for entry in entries or []:
        if entry.get("name") == name:
            return entry
    raise KubeconfigError(f"kubeconfig: {section} {name!r} not found")


def _materialize(data_b64: str, suffix: str) -> str:
    """Write base64 inline data to a 0600 temp file, return its path."""
    try:
        raw = base64.b64decode(data_b64)
    except Exception as exc:  # noqa: BLE001
        raise KubeconfigError(f"kubeconfig: invalid base64 {suffix} data: {exc}")
    fd, path = tempfile.mkstemp(prefix="kubeconfig-", suffix=suffix)
    try:
        os.write(fd, raw)
    finally:
        os.close(fd)
    return path


def load_kubeconfig(path: str, context: Optional[str] = None) -> dict:
    """Parse `path` and reduce `context` (default: current-context) to
    KubeCluster keyword arguments."""
    import yaml

    with open(path) as f:
        config = yaml.safe_load(f) or {}

    ctx_name = context or config.get("current-context")
    if not ctx_name:
        raise KubeconfigError(
            "kubeconfig: no context selected (no current-context and no "
            "--kube-context)"
        )
    ctx = _named(config.get("contexts"), ctx_name, "context").get("context") or {}
    cluster = _named(
        config.get("clusters"), ctx.get("cluster", ""), "cluster"
    ).get("cluster") or {}
    user = _named(config.get("users"), ctx.get("user", ""), "user").get("user") or {}

    server = cluster.get("server")
    if not server:
        raise KubeconfigError(f"kubeconfig: cluster for context {ctx_name!r} has no server")

    out: dict = {"base_url": server}
    if ctx.get("namespace"):
        out["namespace"] = ctx["namespace"]
    if cluster.get("insecure-skip-tls-verify"):
        out["insecure"] = True
    if cluster.get("certificate-authority"):
        out["ca_file"] = cluster["certificate-authority"]
    elif cluster.get("certificate-authority-data"):
        out["ca_file"] = _materialize(cluster["certificate-authority-data"], ".ca.crt")

    if user.get("token"):
        out["token"] = user["token"]
    elif user.get("tokenFile"):
        out["token_file"] = user["tokenFile"]

    cert = user.get("client-certificate")
    key = user.get("client-key")
    has_supported_auth = bool(
        user.get("token") or user.get("tokenFile") or cert or key
        or user.get("client-certificate-data") or user.get("client-key-data")
    )
    unsupported = [
        k for k in ("exec", "auth-provider", "username", "password")
        if user.get(k)
    ]
    if unsupported and not has_supported_auth:
        # Silently producing an anonymous client here would start the
        # operator and fail every request with an opaque 401.
        raise KubeconfigError(
            f"kubeconfig: user {ctx.get('user')!r} uses unsupported auth "
            f"({', '.join(unsupported)}); supported: token, tokenFile, "
            "client certificates"
        )
    if not cert and user.get("client-certificate-data"):
        cert = _materialize(user["client-certificate-data"], ".client.crt")
    if not key and user.get("client-key-data"):
        key = _materialize(user["client-key-data"], ".client.key")
    if bool(cert) != bool(key):
        raise KubeconfigError(
            "kubeconfig: client-certificate and client-key must both be set"
        )
    if cert:
        out["client_cert_file"] = cert
        out["client_key_file"] = key
    return out
