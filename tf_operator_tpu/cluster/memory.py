"""In-memory cluster: API-server + scheduler + kubelet simulation.

Serves two roles the reference splits across harness tiers (SURVEY.md §4):

- T1 double: tests seed pods/phases directly (like testutil.SetPodsStatuses
  seeding informer indexers) and assert engine actions.
- e2e simulator: `step()` plays scheduler + kubelet — binds pending pods
  (honoring gang all-or-nothing via pod groups) and runs container behaviors
  registered per pod, so whole job lifecycles (run → exit codes → restart →
  completion) execute in-process.

Semantics follow the API server where it matters to the engine: objects get
uid + monotonically-increasing resourceVersion, reads return deep copies,
deletes are observable via watch events, status updates bump versions.
"""

from __future__ import annotations

import copy
import itertools
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..api.k8s import (
    POD_FAILED,
    POD_PENDING,
    POD_RUNNING,
    POD_SUCCEEDED,
    ContainerState,
    ContainerStateTerminated,
    ContainerStatus,
    Event,
    Pod,
    Service,
)
from . import base
from .base import ADDED, DELETED, MODIFIED, Conflict, NotFound

_log = logging.getLogger(__name__)


class _RVCounter:
    """Drop-in for itertools.count(1) that also remembers the last value
    issued, so list responses can carry a true collection resourceVersion
    (a real apiserver's list rv is the storage's current revision, not 0)."""

    def __init__(self):
        self._it = itertools.count(1)
        self.latest = 0

    def __next__(self) -> int:
        self.latest = next(self._it)
        return self.latest


class InMemoryCluster(base.Cluster):
    # Every mutation runs under one RLock and the event drainer is
    # designed for concurrent writers (_publish_locked/_drain_events), so
    # the engine's parallel fan-out is safe here — and so are N sync
    # workers reconciling different jobs concurrently.
    supports_concurrent_writes = True
    supports_concurrent_syncs = True
    # Status writes may be coalesced/patched and reads served from the
    # shared watch cache: the simulator's watch delivery is rv-ordered
    # and lossless (_publish_locked/_drain_events), which is exactly the
    # contract the delta-fed cache needs.
    supports_write_coalescing = True
    supports_watch_cache = True

    def __init__(self, clock=time.time):
        self._lock = threading.RLock()
        self._clock = clock
        self._uid = itertools.count(1)
        self._rv = _RVCounter()
        self._jobs: Dict[Tuple[str, str, str], dict] = {}
        self._pods: Dict[Tuple[str, str], Pod] = {}
        self._services: Dict[Tuple[str, str], Service] = {}
        self._pod_groups: Dict[Tuple[str, str], dict] = {}
        self._leases: Dict[Tuple[str, str], dict] = {}
        self._events: List[Event] = []
        self._watchers: Dict[str, List[base.WatchHandler]] = {}
        # Ordered publish log (see _publish_locked/_drain_events).
        self._emit_lock = threading.Lock()
        self._pending_events: List[tuple] = []
        self._draining = False
        self._delivered_rv = 0
        # pod name -> behavior fn(pod) called on each step() while running
        self._behaviors: Dict[Tuple[str, str], Callable[[Pod], None]] = {}
        self._pod_logs: Dict[Tuple[str, str], str] = {}
        # Graceful-deletion holds (the dead-kubelet simulation): matching
        # pods get deletionTimestamp set by delete_pod but stay present —
        # stuck Terminating — until force-deleted or released. Each entry
        # is (namespace-or-None, name substring).
        self._termination_holds: List[Tuple[Optional[str], str]] = []
        self._held_deletions: set = set()  # (ns, name) with a delete pending
        # Schedulable-capacity model (None = unbounded, the historical
        # behavior): when set, step() binds a pending pod only while the
        # bound pods' resource demand (container requests, falling back
        # to limits, plus one synthetic `pods` slot each) still fits.
        # Deliberately PER-POD, not per-gang: a capacity-blind first-come
        # operator therefore really does strand partial gangs under
        # contention — the failure regime the admission layer
        # (core/admission.py) exists to prevent, made reproducible here.
        self._capacity: Optional[Dict[str, str]] = None
        # Device-GENERATION sub-pools (gen -> resource -> qty): the
        # heterogeneous-fleet half of the capacity model (e.g. v5-lite
        # beside current-gen chips). Read by the admission layer's
        # generations_fn so gavel-style placement sees live per-
        # generation bounds; step()'s per-pod binding stays against the
        # FLAT pool — which generation a pod's chips come from is the
        # operator's placement decision, not the simulator's.
        self._capacity_generations: Optional[Dict[str, Dict[str, str]]] = None
        # Monotonic capacity-model epoch: bumped on every
        # set_schedulable_capacity (which rewrites BOTH the flat pool
        # and the generation sub-pools). The admission layer's
        # capacity_version_fn polls this so its effective-capacity
        # cache (EngineOptions.admission_index) invalidates exactly
        # when the backend's capacity model changed.
        self._capacity_version = 0

    # ------------------------------------------------------------------ util
    def latest_rv(self) -> int:
        """Current storage revision: the last resourceVersion issued."""
        return self._rv.latest

    def delivered_rv(self) -> int:
        """Highest rv whose event has been dispatched to EVERY subscriber.
        The safe watermark for watch bookmarks: a client resuming from this
        rv cannot have an undelivered event hiding at-or-below it (the
        publish log is rv-ordered, and the drainer advances this only
        after an event's full dispatch)."""
        with self._emit_lock:
            return self._delivered_rv

    @staticmethod
    def _event_rv(obj) -> int:
        raw = ((obj.get("metadata") or {}).get("resourceVersion")
               if isinstance(obj, dict)
               else obj.metadata.resource_version) or "0"
        try:
            return int(raw)
        except ValueError:
            return 0

    def _publish_locked(self, kind: str, event_type: str, obj) -> None:
        """Append an event to the ordered publish log. MUST be called while
        holding self._lock, in the SAME critical section that assigned the
        object's resourceVersion: publication order equals rv order only
        because assignment and publication share one lock. (Publishing
        after releasing the lock let two writer threads interleave —
        commit rv N, commit+publish rv N+1, publish rv N — and an
        rv-reordered stream breaks every consumer that treats a delivered
        rv as a resume watermark: watch-cache bookmarks, replay floors.)"""
        with self._emit_lock:
            self._pending_events.append((kind, event_type, obj))

    def _drain_events(self) -> None:
        """Dispatch the publish log to subscribers, in order, with NO locks
        held around handler calls. One active drainer at a time: a write
        landing mid-drain (another thread, or a handler writing back — the
        kubelet sim marking a new pod Running) appends behind the in-flight
        event and the active drainer delivers it, preserving causal AND rv
        order for every subscriber. Handler errors log-and-continue (one
        bad subscriber must not corrupt the stream for the rest)."""
        with self._emit_lock:
            if self._draining:
                return  # the active drainer will deliver what we queued
            self._draining = True
        try:
            while True:
                with self._emit_lock:
                    if not self._pending_events:
                        self._draining = False
                        return
                    k, e, o = self._pending_events.pop(0)
                for handler in self._watchers.get(k, []):
                    try:
                        handler(e, copy.deepcopy(o))
                    except Exception:  # noqa: BLE001
                        _log.exception("watch handler for %s failed", k)
                rv = self._event_rv(o)
                with self._emit_lock:
                    self._delivered_rv = max(self._delivered_rv, rv)
        except BaseException:
            with self._emit_lock:
                self._draining = False
            raise

    def watch(self, kind: str, handler: base.WatchHandler) -> None:
        with self._lock:
            self._watchers.setdefault(kind, []).append(handler)

    # ------------------------------------------------------------------ jobs
    def create_job(self, job_dict: dict) -> dict:
        job_dict = copy.deepcopy(job_dict)
        kind = job_dict.get("kind", "")
        meta = job_dict.setdefault("metadata", {})
        ns, name = meta.get("namespace", "default"), meta["name"]
        meta.setdefault("namespace", "default")
        with self._lock:
            if (kind, ns, name) in self._jobs:
                raise ValueError(f"{kind} {ns}/{name} already exists")
            meta["uid"] = f"uid-{next(self._uid)}"
            meta["resourceVersion"] = str(next(self._rv))
            meta["creationTimestamp"] = self._clock()
            self._jobs[(kind, ns, name)] = job_dict
            out = copy.deepcopy(job_dict)
            self._publish_locked(kind, ADDED, copy.deepcopy(job_dict))
        self._drain_events()
        return out

    def get_job(self, kind: str, namespace: str, name: str) -> dict:
        with self._lock:
            try:
                return copy.deepcopy(self._jobs[(kind, namespace, name)])
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}")

    def list_jobs(self, kind: str, namespace: Optional[str] = None) -> List[dict]:
        with self._lock:
            return [
                copy.deepcopy(j)
                for (k, ns, _), j in self._jobs.items()
                if k == kind and (namespace is None or ns == namespace)
            ]

    def update_job(self, job_dict: dict) -> dict:
        kind = job_dict.get("kind", "")
        meta = job_dict.get("metadata", {})
        ns, name = meta.get("namespace", "default"), meta["name"]
        with self._lock:
            existing = self._jobs.get((kind, ns, name))
            if existing is None:
                raise NotFound(f"{kind} {ns}/{name}")
            # Optimistic concurrency (apiserver semantics): a write carrying
            # a resourceVersion must match the stored one, or a concurrent
            # writer's change would be silently reverted by this full-object
            # replace. Writes without one are "last write wins" (kubectl
            # replace --force analog).
            sent_rv = meta.get("resourceVersion")
            stored_rv = existing.get("metadata", {}).get("resourceVersion")
            if sent_rv is not None and stored_rv is not None and sent_rv != stored_rv:
                raise Conflict(
                    f"{kind} {ns}/{name}: resourceVersion {sent_rv} is stale "
                    f"(current {stored_rv})"
                )
            stored = copy.deepcopy(job_dict)
            # Status is a subresource: writes through the main resource must
            # not clobber it (a stale SDK read-modify-write would otherwise
            # erase conditions the controller wrote in between).
            if "status" in existing:
                stored["status"] = copy.deepcopy(existing["status"])
            else:
                stored.pop("status", None)
            stored["metadata"]["resourceVersion"] = str(next(self._rv))
            self._jobs[(kind, ns, name)] = stored
            out = copy.deepcopy(stored)
            self._publish_locked(kind, MODIFIED, copy.deepcopy(stored))
        self._drain_events()
        return out

    def update_job_status(self, kind: str, namespace: str, name: str, status: dict) -> dict:
        with self._lock:
            job = self._jobs.get((kind, namespace, name))
            if job is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            job["status"] = copy.deepcopy(status)
            job["metadata"]["resourceVersion"] = str(next(self._rv))
            out = copy.deepcopy(job)
            self._publish_locked(kind, MODIFIED, copy.deepcopy(job))
        self._drain_events()
        return out

    def patch_job_status(self, kind: str, namespace: str, name: str, status: dict) -> dict:
        """Single-request status-subresource apply (the coalescing
        writer's verb): same end state as update_job_status — the payload
        is the entire intended status, replacing what is stored — but
        modeled as a PATCH: no resourceVersion precondition, so it can
        never Conflict on a stale read (apply-with-force semantics)."""
        with self._lock:
            job = self._jobs.get((kind, namespace, name))
            if job is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            job["status"] = copy.deepcopy(status)
            job["metadata"]["resourceVersion"] = str(next(self._rv))
            out = copy.deepcopy(job)
            self._publish_locked(kind, MODIFIED, copy.deepcopy(job))
        self._drain_events()
        return out

    def delete_job(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            job = self._jobs.pop((kind, namespace, name), None)
            if job is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            # Deletion is a write: the DELETED event carries a fresh
            # resourceVersion (real apiservers bump the revision), so a
            # watch resuming from the object's last rv still sees it.
            job["metadata"]["resourceVersion"] = str(next(self._rv))
            self._publish_locked(kind, DELETED, job)
            # Cascading GC, like a real apiserver's garbage collector: the
            # operator stamps ownerReferences on everything it creates and
            # relies on the cluster to reap them when the owner goes —
            # without this, every deleted job leaked its terminal pods
            # (the soak tier caught it as monotonic residency). The sweep
            # reaps anything whose CONTROLLER owner uid matches no live
            # job — not just this job's uid — so an orphan slipping past
            # one cascade (a concurrent reconcile that read the job before
            # its deletion can create a pod after this snapshot) is
            # collected by the next deletion, mirroring the real GC's
            # eventual reaping. Objects without a controller ref are never
            # touched.
            live_uids = {
                (j.get("metadata") or {}).get("uid")
                for j in self._jobs.values()
            }

            def dangling(refs) -> bool:
                ctrl = [r for r in refs if getattr(r, "controller", False)
                        and r.uid]
                return bool(ctrl) and all(r.uid not in live_uids for r in ctrl)

            owned_pods = [
                k for k, p in self._pods.items()
                if dangling(p.metadata.owner_references)
            ]
            owned_services = [
                k for k, s in self._services.items()
                if dangling(s.metadata.owner_references)
            ]
            owned_groups = [
                k for k, g in self._pod_groups.items()
                if (refs := (g.get("metadata") or {}).get("ownerReferences"))
                and all(r.get("uid") not in live_uids
                        for r in refs if r.get("controller"))
                and any(r.get("controller") for r in refs)
            ]
        self._drain_events()
        for ns, pname in owned_pods:
            try:
                self.delete_pod(ns, pname)
            except NotFound:
                pass
        for ns, sname in owned_services:
            try:
                self.delete_service(ns, sname)
            except NotFound:
                pass
        for ns, gname in owned_groups:
            try:
                self.delete_pod_group(ns, gname)
            except NotFound:
                pass

    # ------------------------------------------------------------------ pods
    def create_pod(self, pod: Pod) -> Pod:
        pod = pod.deep_copy()
        key = (pod.metadata.namespace, pod.metadata.name)
        with self._lock:
            if key in self._pods:
                raise ValueError(f"pod {key} already exists")
            pod.metadata.uid = f"uid-{next(self._uid)}"
            pod.metadata.resource_version = str(next(self._rv))
            pod.metadata.creation_timestamp = self._clock()
            pod.status.phase = POD_PENDING
            self._pods[key] = pod
            out = pod.deep_copy()
            self._publish_locked("pods", ADDED, pod.deep_copy())
        self._drain_events()
        return out

    def get_pod(self, namespace: str, name: str) -> Pod:
        with self._lock:
            try:
                return self._pods[(namespace, name)].deep_copy()
            except KeyError:
                raise NotFound(f"pod {namespace}/{name}")

    def list_pods(self, namespace=None, labels=None, owner_uid=None) -> List[Pod]:
        """Label-selected pods; with ``owner_uid`` the match widens to
        label-match OR controller-owned-by-uid (the claim protocol's view:
        an owned pod whose labels were mutated away must still be seen, or
        it could never be released — without paying a full-scope deep copy
        of every operator pod per sync)."""
        with self._lock:
            out = []
            for (ns, _), pod in self._pods.items():
                if namespace is not None and ns != namespace:
                    continue
                if base.matches_claim_view(pod, labels, owner_uid):
                    out.append(pod.deep_copy())
            return out

    def update_pod(self, pod: Pod) -> Pod:
        key = (pod.metadata.namespace, pod.metadata.name)
        with self._lock:
            if key not in self._pods:
                raise NotFound(f"pod {key}")
            pod = pod.deep_copy()
            pod.metadata.resource_version = str(next(self._rv))
            self._pods[key] = pod
            out = pod.deep_copy()
            self._publish_locked("pods", MODIFIED, pod.deep_copy())
        self._drain_events()
        return out

    def append_pod_log(self, namespace: str, name: str, text: str) -> None:
        """Test/workload hook: emulate container stdout for get_pod_log."""
        with self._lock:
            if (namespace, name) not in self._pods:
                raise NotFound(f"pod {namespace}/{name}")
            self._pod_logs[(namespace, name)] = (
                self._pod_logs.get((namespace, name), "") + text
            )

    def get_pod_log(self, namespace: str, name: str) -> str:
        with self._lock:
            if (namespace, name) not in self._pods:
                raise NotFound(f"pod {namespace}/{name}")
            return self._pod_logs.get((namespace, name), "")

    # Grace the apiserver grants a held (stuck-Terminating) pod — the k8s
    # default terminationGracePeriodSeconds. Folded into the pod's
    # deletionTimestamp (expected-gone time), matching real apiservers.
    DEFAULT_GRACE_PERIOD_SECONDS = 30.0

    def _hold_matches_locked(self, namespace: str, name: str) -> bool:
        return any(
            (ns is None or ns == namespace) and (not frag or frag in name)
            for ns, frag in self._termination_holds
        )

    def hold_pod_termination(self, name_contains: str = "",
                             namespace: Optional[str] = None) -> None:
        """Chaos/test lever — the dead-kubelet simulation: from now on a
        graceful delete of a matching pod sets deletionTimestamp (+ the
        default grace) and HOLDS the object, exactly as a real apiserver
        keeps a pod whose kubelet never acks termination. Only
        delete_pod(..., force=True) — grace-period-0 — removes it."""
        with self._lock:
            self._termination_holds.append((namespace, name_contains))

    def release_pod_terminations(self) -> None:
        """Drop every hold and finish the deletions they blocked (the
        kubelet coming back and acking), so tests can model recovery
        without the force path."""
        with self._lock:
            self._termination_holds.clear()
            held = list(self._held_deletions)
        for ns, name in held:
            try:
                self.delete_pod(ns, name)
            except NotFound:
                pass

    def delete_pod(self, namespace: str, name: str, force: bool = False) -> None:
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFound(f"pod {namespace}/{name}")
            if not force and self._hold_matches_locked(namespace, name):
                # Graceful window held open indefinitely: mark Terminating
                # (idempotently) and keep the object. The MODIFIED event is
                # what informers see for a real graceful delete.
                self._held_deletions.add((namespace, name))
                if pod.metadata.deletion_timestamp is None:
                    # k8s semantics: deletionTimestamp = request time +
                    # grace — the instant the object is expected GONE.
                    pod.metadata.deletion_timestamp = (
                        self._clock() + self.DEFAULT_GRACE_PERIOD_SECONDS
                    )
                    pod.metadata.deletion_grace_period_seconds = (
                        self.DEFAULT_GRACE_PERIOD_SECONDS
                    )
                    pod.metadata.resource_version = str(next(self._rv))
                    self._publish_locked("pods", MODIFIED, pod.deep_copy())
            else:
                self._pods.pop((namespace, name), None)
                self._behaviors.pop((namespace, name), None)
                self._pod_logs.pop((namespace, name), None)
                self._held_deletions.discard((namespace, name))
                pod.metadata.resource_version = str(next(self._rv))
                self._publish_locked("pods", DELETED, pod)
        self._drain_events()

    # -------------------------------------------------------------- services
    def create_service(self, service: Service) -> Service:
        service = service.deep_copy()
        key = (service.metadata.namespace, service.metadata.name)
        with self._lock:
            if key in self._services:
                raise ValueError(f"service {key} already exists")
            service.metadata.uid = f"uid-{next(self._uid)}"
            service.metadata.resource_version = str(next(self._rv))
            self._services[key] = service
            out = service.deep_copy()
            self._publish_locked("services", ADDED, service.deep_copy())
        self._drain_events()
        return out

    def get_service(self, namespace: str, name: str) -> Service:
        with self._lock:
            try:
                return self._services[(namespace, name)].deep_copy()
            except KeyError:
                raise NotFound(f"service {namespace}/{name}")

    def list_services(self, namespace=None, labels=None, owner_uid=None) -> List[Service]:
        with self._lock:
            out = []
            for (ns, _), svc in self._services.items():
                if namespace is not None and ns != namespace:
                    continue
                if base.matches_claim_view(svc, labels, owner_uid):
                    out.append(svc.deep_copy())
            return out

    def update_service(self, service: Service) -> Service:
        key = (service.metadata.namespace, service.metadata.name)
        with self._lock:
            if key not in self._services:
                raise NotFound(f"service {key}")
            service = service.deep_copy()
            service.metadata.resource_version = str(next(self._rv))
            self._services[key] = service
            out = service.deep_copy()
            self._publish_locked("services", MODIFIED, service.deep_copy())
        self._drain_events()
        return out

    def delete_service(self, namespace: str, name: str) -> None:
        with self._lock:
            svc = self._services.pop((namespace, name), None)
            if svc is None:
                raise NotFound(f"service {namespace}/{name}")
            svc.metadata.resource_version = str(next(self._rv))
            self._publish_locked("services", DELETED, svc)
        self._drain_events()

    # ------------------------------------------------------------ pod groups
    def create_pod_group(self, group: dict) -> dict:
        group = copy.deepcopy(group)
        meta = group.setdefault("metadata", {})
        key = (meta.get("namespace", "default"), meta["name"])
        with self._lock:
            self._pod_groups[key] = group
            return copy.deepcopy(group)

    def get_pod_group(self, namespace: str, name: str) -> dict:
        with self._lock:
            try:
                return copy.deepcopy(self._pod_groups[(namespace, name)])
            except KeyError:
                raise NotFound(f"podgroup {namespace}/{name}")

    def list_pod_groups(self, namespace=None, labels=None) -> List[dict]:
        with self._lock:
            out = []
            for (ns, _), group in self._pod_groups.items():
                if namespace is not None and ns != namespace:
                    continue
                glabels = (group.get("metadata") or {}).get("labels") or {}
                if labels and any(glabels.get(k) != v for k, v in labels.items()):
                    continue
                out.append(copy.deepcopy(group))
            return out

    def delete_pod_group(self, namespace: str, name: str) -> None:
        with self._lock:
            self._pod_groups.pop((namespace, name), None)

    def set_pod_group_phase(self, namespace: str, name: str, phase: str) -> None:
        """Set a PodGroup's status.phase (Pending/Inqueue/Running) — the
        slice of the Volcano state machine the simulator models. The gang
        admission layer mirrors its verdicts here so phase-driven
        surfaces (_sync_pod_group's Queued check, dashboards) agree with
        the arbiter; on a real cluster Volcano owns this field."""
        with self._lock:
            group = self._pod_groups.get((namespace, name))
            if group is None:
                raise NotFound(f"podgroup {namespace}/{name}")
            group.setdefault("status", {})["phase"] = phase

    # ------------------------------------------------- schedulable capacity
    def set_schedulable_capacity(
        self, resources: Optional[Dict[str, str]],
        generations: Optional[Dict[str, Dict[str, str]]] = None,
    ) -> None:
        """Declare (or with None, remove) the cluster's schedulable
        capacity. Shrinking it mid-run is the capacity-revocation fault:
        already-bound pods keep running — reclaiming them is the
        operator's job (preempt-to-fit), not the simulator's.
        ``generations`` optionally declares per-device-generation
        sub-pools beside (not instead of) the flat pool; a generation-
        scoped revocation shrinks one sub-pool and the admission layer
        reconciles placement."""
        with self._lock:
            self._capacity = dict(resources) if resources else None
            self._capacity_generations = (
                {gen: dict(res) for gen, res in generations.items()}
                if generations else None
            )
            self._capacity_version += 1

    def schedulable_capacity_version(self) -> int:
        """Capacity-model epoch (see __init__): changes iff a
        set_schedulable_capacity call happened since the last read."""
        with self._lock:
            return self._capacity_version

    def schedulable_capacity(self) -> Optional[Dict[str, str]]:
        """The declared pool (None = unbounded). The admission layer's
        capacity_fn reads this, which is how a seeded revocation becomes
        an admission-visible event."""
        with self._lock:
            return dict(self._capacity) if self._capacity else None

    def schedulable_generations(self) -> Optional[Dict[str, Dict[str, str]]]:
        """The declared per-generation sub-pools (None = homogeneous).
        The admission layer's generations_fn reads this — how a live
        generation-scoped shrink reaches gavel placement."""
        with self._lock:
            return (
                {gen: dict(res)
                 for gen, res in self._capacity_generations.items()}
                if self._capacity_generations else None
            )

    @staticmethod
    def _pod_demand(pod: Pod) -> Dict[str, object]:
        from ..core.job_controller import parse_quantity

        demand: Dict[str, object] = {"pods": 1}
        for container in pod.spec.containers:
            resources = container.resources or {}
            requests = resources.get("requests") or resources.get("limits") or {}
            for name, qty in requests.items():
                try:
                    demand[name] = demand.get(name, 0) + parse_quantity(qty)
                except (ValueError, ZeroDivisionError):
                    continue
        return demand

    def _bound_usage_locked(self) -> Dict[str, object]:
        usage: Dict[str, object] = {}
        for pod in self._pods.values():
            if pod.status.phase != POD_RUNNING:
                continue
            for name, qty in self._pod_demand(pod).items():
                usage[name] = usage.get(name, 0) + qty
        return usage

    def _capacity_allows_locked(self, usage, demand) -> bool:
        if self._capacity is None:
            return True
        from ..core.job_controller import parse_quantity

        for name, qty in demand.items():
            if name not in self._capacity:
                continue
            if usage.get(name, 0) + qty > parse_quantity(self._capacity[name]):
                return False
        return True

    # ---------------------------------------------------------------- leases
    def get_lease(self, namespace: str, name: str) -> dict:
        with self._lock:
            try:
                return copy.deepcopy(self._leases[(namespace, name)])
            except KeyError:
                raise NotFound(f"lease {namespace}/{name}")

    def create_lease(self, lease: dict) -> dict:
        lease = copy.deepcopy(lease)
        meta = lease.setdefault("metadata", {})
        meta.setdefault("namespace", "default")
        key = (meta["namespace"], meta["name"])
        with self._lock:
            if key in self._leases:
                raise Conflict(f"lease {key} already exists")
            meta["resourceVersion"] = str(next(self._rv))
            self._leases[key] = lease
            out = copy.deepcopy(lease)
            self._publish_locked("leases", ADDED, copy.deepcopy(lease))
        self._drain_events()
        return out

    def update_lease(self, lease: dict) -> dict:
        meta = lease.get("metadata", {})
        key = (meta.get("namespace", "default"), meta["name"])
        with self._lock:
            existing = self._leases.get(key)
            if existing is None:
                raise NotFound(f"lease {key}")
            sent_rv = meta.get("resourceVersion")
            stored_rv = existing.get("metadata", {}).get("resourceVersion")
            if sent_rv is not None and sent_rv != stored_rv:
                raise Conflict(
                    f"lease {key}: resourceVersion {sent_rv} is stale (current {stored_rv})"
                )
            stored = copy.deepcopy(lease)
            stored["metadata"]["resourceVersion"] = str(next(self._rv))
            self._leases[key] = stored
            out = copy.deepcopy(stored)
            self._publish_locked("leases", MODIFIED, copy.deepcopy(stored))
        self._drain_events()
        return out

    def delete_lease(self, namespace: str, name: str) -> None:
        with self._lock:
            lease = self._leases.pop((namespace, name), None)
            if lease is None:
                raise NotFound(f"lease {namespace}/{name}")
            lease["metadata"]["resourceVersion"] = str(next(self._rv))
            self._publish_locked("leases", DELETED, lease)
        self._drain_events()

    def list_leases(self, namespace: Optional[str] = None,
                    name_prefix: str = "",
                    labels: Optional[Dict[str, str]] = None) -> List[dict]:
        def selected(lease: dict) -> bool:
            if not labels:
                return True
            stamped = (lease.get("metadata") or {}).get("labels") or {}
            return all(stamped.get(k) == v for k, v in labels.items())

        with self._lock:
            return [
                copy.deepcopy(lease)
                for (ns, name), lease in sorted(self._leases.items())
                if (namespace is None or ns == namespace)
                and name.startswith(name_prefix)
                and selected(lease)
            ]

    # ---------------------------------------------------------------- events
    def record_event(self, event: Event) -> None:
        with self._lock:
            if event.timestamp is None:
                event.timestamp = self._clock()
            self._events.append(event)

    def list_events(self, involved_object: Optional[str] = None) -> List[Event]:
        with self._lock:
            return [
                copy.deepcopy(e)
                for e in self._events
                if involved_object is None or e.involved_object == involved_object
            ]

    # ----------------------------------------------------- kubelet/scheduler
    def set_behavior(self, namespace: str, name: str, fn: Callable[[Pod], None]) -> None:
        """Register a per-step container behavior for a running pod. `fn`
        mutates the pod in place (e.g. terminate with an exit code)."""
        with self._lock:
            self._behaviors[(namespace, name)] = fn

    def _gang_schedulable(self, pod: Pod) -> bool:
        """All-or-nothing: a pod annotated with a gang group only binds when
        the whole gang's pods exist (minAvailable present in the cluster)."""
        from ..core.constants import ANNOTATION_GANG_GROUP_NAME

        group_name = pod.metadata.annotations.get(ANNOTATION_GANG_GROUP_NAME)
        if not group_name:
            return True
        group = self._pod_groups.get((pod.metadata.namespace, group_name))
        if group is None:
            return False
        min_available = group.get("spec", {}).get("minMember", 1)
        peers = [
            p
            for p in self._pods.values()
            if p.metadata.namespace == pod.metadata.namespace
            and p.metadata.annotations.get(ANNOTATION_GANG_GROUP_NAME) == group_name
        ]
        return len(peers) >= min_available

    def step(self) -> None:
        """Advance the simulated cluster by one tick: bind pending pods
        (gang-aware, capacity-bounded when a pool is declared) and run
        container behaviors of running pods."""
        updates = []
        with self._lock:
            usage = (
                self._bound_usage_locked() if self._capacity is not None
                else None
            )
            for key, pod in list(self._pods.items()):
                if pod.status.phase == POD_PENDING:
                    demand = (
                        self._pod_demand(pod) if usage is not None else None
                    )
                    if usage is not None and not self._capacity_allows_locked(
                        usage, demand
                    ):
                        continue  # no room: stays Pending (contention!)
                    if self._gang_schedulable(pod):
                        pod.status.phase = POD_RUNNING
                        pod.status.start_time = self._clock()
                        pod.metadata.resource_version = str(next(self._rv))
                        updates.append(pod.deep_copy())
                        if usage is not None:
                            for name, qty in demand.items():
                                usage[name] = usage.get(name, 0) + qty
                elif pod.status.phase == POD_RUNNING:
                    behavior = self._behaviors.get(key)
                    if behavior is not None:
                        behavior(pod)
                        pod.metadata.resource_version = str(next(self._rv))
                        self._publish_locked("pods", MODIFIED, pod.deep_copy())
        self._drain_events()

    # ------------------------------------------------- test-seeding helpers
    def set_pod_phase(
        self,
        namespace: str,
        name: str,
        phase: str,
        exit_code: Optional[int] = None,
        container_name: str = "",
        restart_count: int = 0,
        reason: str = "",
        disruption_target: Optional[str] = None,
        container_reason: str = "",
    ) -> None:
        """Directly set a pod's phase (and terminated exit code), as the
        reference's testutil.SetPodsStatuses seeds informer indexers.
        `reason` seeds PodStatus.reason (kubelet "Evicted"/"Preempted"
        style); `disruption_target` appends a DisruptionTarget condition
        with that reason — the k8s >=1.26 infrastructure-kill marker."""
        from ..api.k8s import POD_CONDITION_DISRUPTION_TARGET, PodCondition

        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFound(f"pod {namespace}/{name}")
            pod.status.phase = phase
            if phase == POD_RUNNING and pod.status.start_time is None:
                pod.status.start_time = self._clock()
            if reason:
                pod.status.reason = reason
            if disruption_target is not None:
                pod.status.conditions.append(
                    PodCondition(
                        type=POD_CONDITION_DISRUPTION_TARGET,
                        status="True",
                        reason=disruption_target,
                    )
                )
            if exit_code is not None:
                cname = container_name or (pod.spec.containers[0].name if pod.spec.containers else "")
                pod.status.container_statuses = [
                    ContainerStatus(
                        name=cname,
                        restart_count=restart_count,
                        state=ContainerState(
                            terminated=ContainerStateTerminated(
                                exit_code=exit_code,
                                reason=container_reason,
                                finished_at=self._clock(),
                            )
                        ),
                    )
                ]
            pod.metadata.resource_version = str(next(self._rv))
            self._publish_locked("pods", MODIFIED, pod.deep_copy())
        self._drain_events()

    def set_pod_deleting(self, namespace: str, name: str) -> None:
        """Test hook: mark a pod Terminating (deletion_timestamp set, object
        still present) — the graceful-deletion window a real apiserver holds
        pods in, which the instant-removal delete_pod above never shows."""
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFound(f"pod {namespace}/{name}")
            pod.metadata.deletion_timestamp = self._clock()
            pod.metadata.resource_version = str(next(self._rv))
            self._publish_locked("pods", MODIFIED, pod.deep_copy())
        self._drain_events()


def terminate_after(steps: int, exit_code: int = 0):
    """Behavior factory: container runs `steps` ticks then terminates."""
    state = {"left": steps}

    def fn(pod: Pod) -> None:
        state["left"] -= 1
        if state["left"] > 0:
            return
        pod.status.phase = POD_SUCCEEDED if exit_code == 0 else POD_FAILED
        cname = pod.spec.containers[0].name if pod.spec.containers else ""
        pod.status.container_statuses = [
            ContainerStatus(
                name=cname,
                state=ContainerState(
                    terminated=ContainerStateTerminated(exit_code=exit_code)
                ),
            )
        ]

    return fn
