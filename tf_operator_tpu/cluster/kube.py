"""Kube-apiserver Cluster backend: the production adapter.

The in-memory and process backends serve tests and dev; this one speaks
the real Kubernetes REST API so the SAME operator binary reconciles a
real cluster (`python -m tf_operator_tpu --kube`). Dependency-free by
design (stdlib http.client + ssl): the image rules out pip installs, and
the API surface we need — typed CRUD on five CRDs, core pods/services/
events, volcano PodGroups, coordination Leases, streaming watches — is
plain JSON over HTTPS.

Auth: in-cluster service-account (token + CA from
/var/run/secrets/kubernetes.io/serviceaccount, apiserver from
KUBERNETES_SERVICE_HOST/PORT), or explicit base_url/token/ca_file for
tests and kubeconfig-less setups.

Informer semantics (reference: client-go SharedInformer feeding the
controllers, scoped at cmd/tf-operator.v1/app/server.go:129): ONE watch
thread per kind regardless of how many controllers subscribe; the stream
feeds an in-process store; `list_pods`/`list_services` serve from that
store once primed, so a reconcile costs zero apiserver round-trips for
its relists. Watches are namespace-scoped when the operator is, and
pod/service watches carry the operator's label selector
(`group-name=kubeflow.org`) so unrelated cluster traffic never reaches
us. Relist replays emit SYNC — not ADDED — so event-derived counters
(jobs_created_total) cannot inflate on reconnect, and MODIFIED events
whose resourceVersion matches the stored object are dropped (the
reference's same-RV resync filter, common/util/reconciler.go:80-123).
"""

from __future__ import annotations

import calendar
import http.client
import json
import logging
import os
import socket
import ssl
import threading
import time
import urllib.parse
from typing import Callable, Dict, List, Optional, Tuple

from ..api.k8s import Event, Pod, Service, from_dict, to_dict
from ..core import constants
from .base import (
    ADDED,
    DELETED,
    MODIFIED,
    SYNC,
    Cluster,
    Conflict,
    Gone,
    NotFound,
    matches_claim_view,
)

_log = logging.getLogger(__name__)

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

_PODGROUP = ("scheduling.volcano.sh", "v1beta1", "podgroups")
_LEASE = ("coordination.k8s.io", "v1", "leases")

# Server-side watch window: the apiserver closes the stream cleanly after
# this many seconds and we resume from the last seen resourceVersion — no
# relist, no replay. The socket timeout is set slightly above so a healthy
# but idle stream never trips the client timeout (ADVICE r1: a 30s socket
# timeout degraded every watch into 30s full-relist polling).
_WATCH_TIMEOUT_SECONDS = 240


def _job_plural(kind: str) -> str:
    from .. import api

    module = getattr(api, kind.lower())
    return module.PLURAL


def _iso_to_epoch(value):
    """k8s RFC3339 timestamps -> epoch floats (our dataclasses hold floats)."""
    if not isinstance(value, str):
        return value
    try:
        return calendar.timegm(time.strptime(value, "%Y-%m-%dT%H:%M:%SZ"))
    except ValueError:
        return None


def _normalize_times(obj: dict) -> dict:
    meta = obj.get("metadata") or {}
    if "creationTimestamp" in meta:
        meta["creationTimestamp"] = _iso_to_epoch(meta["creationTimestamp"])
    if "deletionTimestamp" in meta:
        meta["deletionTimestamp"] = _iso_to_epoch(meta["deletionTimestamp"])
    status = obj.get("status") or {}
    if "startTime" in status:
        status["startTime"] = _iso_to_epoch(status["startTime"])
    return obj


def _meta_of(obj) -> Tuple[str, str, str]:
    """(namespace, name, resourceVersion) for dict jobs and typed pods/services."""
    if isinstance(obj, dict):
        meta = obj.get("metadata") or {}
        return (
            meta.get("namespace", "default"),
            meta.get("name", ""),
            meta.get("resourceVersion", ""),
        )
    meta = obj.metadata
    return (meta.namespace, meta.name, meta.resource_version or "")


class _NoDelayHTTPConnection(http.client.HTTPConnection):
    """http.client writes headers and body as separate sends; Nagle holds
    the second waiting for a delayed ACK (~40ms) — at ~8 writes per
    reconcile that tripled restart MTTR. TCP_NODELAY the moment the socket
    exists."""

    def connect(self):
        super().connect()
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass


class _NoDelayHTTPSConnection(http.client.HTTPSConnection):
    def connect(self):
        super().connect()
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass


class KubeCluster(Cluster):
    # Each thread holds its own keep-alive connection (self._local) and a
    # real apiserver is built for concurrent clients — the whole point of
    # the parallel fan-out (and of the sync-worker pool) is overlapping
    # these round trips.
    supports_concurrent_writes = True
    supports_concurrent_syncs = True
    # Coalesced status writes are exactly what a real apiserver wants
    # (every deferred write is a round trip + etcd write saved); the
    # shared watch cache stays OFF because the reflector below already
    # serves list/get from its informer store — a second cache layer
    # would only add staleness.
    supports_write_coalescing = True
    supports_watch_cache = False

    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
        timeout: float = 30.0,
        namespace: str = "",
        label_selector: Optional[str] = None,
        token_file: Optional[str] = None,
        client_cert_file: Optional[str] = None,
        client_key_file: Optional[str] = None,
        list_limit: int = 500,
    ):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "KubeCluster: no base_url and not in-cluster "
                    "(KUBERNETES_SERVICE_HOST unset)"
                )
            base_url = f"https://{host}:{port}"
        # File-backed tokens (in-cluster SA, kubeconfig tokenFile) are
        # RE-READABLE: bound SA tokens rotate (~1h), so a token read once at
        # init would start taking 401s mid-run and never recover. The file
        # path is kept and re-read on 401 (_refresh_token).
        if token is None and token_file is None and os.path.exists(f"{_SA_DIR}/token"):
            token_file = f"{_SA_DIR}/token"
        if ca_file is None and os.path.exists(f"{_SA_DIR}/ca.crt"):
            ca_file = f"{_SA_DIR}/ca.crt"
        self._url = urllib.parse.urlparse(base_url)
        self._token_file = token_file
        if token is None and token_file is not None:
            try:
                with open(token_file) as f:
                    token = f.read().strip()
            except OSError as exc:
                raise RuntimeError(
                    f"KubeCluster: cannot read token file {token_file!r}: {exc}"
                )
        self._token = token
        self._token_lock = threading.Lock()
        self._timeout = timeout
        # Operator scope: restricts watch paths (and therefore the cache) to
        # one namespace when set — the legacy factory's namespace filter
        # (server.go:129).
        self._namespace = namespace
        # Dependent watches only see objects this operator stamped
        # (tfjob_controller.go:764-770 labels) unless overridden.
        self._label_selector = (
            label_selector
            if label_selector is not None
            else f"{constants.LABEL_GROUP_NAME}={constants.GROUP_NAME}"
        )
        if self._url.scheme == "https":
            if insecure:
                self._ssl = ssl._create_unverified_context()
            else:
                self._ssl = ssl.create_default_context(cafile=ca_file)
            if client_cert_file:
                # mTLS client auth (kubeconfig client-certificate/key).
                self._ssl.load_cert_chain(client_cert_file, client_key_file)
        else:
            self._ssl = None
        # Informer relists paginate with this page size (client-go reflector
        # default 500); 0 = single-shot unchunked lists.
        self._list_limit = list_limit
        self._stop = threading.Event()
        self._local = threading.local()  # per-thread keep-alive connection
        # ---- informer state: one watch loop per kind, N handlers ----
        self._informer_lock = threading.Lock()
        self._handlers: Dict[str, List[Callable]] = {}
        self._stores: Dict[str, Dict[Tuple[str, str], Tuple[str, object]]] = {}
        self._synced: Dict[str, threading.Event] = {}
        self._watch_threads: Dict[str, threading.Thread] = {}
        self._stream_conns: Dict[str, http.client.HTTPConnection] = {}

    # ------------------------------------------------------------- plumbing
    def _connect(self, timeout: Optional[float] = None) -> http.client.HTTPConnection:
        host = self._url.hostname
        port = self._url.port or (443 if self._url.scheme == "https" else 80)
        timeout = self._timeout if timeout is None else timeout
        # Connection stays LAZY (established inside _request's try, so
        # connect failures keep their retry/context handling); NODELAY is
        # applied in the subclass the moment the socket exists.
        if self._url.scheme == "https":
            return _NoDelayHTTPSConnection(
                host, port, context=self._ssl, timeout=timeout
            )
        return _NoDelayHTTPConnection(host, port, timeout=timeout)

    def _headers(self, content_type: Optional[str] = None,
                 token: Optional[str] = None) -> Dict[str, str]:
        headers = {"Accept": "application/json"}
        token = self._token if token is None else token
        if token:
            headers["Authorization"] = f"Bearer {token}"
        if content_type:
            headers["Content-Type"] = content_type
        return headers

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 content_type: str = "application/json") -> dict:
        # Keep-alive: one connection per calling thread, reused across
        # requests (ADVICE r1: fresh TCP+TLS per call made every reconcile
        # pay several handshakes). Retry-on-a-fresh-socket is bounded by
        # idempotency: a mutation whose response was lost MAY have committed
        # server-side, so POST/PUT/DELETE only retry when the send itself
        # failed on a reused (stale keep-alive) connection — never after
        # bytes could have reached the server twice.
        refreshed = False
        while True:
            conn = getattr(self._local, "conn", None)
            reused = conn is not None
            if conn is None:
                conn = self._connect()
                self._local.conn = conn
            sent = False
            token_sent = self._token
            try:
                conn.request(
                    method,
                    path,
                    body=None if body is None else json.dumps(body),
                    headers=self._headers(
                        content_type if body is not None else None,
                        token=token_sent,
                    ),
                )
                sent = True
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError) as exc:
                self._local.conn = None
                try:
                    conn.close()
                except Exception:
                    pass
                retry_safe = reused and (method == "GET" or not sent)
                if retry_safe:
                    continue
                raise RuntimeError(f"{method} {path}: connection failed ({exc})")
            if resp.status == 401 and not refreshed and self._refresh_token(token_sent):
                # Bound SA tokens rotate (~1h): the mounted file has fresh
                # credentials — re-read once and replay. Safe for mutations:
                # a 401 means the apiserver rejected the request before
                # processing it. Replay on a FRESH connection — a server
                # that rejected at the auth layer may not have drained the
                # request body, leaving the keep-alive stream desynced.
                refreshed = True
                self._local.conn = None
                try:
                    conn.close()
                except Exception:
                    pass
                continue
            if resp.status == 404:
                raise NotFound(f"{method} {path}: 404")
            if resp.status == 409:
                raise Conflict(f"{method} {path}: 409 {data[:200]!r}")
            if resp.status == 410:
                # Expired list continue token (or rv): restartable.
                raise Gone(f"{method} {path}: 410 {data[:200]!r}")
            if resp.status >= 400:
                raise RuntimeError(f"{method} {path}: {resp.status} {data[:300]!r}")
            return json.loads(data) if data else {}

    def _refresh_token(self, rejected: Optional[str]) -> bool:
        """Re-read the token file after a 401. True iff the file yields a
        token DIFFERENT from the one the failed request actually sent
        (otherwise retrying is pointless and the 401 should surface).
        Comparing against `rejected` rather than self._token keeps
        concurrent 401s correct: a thread whose peer already refreshed
        still gets True and replays with the current credentials."""
        if not self._token_file:
            return False
        try:
            with open(self._token_file) as f:
                fresh = f.read().strip()
        except OSError:
            return False
        if not fresh or fresh == rejected:
            return False
        with self._token_lock:
            if self._token != fresh:
                self._token = fresh
                _log.info(
                    "bearer token rotated (re-read %s after 401)", self._token_file
                )
        return True

    @classmethod
    def from_kubeconfig(
        cls,
        path: Optional[str] = None,
        context: Optional[str] = None,
        **kwargs,
    ) -> "KubeCluster":
        """Build a client from a kubeconfig (--kubeconfig > $KUBECONFIG >
        ~/.kube/config), the reference's clientcmd resolution
        (cmd/tf-operator.v1/app/server.go:97-107). Extra kwargs (namespace,
        label_selector, timeout) override the kubeconfig's."""
        from .kubeconfig import load_kubeconfig, resolve_kubeconfig_path

        resolved = resolve_kubeconfig_path(path)
        if resolved is None:
            raise RuntimeError(
                "KubeCluster.from_kubeconfig: no kubeconfig found "
                "(no --kubeconfig, $KUBECONFIG, or ~/.kube/config)"
            )
        conf = load_kubeconfig(resolved, context=context)
        conf.update(kwargs)
        return cls(**conf)

    @staticmethod
    def _selector_query(labels: Dict[str, str]) -> str:
        """`?labelSelector=k=v,...` suffix (sorted for stable URLs)."""
        selector = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        return "?" + urllib.parse.urlencode({"labelSelector": selector})

    # ---------------------------------------------------------------- paths
    def _job_path(self, kind: str, namespace: str, name: str = "") -> str:
        plural = _job_plural(kind)
        base = f"/apis/kubeflow.org/v1/namespaces/{namespace}/{plural}"
        return f"{base}/{name}" if name else base

    def _core_path(self, resource: str, namespace: Optional[str], name: str = "") -> str:
        base = (
            f"/api/v1/namespaces/{namespace}/{resource}"
            if namespace
            else f"/api/v1/{resource}"
        )
        return f"{base}/{name}" if name else base

    # ----------------------------------------------------------------- jobs
    def create_job(self, job_dict: dict) -> dict:
        meta = job_dict.get("metadata", {})
        return self._request(
            "POST",
            self._job_path(job_dict["kind"], meta.get("namespace", "default")),
            job_dict,
        )

    def get_job(self, kind: str, namespace: str, name: str) -> dict:
        """Cache-served once the kind's watch is primed (the reference syncs
        from the informer lister, tfjob_controller.go:222-235): a reconcile
        then costs zero live reads. Store misses fall back to a live GET —
        never a synthesized 404 from a cold cache."""
        synced = self._synced.get(kind)
        if synced is not None and synced.is_set():
            with self._informer_lock:
                entry = self._stores.get(kind, {}).get((namespace, name))
            if entry is not None:
                return json.loads(json.dumps(entry[1]))  # caller-safe copy
        return self._get_job_live(kind, namespace, name)

    def _get_job_live(self, kind: str, namespace: str, name: str) -> dict:
        return _normalize_times(self._request("GET", self._job_path(kind, namespace, name)))

    def get_job_uncached(self, kind: str, namespace: str, name: str) -> dict:
        return self._get_job_live(kind, namespace, name)

    def list_jobs(self, kind: str, namespace: Optional[str] = None) -> List[dict]:
        store = self._store_list(kind, namespace)
        if store is not None:
            return store
        if namespace:
            path = self._job_path(kind, namespace)
        else:
            path = f"/apis/kubeflow.org/v1/{_job_plural(kind)}"
        return [_normalize_times(i) for i in self._request("GET", path).get("items", [])]

    def update_job(self, job_dict: dict) -> dict:
        meta = job_dict.get("metadata", {})
        return self._request(
            "PUT",
            self._job_path(job_dict["kind"], meta.get("namespace", "default"), meta["name"]),
            job_dict,
        )

    def update_job_status(self, kind: str, namespace: str, name: str, status: dict) -> dict:
        # REPLACE semantics via PUT on the status subresource: the engine
        # sends the entire intended status, and cleared fields (startTime
        # reset on resume) must actually clear — a merge-patch would keep
        # any key to_dict omitted as None, silently resurrecting stale
        # values on the server. Read-modify-write with the current rv;
        # Conflict propagates and the workqueue retries. The read MUST be
        # live: a cache-served (possibly stale) resourceVersion would turn
        # every status write into a conflict until the watch caught up.
        job = self._get_job_live(kind, namespace, name)
        job["status"] = status
        return self._request(
            "PUT", self._job_path(kind, namespace, name) + "/status", job
        )

    # Every JobStatus wire field, derived from the schema itself (not a
    # hand-maintained list that would silently drift when a field is
    # added): to_dict drops unset/empty fields, and a JSON merge-patch
    # keeps any key the payload omits, so patch_job_status must null
    # every absent field explicitly or a cleared one (startTime reset on
    # resume, a drained ledger) would resurrect server-side — the exact
    # hazard the update_job_status comment above documents for naive
    # merge patches.
    _status_wire_keys_cache: Optional[Tuple[str, ...]] = None

    @classmethod
    def _status_wire_keys(cls) -> Tuple[str, ...]:
        if cls._status_wire_keys_cache is None:
            import dataclasses

            from ..api.common import JobStatus
            from ..api.k8s import _to_camel

            # Computed once (this sits on every coalesced status flush);
            # the schema cannot change at runtime.
            cls._status_wire_keys_cache = tuple(
                f.metadata.get("json", _to_camel(f.name))
                for f in dataclasses.fields(JobStatus)
            )
        return cls._status_wire_keys_cache

    def patch_job_status(self, kind: str, namespace: str, name: str, status: dict) -> dict:
        """ONE merge-patch on the status subresource — the coalescing
        writer's verb. Halves the request cost of update_job_status (no
        read-modify-write) and removes the Conflict surface entirely: a
        merge patch carries no resourceVersion precondition.

        Replace semantics hold at the TOP LEVEL: every JobStatus wire
        key the payload omits is nulled explicitly (JSON merge-patch:
        null deletes the key), so a cleared field — startTime reset on
        resume, a ledger drained to {} (to_dict drops it) — really
        clears. Inside a KEPT dict-valued field (replicaStatuses, the
        ledgers) RFC 7386 merges key-wise: a sub-key present server-side
        but absent from the payload would survive. No current writer
        shrinks those maps (replicaStatuses is rebuilt with every spec
        type each sync; ledger types are only ever added or wholly
        reset), but a future path that prunes individual sub-keys must
        use update_job_status's PUT — the sim/stub backends model this
        patch as a full replace and cannot catch the divergence."""
        body = dict(status)
        for key in self._status_wire_keys():
            body.setdefault(key, None)
        return self._request(
            "PATCH",
            self._job_path(kind, namespace, name) + "/status",
            {"status": body},
            content_type="application/merge-patch+json",
        )

    def delete_job(self, kind: str, namespace: str, name: str) -> None:
        self._request("DELETE", self._job_path(kind, namespace, name))

    # ----------------------------------------------------------------- pods
    def create_pod(self, pod: Pod) -> Pod:
        body = to_dict(pod)
        body.setdefault("apiVersion", "v1")
        body.setdefault("kind", "Pod")
        out = self._request(
            "POST", self._core_path("pods", pod.metadata.namespace), body
        )
        return from_dict(Pod, _normalize_times(out))

    def get_pod(self, namespace: str, name: str) -> Pod:
        out = self._request("GET", self._core_path("pods", namespace, name))
        return from_dict(Pod, _normalize_times(out))

    def list_pods(self, namespace: Optional[str] = None,
                  labels: Optional[Dict[str, str]] = None,
                  owner_uid: Optional[str] = None) -> List[Pod]:
        store = self._store_list("pods", namespace, labels, owner_uid)
        if store is not None:
            return store
        query_labels = labels
        if owner_uid is not None:
            # OR semantics need operator scope server-side, narrowed locally.
            query_labels = {constants.LABEL_GROUP_NAME: constants.GROUP_NAME}
        path = self._core_path("pods", namespace)
        if query_labels:
            path += self._selector_query(query_labels)
        items = self._request("GET", path).get("items", [])
        out = [from_dict(Pod, _normalize_times(i)) for i in items]
        if owner_uid is not None:
            out = self._filter_with_owner(out, labels, owner_uid)
        return out

    def update_pod(self, pod: Pod) -> Pod:
        body = to_dict(pod)
        body.setdefault("apiVersion", "v1")
        body.setdefault("kind", "Pod")
        out = self._request(
            "PUT",
            self._core_path("pods", pod.metadata.namespace, pod.metadata.name),
            body,
        )
        return from_dict(Pod, _normalize_times(out))

    def get_pod_log(self, namespace: str, name: str) -> str:
        conn = self._connect()
        try:
            conn.request("GET", self._core_path("pods", namespace, name) + "/log",
                         headers=self._headers())
            resp = conn.getresponse()
            data = resp.read()
            if resp.status == 404:
                raise NotFound(f"pod {namespace}/{name}")
            if resp.status >= 400:
                # An RBAC/auth error body must not masquerade as log text.
                raise RuntimeError(f"pod log {namespace}/{name}: {resp.status} {data[:200]!r}")
            return data.decode("utf-8", errors="replace")
        finally:
            conn.close()

    def stream_pod_log(self, namespace: str, name: str, follow: bool = False,
                       poll_interval: float = 0.2, stop=None):
        """Real `pods/log?follow=true` streaming: one long-lived chunked
        response, yielded as it arrives; the apiserver closes the stream
        when the container terminates. ``stop`` severs the socket from a
        sidecar watcher — a reader blocked in read1 on a quiet pod cannot
        check an event cooperatively, and without the sever an abandoned
        follow would leak the connection for up to the 86400s socket
        timeout. Incremental UTF-8 decode: a multibyte char split across a
        read boundary must not become U+FFFD."""
        import codecs

        if not follow:
            yield self.get_pod_log(namespace, name)
            return
        # A quiet pod (training between log lines) must not kill the
        # stream: _connect(None) would apply the default 30s socket
        # timeout, so pass an explicitly long one (same workaround as the
        # watch path); the server closes the stream on pod termination.
        conn = self._connect(timeout=86400.0)
        done = threading.Event()
        if stop is not None:
            def sever() -> None:
                import socket as socket_mod

                while not done.is_set():
                    if stop.wait(0.2):
                        # Keep waiting for the socket if the reader is
                        # still mid connection setup — returning on a None
                        # sock would make the follow uncancellable.
                        while not done.is_set():
                            sock = conn.sock
                            if sock is not None:
                                try:
                                    # shutdown() interrupts a recv blocked
                                    # in another thread; close() does not.
                                    sock.shutdown(socket_mod.SHUT_RDWR)
                                except Exception:  # noqa: BLE001
                                    pass
                                try:
                                    sock.close()
                                except Exception:  # noqa: BLE001
                                    pass
                                return
                            done.wait(0.1)
                        return
                    if done.is_set():
                        return

            threading.Thread(target=sever, daemon=True,
                             name=f"log-sever-{name}").start()
        decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")
        try:
            conn.request(
                "GET",
                self._core_path("pods", namespace, name) + "/log?follow=true",
                headers=self._headers(),
            )
            resp = conn.getresponse()
            if resp.status == 404:
                raise NotFound(f"pod {namespace}/{name}")
            if resp.status >= 400:
                data = resp.read()
                raise RuntimeError(
                    f"pod log {namespace}/{name}: {resp.status} {data[:200]!r}"
                )
            while True:
                try:
                    chunk = resp.read1(65536)
                except (OSError, http.client.HTTPException):
                    if stop is not None and stop.is_set():
                        return  # severed by stop: clean cancellation
                    raise  # real network failure: a silent return would
                    # masquerade as pod completion and truncate the follow
                if not chunk:
                    text = decoder.decode(b"", final=True)
                    if text:
                        yield text
                    return
                text = decoder.decode(chunk)
                if text:
                    yield text
        finally:
            done.set()
            conn.close()

    def delete_pod(self, namespace: str, name: str, force: bool = False) -> None:
        path = self._core_path("pods", namespace, name)
        if force:
            # Grace-period-0 delete (DeleteOptions as query params, the
            # `kubectl delete --force --grace-period=0` wire form): the
            # apiserver drops the object immediately instead of waiting
            # for a kubelet that may be dead to ack termination.
            path += "?gracePeriodSeconds=0"
        self._request("DELETE", path)

    # ------------------------------------------------------------- services
    def create_service(self, service: Service) -> Service:
        body = to_dict(service)
        body.setdefault("apiVersion", "v1")
        body.setdefault("kind", "Service")
        out = self._request(
            "POST", self._core_path("services", service.metadata.namespace), body
        )
        return from_dict(Service, _normalize_times(out))

    def get_service(self, namespace: str, name: str) -> Service:
        out = self._request("GET", self._core_path("services", namespace, name))
        return from_dict(Service, _normalize_times(out))

    def update_service(self, service: Service) -> Service:
        body = to_dict(service)
        body.setdefault("apiVersion", "v1")
        body.setdefault("kind", "Service")
        out = self._request(
            "PUT",
            self._core_path(
                "services", service.metadata.namespace, service.metadata.name
            ),
            body,
        )
        return from_dict(Service, _normalize_times(out))

    def list_services(self, namespace: Optional[str] = None,
                      labels: Optional[Dict[str, str]] = None,
                      owner_uid: Optional[str] = None) -> List[Service]:
        store = self._store_list("services", namespace, labels, owner_uid)
        if store is not None:
            return store
        query_labels = labels
        if owner_uid is not None:
            query_labels = {constants.LABEL_GROUP_NAME: constants.GROUP_NAME}
        path = self._core_path("services", namespace)
        if query_labels:
            path += self._selector_query(query_labels)
        items = self._request("GET", path).get("items", [])
        out = [from_dict(Service, _normalize_times(i)) for i in items]
        if owner_uid is not None:
            out = self._filter_with_owner(out, labels, owner_uid)
        return out

    def delete_service(self, namespace: str, name: str) -> None:
        self._request("DELETE", self._core_path("services", namespace, name))

    # ----------------------------------------------------------- pod groups
    def create_pod_group(self, group: dict) -> dict:
        ns = group.get("metadata", {}).get("namespace", "default")
        return self._request(
            "POST",
            f"/apis/{_PODGROUP[0]}/{_PODGROUP[1]}/namespaces/{ns}/{_PODGROUP[2]}",
            group,
        )

    def get_pod_group(self, namespace: str, name: str) -> dict:
        return self._request(
            "GET",
            f"/apis/{_PODGROUP[0]}/{_PODGROUP[1]}/namespaces/{namespace}/{_PODGROUP[2]}/{name}",
        )

    def list_pod_groups(self, namespace: Optional[str] = None,
                        labels: Optional[Dict[str, str]] = None) -> List[dict]:
        if namespace:
            path = (
                f"/apis/{_PODGROUP[0]}/{_PODGROUP[1]}/namespaces/{namespace}"
                f"/{_PODGROUP[2]}"
            )
        else:
            # Base-contract parity with the memory backend: no namespace
            # means ALL namespaces (cluster-scoped path), not "default".
            path = f"/apis/{_PODGROUP[0]}/{_PODGROUP[1]}/{_PODGROUP[2]}"
        if labels:
            path += self._selector_query(labels)
        return self._request("GET", path).get("items", [])

    def delete_pod_group(self, namespace: str, name: str) -> None:
        self._request(
            "DELETE",
            f"/apis/{_PODGROUP[0]}/{_PODGROUP[1]}/namespaces/{namespace}/{_PODGROUP[2]}/{name}",
        )

    # --------------------------------------------------------------- leases
    def _lease_path(self, namespace: str, name: str = "") -> str:
        base = f"/apis/{_LEASE[0]}/{_LEASE[1]}/namespaces/{namespace}/{_LEASE[2]}"
        return f"{base}/{name}" if name else base

    def get_lease(self, namespace: str, name: str) -> dict:
        return self._request("GET", self._lease_path(namespace, name))

    def create_lease(self, lease: dict) -> dict:
        meta = lease.get("metadata", {})
        return self._request(
            "POST", self._lease_path(meta.get("namespace", "default")), lease
        )

    def update_lease(self, lease: dict) -> dict:
        meta = lease.get("metadata", {})
        return self._request(
            "PUT",
            self._lease_path(meta.get("namespace", "default"), meta["name"]),
            lease,
        )

    def delete_lease(self, namespace: str, name: str) -> None:
        self._request("DELETE", self._lease_path(namespace, name))

    def list_leases(self, namespace: Optional[str] = None,
                    name_prefix: str = "",
                    labels: Optional[Dict[str, str]] = None) -> List[dict]:
        # One collection GET per namespace. `labels` goes server-side as
        # a labelSelector — membership discovery must not download every
        # heartbeat lease in the namespace just to rank a handful of
        # members; the name prefix stays a client-side filter (lease
        # names cannot be prefix-selected by the apiserver).
        namespace = namespace or self.namespace or "default"
        path = self._lease_path(namespace)
        if labels:
            path += self._selector_query(labels)
        body = self._request("GET", path)
        items = body.get("items") or []
        return [
            lease for lease in items
            if ((lease.get("metadata") or {}).get("name", "")).startswith(
                name_prefix
            )
        ]

    # --------------------------------------------------------------- events
    def record_event(self, event: Event) -> None:
        kind, _, key = event.involved_object.partition("/")
        namespace, _, name = key.partition("/")
        namespace = namespace or "default"
        body = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"generateName": f"{name or 'job'}-", "namespace": namespace},
            "type": event.type,
            "reason": event.reason,
            "message": event.message,
            "involvedObject": {"kind": kind, "namespace": namespace, "name": name},
            "source": {"component": "tf-operator-tpu"},
        }
        try:
            self._request("POST", self._core_path("events", namespace), body)
        except Exception:  # noqa: BLE001 — events are best-effort everywhere
            _log.debug("event write failed", exc_info=True)

    def list_events(self, involved_object: Optional[str] = None) -> List[Event]:
        path = self._core_path("events", None)
        if involved_object:
            # Server-side narrowing: without this a busy cluster returns
            # thousands of unrelated events per call.
            kind, _, key = involved_object.partition("/")
            namespace, _, name = key.partition("/")
            path = self._core_path("events", namespace or "default")
            selector = f"involvedObject.kind={kind},involvedObject.name={name}"
            path += "?" + urllib.parse.urlencode({"fieldSelector": selector})
        items = self._request("GET", path).get("items", [])
        out = []
        for i in items:
            inv = i.get("involvedObject", {})
            key = f"{inv.get('kind', '')}/{inv.get('namespace', 'default')}/{inv.get('name', '')}"
            if involved_object and key != involved_object:
                continue
            out.append(Event(type=i.get("type", ""), reason=i.get("reason", ""),
                             message=i.get("message", ""), involved_object=key))
        return out

    # ------------------------------------------------------------- informer
    def watch(self, kind: str, handler) -> None:
        """Subscribe to events for `kind`. The first subscriber starts the
        kind's single list+watch loop; later subscribers share it and get
        the current store replayed as SYNC so they start complete."""
        # The subscriber must see the snapshot BEFORE any live event — a
        # live MODIFIED delivered ahead of the older SYNC replay of the same
        # object would regress it — and must not MISS events emitted during
        # the replay (a healthy watch stream never relists, so a dropped
        # ADDED/DELETED here would stay invisible until the next resync).
        # Both at once: register a gated wrapper immediately (nothing is
        # missed), replay the snapshot directly, then flush the buffered
        # live events in arrival order and open the gate.
        gate_lock = threading.Lock()
        gate = {"open": False, "buffer": []}

        def gated(event_type, obj):
            with gate_lock:
                if not gate["open"]:
                    gate["buffer"].append((event_type, obj))
                    return
            handler(event_type, obj)

        with self._informer_lock:
            synced = self._synced.setdefault(kind, threading.Event())
            replay = (
                list(self._stores.get(kind, {}).values()) if synced.is_set() else []
            )
            self._handlers.setdefault(kind, []).append(gated)
            if kind not in self._watch_threads:
                thread = threading.Thread(
                    target=self._watch_loop, args=(kind,),
                    daemon=True, name=f"kube-watch-{kind}",
                )
                self._watch_threads[kind] = thread
                thread.start()
        # Handler exceptions here log-and-continue like _emit's steady-state
        # delivery: one bad object must not abort the replay with the gate
        # still closed (the wrapper would then buffer every future event
        # forever, and the subscriber would never hear another one).
        def deliver(event_type, obj):
            try:
                handler(event_type, obj)
            except Exception:
                _log.exception("watch handler for %s failed", kind)

        for _, obj in replay:
            deliver(SYNC, obj)
        while True:
            with gate_lock:
                if not gate["buffer"]:
                    gate["open"] = True
                    break
                pending, gate["buffer"] = gate["buffer"], []
            for event_type, obj in pending:
                deliver(event_type, obj)

    def _store_list(self, kind: str, namespace: Optional[str],
                    labels: Optional[Dict[str, str]] = None,
                    owner_uid: Optional[str] = None):
        """Serve a list from the informer store when primed AND the query
        falls within the watch's scope; None = caller must do a live GET
        (no watch running — e.g. SDK usage — or a query broader than the
        cache: other namespace, or labels outside the watch selector).
        `owner_uid` widens the match to label-match OR owned-by-uid (claim
        protocol view); with a selector-filtered watch that OR cannot be
        served from the cache (released objects drop out of it), so those
        queries always go live."""
        synced = self._synced.get(kind)
        if synced is None or not synced.is_set():
            return None
        if self._namespace and namespace != self._namespace:
            return None  # cache only holds the scoped namespace
        if kind in ("pods", "services") and self._label_selector:
            selector = {}
            for part in self._label_selector.split(","):
                if part.strip():
                    k, _, v = part.partition("=")
                    selector[k.strip()] = v.strip()
            operator_scope = {constants.LABEL_GROUP_NAME: constants.GROUP_NAME}
            if owner_uid is not None and selector != operator_scope:
                # Claim view is label-match OR owned-by-uid. With the default
                # operator-scope selector the cache holds every object the
                # live query would return (a released object keeps its
                # group-name stamp, so it stays in the watch and the
                # owned-by branch of matches_claim_view surfaces it). A
                # NARROWER custom selector, though, drops released-but-owned
                # objects from the watch, so the OR must go to the live
                # operator-scope query. (If the group-name label itself was
                # stripped, even the live query misses it and the object
                # stays orphaned until GC — matching reference informer
                # limits.)
                return None
            # The watch stream is selector-filtered; only queries that imply
            # the selector (engine calls pass the full label stamp) can be
            # answered completely from the store.
            if not labels or any(labels.get(k) != v for k, v in selector.items()):
                return None
        with self._informer_lock:
            entries = [obj for _, obj in self._stores.get(kind, {}).values()]
        out = []
        for obj in entries:
            if isinstance(obj, dict):
                meta = obj.get("metadata") or {}
                if namespace and meta.get("namespace", "default") != namespace:
                    continue
                out.append(json.loads(json.dumps(obj)))  # caller-safe copy
            else:
                if namespace and obj.metadata.namespace != namespace:
                    continue
                if not matches_claim_view(obj, labels, owner_uid):
                    continue
                out.append(obj.deep_copy())
        return out

    @staticmethod
    def _filter_with_owner(items, labels, owner_uid):
        """Client-side claim-view filter for live-GET fallbacks: the
        apiserver cannot express the OR, so the query goes out at operator
        scope and narrows here."""
        return [o for o in items if matches_claim_view(o, labels, owner_uid)]

    def _watch_paths(self, kind: str):
        ns = self._namespace
        if kind == "pods":
            return (
                self._core_path("pods", ns or None),
                self._label_selector,
                lambda o: from_dict(Pod, _normalize_times(o)),
            )
        if kind == "services":
            return (
                self._core_path("services", ns or None),
                self._label_selector,
                lambda o: from_dict(Service, _normalize_times(o)),
            )
        plural = _job_plural(kind)
        path = (
            f"/apis/kubeflow.org/v1/namespaces/{ns}/{plural}"
            if ns
            else f"/apis/kubeflow.org/v1/{plural}"
        )
        return path, None, _normalize_times

    def _emit(self, kind: str, event_type: str, obj) -> None:
        with self._informer_lock:
            handlers = list(self._handlers.get(kind, []))
        for handler in handlers:
            try:
                handler(event_type, obj)
            except Exception:
                _log.exception("watch handler for %s failed", kind)

    def _relist(self, kind: str, path: str, selector: Optional[str], convert) -> str:
        """List, diff against the store, emit ADDED/MODIFIED/SYNC/DELETED
        deltas, replace the store. Returns the collection resourceVersion to
        stream from."""
        base_query = {"labelSelector": selector} if selector else {}
        items, rv = self._list_paginated(path, base_query)
        # Conversion happens outside the lock: a large relist must not stall
        # every cached read and event emission across the operator.
        fresh: Dict[Tuple[str, str], Tuple[str, object]] = {}
        for item in items:
            obj = convert(item)
            ns, name, obj_rv = _meta_of(obj)
            fresh[(ns, name)] = (obj_rv, obj)
        events: List[Tuple[str, object]] = []
        with self._informer_lock:
            old = self._stores.get(kind, {})
            for key, (obj_rv, obj) in fresh.items():
                stale = old.get(key)
                if stale is None:
                    events.append((ADDED, obj))
                elif stale[0] != obj_rv:
                    events.append((MODIFIED, obj))
                else:
                    events.append((SYNC, obj))
            for key, (_, obj) in old.items():
                if key not in fresh:
                    events.append((DELETED, obj))
            self._stores[kind] = fresh
            self._synced.setdefault(kind, threading.Event()).set()
        for event_type, obj in events:
            self._emit(kind, event_type, obj)
        return rv

    def _list_paginated(self, path: str, base_query: dict):
        """Chunked LIST: request `limit`-sized pages and follow `continue`
        tokens (client-go reflector semantics). A 410 Gone mid-pagination
        means the server compacted the snapshot the token referenced —
        restart the list from scratch (bounded), exactly what a reflector
        does. Returns (items, collection resourceVersion)."""
        for attempt in range(4):
            items: List[dict] = []
            cont: Optional[str] = None
            try:
                while True:
                    query = dict(base_query)
                    if self._list_limit:
                        query["limit"] = str(self._list_limit)
                    if cont:
                        query["continue"] = cont
                    full = (f"{path}?{urllib.parse.urlencode(query)}"
                            if query else path)
                    listing = self._request("GET", full)
                    items.extend(listing.get("items", []))
                    meta = listing.get("metadata", {})
                    cont = meta.get("continue")
                    if not cont:
                        return items, meta.get("resourceVersion", "")
            except Gone:
                if attempt == 3:
                    raise
                _log.debug("list %s: continue token expired, restarting", path)
                continue

    def _watch_loop(self, kind: str) -> None:
        path, selector, convert = self._watch_paths(kind)
        rv = ""
        while not self._stop.is_set():
            try:
                if not rv:
                    rv = self._relist(kind, path, selector, convert)
                rv = self._stream(kind, path, selector, rv, convert)
            except Exception:
                if self._stop.is_set():
                    return
                _log.debug("watch %s: reconnecting", kind, exc_info=True)
                rv = ""  # relist (diffed against the store: no ADDED replay)
                time.sleep(1.0)

    def _stream(self, kind: str, path: str, selector: Optional[str], rv: str,
                convert) -> str:
        """One streaming watch connection. Returns the resourceVersion to
        resume from (empty = relist needed)."""
        query = {
            "watch": "true",
            "resourceVersion": rv,
            "allowWatchBookmarks": "true",
            "timeoutSeconds": str(_WATCH_TIMEOUT_SECONDS),
        }
        if selector:
            query["labelSelector"] = selector
        conn = self._connect(timeout=_WATCH_TIMEOUT_SECONDS + 30)
        with self._informer_lock:
            self._stream_conns[kind] = conn
        try:
            token_sent = self._token
            conn.request("GET", f"{path}?{urllib.parse.urlencode(query)}",
                         headers=self._headers(token=token_sent))
            resp = conn.getresponse()
            if resp.status == 410:  # Gone: our rv aged out server-side
                return ""
            if resp.status == 401:
                # Rotated SA token: refresh; the loop's error path re-opens
                # the stream with the fresh credentials.
                self._refresh_token(token_sent)
                raise RuntimeError(f"watch {kind}: 401 (token refreshed, retrying)")
            if resp.status >= 400:
                raise RuntimeError(f"watch {kind}: {resp.status}")
            buffer = b""
            while not self._stop.is_set():
                chunk = resp.read1(65536)
                if not chunk:
                    return rv  # clean server close: resume from last rv
                buffer += chunk
                while b"\n" in buffer:
                    line, buffer = buffer.split(b"\n", 1)
                    if not line.strip():
                        continue
                    evt = json.loads(line)
                    etype = evt.get("type", "")
                    obj_raw = evt.get("object", {})
                    if etype == "BOOKMARK":
                        rv = obj_raw.get("metadata", {}).get("resourceVersion", rv)
                        continue
                    if etype == "ERROR":
                        return ""  # e.g. expired rv delivered in-stream
                    if etype not in (ADDED, MODIFIED, DELETED):
                        continue
                    obj = convert(obj_raw)
                    ns, name, obj_rv = _meta_of(obj)
                    key = (ns, name)
                    rv = obj_rv or rv
                    with self._informer_lock:
                        store = self._stores.setdefault(kind, {})
                        stale = store.get(key)
                        if etype == DELETED:
                            store.pop(key, None)
                        elif stale is not None and stale[0] == obj_rv:
                            continue  # same-RV duplicate (resync echo): drop
                        elif stale is not None:
                            store[key] = (obj_rv, obj)
                            etype = MODIFIED  # replayed ADDED of a known object
                        else:
                            store[key] = (obj_rv, obj)
                    self._emit(kind, etype, obj)
            return rv
        finally:
            with self._informer_lock:
                self._stream_conns.pop(kind, None)
            conn.close()

    def _force_reconnect(self) -> None:
        """Test hook: sever every active watch stream; loops resume/relist."""
        with self._informer_lock:
            conns = list(self._stream_conns.values())
        for conn in conns:
            try:
                conn.sock and conn.sock.close()
            except Exception:
                pass

    def shutdown(self) -> None:
        self._stop.set()
        self._force_reconnect()
