"""Kube-apiserver Cluster backend: the production adapter.

The in-memory and process backends serve tests and dev; this one speaks
the real Kubernetes REST API so the SAME operator binary reconciles a
real cluster (`python -m tf_operator_tpu --kube`). Dependency-free by
design (stdlib http.client + ssl): the image rules out pip installs, and
the API surface we need — typed CRUD on five CRDs, core pods/services/
events, volcano PodGroups, streaming watches — is plain JSON over HTTPS.

Auth: in-cluster service-account (token + CA from
/var/run/secrets/kubernetes.io/serviceaccount, apiserver from
KUBERNETES_SERVICE_HOST/PORT), or explicit base_url/token/ca_file for
tests and kubeconfig-less setups.

Watches: one daemon thread per watched kind runs the list-then-watch
loop (GET ?watch=true streaming newline-delimited {type, object} events,
resuming from the last resourceVersion; 410 Gone → relist). Handlers
receive the same (event_type, object) shapes the other backends emit, so
controllers cannot tell the difference.
"""

from __future__ import annotations

import calendar
import http.client
import json
import logging
import os
import ssl
import threading
import time
import urllib.parse
from typing import Dict, List, Optional

from ..api.k8s import Event, Pod, Service, from_dict, to_dict
from .base import ADDED, DELETED, MODIFIED, Cluster, Conflict, NotFound

_log = logging.getLogger(__name__)

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# kind -> (group, version, plural). Jobs come from the API registry.
_CORE = ("", "v1")
_PODGROUP = ("scheduling.volcano.sh", "v1beta1", "podgroups")


def _job_plural(kind: str) -> str:
    from .. import api

    module = getattr(api, kind.lower())
    return module.PLURAL


def _iso_to_epoch(value):
    """k8s RFC3339 timestamps -> epoch floats (our dataclasses hold floats)."""
    if not isinstance(value, str):
        return value
    try:
        return calendar.timegm(time.strptime(value, "%Y-%m-%dT%H:%M:%SZ"))
    except ValueError:
        return None


def _normalize_times(obj: dict) -> dict:
    meta = obj.get("metadata") or {}
    if "creationTimestamp" in meta:
        meta["creationTimestamp"] = _iso_to_epoch(meta["creationTimestamp"])
    if "deletionTimestamp" in meta:
        meta["deletionTimestamp"] = _iso_to_epoch(meta["deletionTimestamp"])
    status = obj.get("status") or {}
    if "startTime" in status:
        status["startTime"] = _iso_to_epoch(status["startTime"])
    return obj


class KubeCluster(Cluster):
    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
        timeout: float = 30.0,
    ):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "KubeCluster: no base_url and not in-cluster "
                    "(KUBERNETES_SERVICE_HOST unset)"
                )
            base_url = f"https://{host}:{port}"
        if token is None and os.path.exists(f"{_SA_DIR}/token"):
            with open(f"{_SA_DIR}/token") as f:
                token = f.read().strip()
        if ca_file is None and os.path.exists(f"{_SA_DIR}/ca.crt"):
            ca_file = f"{_SA_DIR}/ca.crt"
        self._url = urllib.parse.urlparse(base_url)
        self._token = token
        self._timeout = timeout
        if self._url.scheme == "https":
            if insecure:
                self._ssl = ssl._create_unverified_context()
            else:
                self._ssl = ssl.create_default_context(cafile=ca_file)
        else:
            self._ssl = None
        self._stop = threading.Event()
        self._watch_threads: List[threading.Thread] = []

    # ------------------------------------------------------------- plumbing
    def _connect(self) -> http.client.HTTPConnection:
        host = self._url.hostname
        port = self._url.port or (443 if self._url.scheme == "https" else 80)
        if self._url.scheme == "https":
            return http.client.HTTPSConnection(
                host, port, context=self._ssl, timeout=self._timeout
            )
        return http.client.HTTPConnection(host, port, timeout=self._timeout)

    def _headers(self, content_type: Optional[str] = None) -> Dict[str, str]:
        headers = {"Accept": "application/json"}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        if content_type:
            headers["Content-Type"] = content_type
        return headers

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 content_type: str = "application/json") -> dict:
        conn = self._connect()
        try:
            conn.request(
                method,
                path,
                body=None if body is None else json.dumps(body),
                headers=self._headers(content_type if body is not None else None),
            )
            resp = conn.getresponse()
            data = resp.read()
            if resp.status == 404:
                raise NotFound(f"{method} {path}: 404")
            if resp.status == 409:
                raise Conflict(f"{method} {path}: 409 {data[:200]!r}")
            if resp.status >= 400:
                raise RuntimeError(f"{method} {path}: {resp.status} {data[:300]!r}")
            return json.loads(data) if data else {}
        finally:
            conn.close()

    # ---------------------------------------------------------------- paths
    def _job_path(self, kind: str, namespace: str, name: str = "") -> str:
        plural = _job_plural(kind)
        base = f"/apis/kubeflow.org/v1/namespaces/{namespace}/{plural}"
        return f"{base}/{name}" if name else base

    def _core_path(self, resource: str, namespace: Optional[str], name: str = "") -> str:
        base = (
            f"/api/v1/namespaces/{namespace}/{resource}"
            if namespace
            else f"/api/v1/{resource}"
        )
        return f"{base}/{name}" if name else base

    # ----------------------------------------------------------------- jobs
    def create_job(self, job_dict: dict) -> dict:
        meta = job_dict.get("metadata", {})
        return self._request(
            "POST",
            self._job_path(job_dict["kind"], meta.get("namespace", "default")),
            job_dict,
        )

    def get_job(self, kind: str, namespace: str, name: str) -> dict:
        return _normalize_times(self._request("GET", self._job_path(kind, namespace, name)))

    def list_jobs(self, kind: str, namespace: Optional[str] = None) -> List[dict]:
        if namespace:
            path = self._job_path(kind, namespace)
        else:
            path = f"/apis/kubeflow.org/v1/{_job_plural(kind)}"
        return [_normalize_times(i) for i in self._request("GET", path).get("items", [])]

    def update_job(self, job_dict: dict) -> dict:
        meta = job_dict.get("metadata", {})
        return self._request(
            "PUT",
            self._job_path(job_dict["kind"], meta.get("namespace", "default"), meta["name"]),
            job_dict,
        )

    def update_job_status(self, kind: str, namespace: str, name: str, status: dict) -> dict:
        # REPLACE semantics via PUT on the status subresource: the engine
        # sends the entire intended status, and cleared fields (startTime
        # reset on resume) must actually clear — a merge-patch would keep
        # any key to_dict omitted as None, silently resurrecting stale
        # values on the server. Read-modify-write with the current rv;
        # Conflict propagates and the workqueue retries.
        job = self.get_job(kind, namespace, name)
        job["status"] = status
        return self._request(
            "PUT", self._job_path(kind, namespace, name) + "/status", job
        )

    def delete_job(self, kind: str, namespace: str, name: str) -> None:
        self._request("DELETE", self._job_path(kind, namespace, name))

    # ----------------------------------------------------------------- pods
    def create_pod(self, pod: Pod) -> Pod:
        body = to_dict(pod)
        body.setdefault("apiVersion", "v1")
        body.setdefault("kind", "Pod")
        out = self._request(
            "POST", self._core_path("pods", pod.metadata.namespace), body
        )
        return from_dict(Pod, _normalize_times(out))

    def get_pod(self, namespace: str, name: str) -> Pod:
        out = self._request("GET", self._core_path("pods", namespace, name))
        return from_dict(Pod, _normalize_times(out))

    def list_pods(self, namespace: Optional[str] = None,
                  labels: Optional[Dict[str, str]] = None) -> List[Pod]:
        path = self._core_path("pods", namespace)
        if labels:
            selector = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            path += "?" + urllib.parse.urlencode({"labelSelector": selector})
        items = self._request("GET", path).get("items", [])
        return [from_dict(Pod, _normalize_times(i)) for i in items]

    def update_pod(self, pod: Pod) -> Pod:
        body = to_dict(pod)
        body.setdefault("apiVersion", "v1")
        body.setdefault("kind", "Pod")
        out = self._request(
            "PUT",
            self._core_path("pods", pod.metadata.namespace, pod.metadata.name),
            body,
        )
        return from_dict(Pod, _normalize_times(out))

    def get_pod_log(self, namespace: str, name: str) -> str:
        conn = self._connect()
        try:
            conn.request("GET", self._core_path("pods", namespace, name) + "/log",
                         headers=self._headers())
            resp = conn.getresponse()
            data = resp.read()
            if resp.status == 404:
                raise NotFound(f"pod {namespace}/{name}")
            if resp.status >= 400:
                # An RBAC/auth error body must not masquerade as log text.
                raise RuntimeError(f"pod log {namespace}/{name}: {resp.status} {data[:200]!r}")
            return data.decode("utf-8", errors="replace")
        finally:
            conn.close()

    def delete_pod(self, namespace: str, name: str) -> None:
        self._request("DELETE", self._core_path("pods", namespace, name))

    # ------------------------------------------------------------- services
    def create_service(self, service: Service) -> Service:
        body = to_dict(service)
        body.setdefault("apiVersion", "v1")
        body.setdefault("kind", "Service")
        out = self._request(
            "POST", self._core_path("services", service.metadata.namespace), body
        )
        return from_dict(Service, _normalize_times(out))

    def list_services(self, namespace: Optional[str] = None,
                      labels: Optional[Dict[str, str]] = None) -> List[Service]:
        path = self._core_path("services", namespace)
        if labels:
            selector = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            path += "?" + urllib.parse.urlencode({"labelSelector": selector})
        items = self._request("GET", path).get("items", [])
        return [from_dict(Service, _normalize_times(i)) for i in items]

    def delete_service(self, namespace: str, name: str) -> None:
        self._request("DELETE", self._core_path("services", namespace, name))

    # ----------------------------------------------------------- pod groups
    def create_pod_group(self, group: dict) -> dict:
        ns = group.get("metadata", {}).get("namespace", "default")
        return self._request(
            "POST",
            f"/apis/{_PODGROUP[0]}/{_PODGROUP[1]}/namespaces/{ns}/{_PODGROUP[2]}",
            group,
        )

    def get_pod_group(self, namespace: str, name: str) -> dict:
        return self._request(
            "GET",
            f"/apis/{_PODGROUP[0]}/{_PODGROUP[1]}/namespaces/{namespace}/{_PODGROUP[2]}/{name}",
        )

    def delete_pod_group(self, namespace: str, name: str) -> None:
        self._request(
            "DELETE",
            f"/apis/{_PODGROUP[0]}/{_PODGROUP[1]}/namespaces/{namespace}/{_PODGROUP[2]}/{name}",
        )

    # --------------------------------------------------------------- events
    def record_event(self, event: Event) -> None:
        kind, _, key = event.involved_object.partition("/")
        namespace, _, name = key.partition("/")
        namespace = namespace or "default"
        body = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"generateName": f"{name or 'job'}-", "namespace": namespace},
            "type": event.type,
            "reason": event.reason,
            "message": event.message,
            "involvedObject": {"kind": kind, "namespace": namespace, "name": name},
            "source": {"component": "tf-operator-tpu"},
        }
        try:
            self._request("POST", self._core_path("events", namespace), body)
        except Exception:  # noqa: BLE001 — events are best-effort everywhere
            _log.debug("event write failed", exc_info=True)

    def list_events(self, involved_object: Optional[str] = None) -> List[Event]:
        items = self._request("GET", self._core_path("events", None)).get("items", [])
        out = []
        for i in items:
            inv = i.get("involvedObject", {})
            key = f"{inv.get('kind', '')}/{inv.get('namespace', 'default')}/{inv.get('name', '')}"
            if involved_object and key != involved_object:
                continue
            out.append(Event(type=i.get("type", ""), reason=i.get("reason", ""),
                             message=i.get("message", ""), involved_object=key))
        return out

    # -------------------------------------------------------------- watches
    def watch(self, kind: str, handler) -> None:
        thread = threading.Thread(
            target=self._watch_loop, args=(kind, handler),
            daemon=True, name=f"kube-watch-{kind}",
        )
        self._watch_threads.append(thread)
        thread.start()

    def _watch_paths(self, kind: str):
        if kind == "pods":
            return "/api/v1/pods", lambda o: from_dict(Pod, _normalize_times(o))
        if kind == "services":
            return "/api/v1/services", lambda o: from_dict(Service, _normalize_times(o))
        return f"/apis/kubeflow.org/v1/{_job_plural(kind)}", _normalize_times

    def _watch_loop(self, kind: str, handler) -> None:
        path, convert = self._watch_paths(kind)
        while not self._stop.is_set():
            try:
                listing = self._request("GET", path)
                rv = listing.get("metadata", {}).get("resourceVersion", "")
                for item in listing.get("items", []):
                    handler(ADDED, convert(item))
                self._stream(kind, path, rv, convert, handler)
            except Exception:
                if self._stop.is_set():
                    return
                _log.debug("watch %s: reconnecting", kind, exc_info=True)
                time.sleep(1.0)

    def _stream(self, kind: str, path: str, rv: str, convert, handler) -> None:
        query = urllib.parse.urlencode(
            {"watch": "true", "resourceVersion": rv, "allowWatchBookmarks": "true"}
        )
        conn = self._connect()
        try:
            conn.request("GET", f"{path}?{query}", headers=self._headers())
            resp = conn.getresponse()
            if resp.status == 410:  # Gone: relist
                return
            if resp.status >= 400:
                raise RuntimeError(f"watch {kind}: {resp.status}")
            buffer = b""
            while not self._stop.is_set():
                chunk = resp.read1(65536)
                if not chunk:
                    return  # server closed: relist + rewatch
                buffer += chunk
                while b"\n" in buffer:
                    line, buffer = buffer.split(b"\n", 1)
                    if not line.strip():
                        continue
                    evt = json.loads(line)
                    etype = evt.get("type", "")
                    if etype == "BOOKMARK":
                        continue
                    obj = evt.get("object", {})
                    mapped = {
                        "ADDED": ADDED, "MODIFIED": MODIFIED, "DELETED": DELETED,
                    }.get(etype)
                    if mapped is None:
                        continue
                    handler(mapped, convert(obj))
        finally:
            conn.close()

    def shutdown(self) -> None:
        self._stop.set()
