"""Apiserver request-accounting cluster proxy.

Sits directly over the backend (inside the throttle, inside any chaos
seam's view of the world from the controller's side) and records every
cluster call twice:

- `training_operator_apiserver_requests_total{verb,resource,code}` in the
  metrics registry — the aggregate apiserver-load number the ROADMAP's
  watch-cache/status-coalescing item needs a baseline for;
- `Tracer.record_request` — per-JOB attribution: a request issued while a
  job's sync span is active on this thread is charged to that job's
  trace, and write verbs additionally become `api.<verb>` child spans
  (which is what makes span-order invariants like count-before-teardown
  checkable from the trace alone).

Determinism contract (the same one ThrottledCluster honors): the proxy
forwards every call 1:1 — no extra cluster calls, no reordering, no
sleeps — so a chaos seam underneath sees the identical (method, call
index) sequence with accounting on or off, and every seeded fault tier
replays byte-identically. Recording happens entirely in process memory.

`supports_concurrent_writes` / `supports_concurrent_syncs` pass through
untouched via __getattr__, like every other proxy seam.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

from .base import Conflict, Gone, NotFound, ServerError

# Cluster method -> (verb, resource). Methods absent here (watch,
# stream_pod_log, capability flags, chaos control knobs) pass through
# unaccounted — they are not apiserver request/response calls.
METHOD_VERBS = {
    "create_job": ("create", "jobs"),
    "get_job": ("get", "jobs"),
    "get_job_uncached": ("get", "jobs"),
    "list_jobs": ("list", "jobs"),
    "update_job": ("update", "jobs"),
    # Status writes get their own resource label: they are the coalescing
    # target (today every sync may write status) and must be separable
    # from spec updates in both the counter and the per-job attribution.
    "update_job_status": ("update", "status"),
    # The coalescing writer's single-request status apply: its own verb
    # label so dashboards can watch the update->patch migration (and the
    # coalesced flush rate) directly off apiserver_requests_total.
    "patch_job_status": ("patch", "status"),
    "delete_job": ("delete", "jobs"),
    "create_pod": ("create", "pods"),
    "get_pod": ("get", "pods"),
    "list_pods": ("list", "pods"),
    "update_pod": ("update", "pods"),
    "delete_pod": ("delete", "pods"),
    "get_pod_log": ("get", "pods/log"),
    "create_service": ("create", "services"),
    "get_service": ("get", "services"),
    "list_services": ("list", "services"),
    "update_service": ("update", "services"),
    "delete_service": ("delete", "services"),
    "create_pod_group": ("create", "podgroups"),
    "get_pod_group": ("get", "podgroups"),
    "list_pod_groups": ("list", "podgroups"),
    "delete_pod_group": ("delete", "podgroups"),
    "get_lease": ("get", "leases"),
    "create_lease": ("create", "leases"),
    "update_lease": ("update", "leases"),
    "delete_lease": ("delete", "leases"),
    "record_event": ("create", "events"),
    "list_events": ("list", "events"),
}


def code_of(exc: Optional[BaseException]) -> str:
    """Outcome label: HTTP-analog codes for the typed cluster errors,
    the exception class name for anything else, "200" for success.
    Pure function of the exception type — deterministic under seeded
    fault injection."""
    if exc is None:
        return "200"
    if isinstance(exc, NotFound):
        return "404"
    if isinstance(exc, Conflict):
        return "409"
    if isinstance(exc, Gone):
        return "410"
    if isinstance(exc, ServerError):
        return "500"
    return type(exc).__name__


class AccountingCluster:
    """Delegates everything to `inner`; request/response methods are
    counted + attributed on the way through. Exceptions — including
    BaseException-derived SimulatedCrash, whose planted call must still
    appear in the timeline it kills — are recorded and re-raised
    unchanged."""

    def __init__(self, inner, metrics=None, tracer=None, clock=time.monotonic):
        self._inner = inner
        self._metrics = metrics
        self._tracer = tracer
        self._clock = clock

    def _record(self, verb: str, resource: str, code: str,
                duration: float) -> None:
        if self._metrics is not None:
            self._metrics.apiserver_request_inc(verb, resource, code)
        if self._tracer is not None:
            self._tracer.record_request(verb, resource, code, duration)

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        vr: Optional[Tuple[str, str]] = METHOD_VERBS.get(name)
        if vr is None or not callable(attr):
            # Pass-through attrs (capability flags, fault_log, chaos
            # knobs) are NOT memoized: some are live state.
            return attr
        verb, resource = vr
        record, clock = self._record, self._clock

        def accounted(*args, **kwargs):
            t0 = clock()
            try:
                result = attr(*args, **kwargs)
            except BaseException as exc:
                record(verb, resource, code_of(exc), clock() - t0)
                raise
            record(verb, resource, "200", clock() - t0)
            return result

        # Memoize the wrapper on the instance: __getattr__ only fires on
        # a miss, so every later access is a plain attribute hit — this
        # sits on the hottest path in the process (every apiserver call
        # of every controller), and the inner method binding is stable.
        self.__dict__[name] = accounted
        return accounted
