"""Prometheus-style job metrics.

Reference parity: pkg/common/metrics.go:25-89
(`training_operator_jobs_{created,deleted,successful,failed,restarted}_total`
labeled {job_namespace, framework}); exposition here is dependency-free
Prometheus text format served by the operator CLI.

TPU-native additions: startup/restart latency histograms feeding the
job-startup p50 and restart-MTTR baselines (BASELINE.md).
"""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict, deque
from typing import Dict, List, Optional, Set, Tuple


class _Histogram:
    """Streaming Prometheus histogram: per-bucket counts + sum/count, O(1)
    memory per series no matter how many observations (ADVICE r1: raw
    sample lists grew without bound — observe_reconcile fires on every sync
    of every job). A small bounded `recent` window is kept for tests and
    debug introspection only."""

    __slots__ = ("bounds", "counts", "total", "count", "recent")

    def __init__(self, bounds: Tuple[float, ...]):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +1 = the +Inf bucket
        self.total = 0.0
        self.count = 0
        self.recent = deque(maxlen=256)

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.total += value
        self.count += 1
        self.recent.append(value)

    def cumulative(self) -> List[int]:
        out, running = [], 0
        for c in self.counts[:-1]:
            running += c
            out.append(running)
        return out


def escape_label_value(value) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or the exposition line is invalid
    (label values here include exception strings — sync_errors_total's
    `exception` label, accounting's `code` — which can legally contain
    any of the three)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class Metrics:
    _COUNTERS = (
        ("training_operator_jobs_created_total", "The number of created jobs"),
        ("training_operator_jobs_deleted_total", "The number of deleted jobs"),
        ("training_operator_jobs_successful_total", "The number of successful jobs"),
        ("training_operator_jobs_failed_total", "The number of failed jobs"),
        ("training_operator_jobs_restarted_total", "The number of restarted jobs"),
    )
    # Counters with label sets beyond (job_namespace, framework): name ->
    # (label names, help). Values live in _labeled_counters keyed by the
    # label-value tuple, in label-name order.
    _LABELED_COUNTERS = {
        "training_operator_jobs_restarted_by_cause_total": (
            ("job_namespace", "framework", "cause"),
            "Operator-initiated job restarts by restart cause "
            "(ApplicationFailure consumes backoffLimit; "
            "InfrastructureDisruption consumes maxDisruptionRetries)",
        ),
        "training_operator_expectation_timeouts_total": (
            ("job_namespace", "framework", "kind"),
            "Expectations that expired unfulfilled (a dependent watch event "
            "never arrived); the job self-healed but was wedged for the "
            "full expectation window",
        ),
        "training_operator_force_deletes_total": (
            ("job_namespace", "framework", "cause"),
            "Pods the operator force-deleted (grace-period-0) after they "
            "lingered Terminating past runPolicy.forceDeleteAfterSeconds "
            "(cause StuckTerminating = dead kubelet/reclaimed host). Each "
            "one means a node stopped acking and a gang was blocked",
        ),
        "training_operator_sync_errors_total": (
            ("job_namespace", "framework", "exception"),
            "Reconcile syncs that raised and were rate-limit-requeued "
            "(controllers/base.py process_next). A sustained rate here is "
            "an error-requeue storm: jobs burning backoff delays instead "
            "of converging. Namespace-labeled so a storm surfaced by "
            "interleaved concurrent workers stays attributable to the "
            "tenant causing it",
        ),
        "training_operator_fanout_batches_total": (
            ("framework", "resource"),
            "Slow-start fan-out waves issued (core/control.py "
            "slow_start_batch; resource = pods|services). Parallel "
            "batches double 1->2->4->..., so ~log2(gang size) waves per "
            "fan-out; a serialized fan-out (chaos seam or "
            "--disable-parallel-fanout) counts exactly one wave per "
            "fan-out regardless of gang size",
        ),
        "training_operator_fanout_batch_aborts_total": (
            ("framework", "resource"),
            "Fan-outs aborted by a write error before completing (first-"
            "error abort: a broken pod template costs one apiserver call, "
            "not gang-size of them). Each abort rolled back the "
            "unobserved remainder of its expectation batch and requeued "
            "rate-limited",
        ),
        "training_operator_status_writes_coalesced_total": (
            ("job_namespace", "framework"),
            "Status writes absorbed by the per-job coalescing buffer "
            "(core/job_controller.py write coalescing): the sync's status "
            "delta was pure replica-count churn inside the rate window, so "
            "no apiserver request was issued — a scheduled flush carries "
            "it later. Each increment is one apiserver write saved; a "
            "high rate with a low flush rate is the coalescer working",
        ),
        "training_operator_shard_handoffs_total": (
            ("cause",),
            "Shard ownership transitions at this replica "
            "(core/sharding.py): cause=claim (free/released lease "
            "acquired), steal (expired lease of a dead peer taken over), "
            "rebalance (drained and released because the membership "
            "re-assigned it), reclaim (a drain cancelled mid-flight — "
            "ownership never moved, but the drain window dropped "
            "enqueues so the claim resync re-runs), lost (lease stolen "
            "or renewals failed past the deadline — involuntary), "
            "shutdown (released on clean exit). A sustained "
            "claim/steal/lost rate with stable membership is ownership "
            "flapping",
        ),
        "training_operator_gang_preemptions_total": (
            ("cause", "band"),
            "Gangs preempted by the admission layer (core/admission.py), "
            "by cause (PriorityPreemption = a higher-priority gang needed "
            "the capacity; CapacityRevoked = the declared pool shrank "
            "under the admitted set) and the VICTIM's priority band. "
            "Each increment is exactly one counted disruption restart — "
            "the preempted job re-queued at the head of its band",
        ),
        "training_operator_gang_restarts_total": (
            ("job_namespace", "framework", "scope", "cause"),
            "Counted gang restarts by restart-domain scope "
            "(core/job_controller.py slice-scoped failure domains): "
            "scope=slice is a restart confined to one slice of a "
            "multislice job (surviving slices untouched); scope=world is "
            "a whole-world restart — single-slice jobs, coordinator-"
            "slice loss, or a minSlices quorum escalation. Each "
            "increment is exactly one counted restart in the cause's "
            "ledger",
        ),
        "training_operator_slice_restarts_total": (
            ("job_namespace", "framework", "slice"),
            "Slice-scoped counted restarts by SLICE INDEX (mirrors "
            "status.sliceRestartCounts). A sustained rate on one slice "
            "index across a fleet is slice-restart flapping — a bad "
            "host/link inside that slice's ICI domain restarting the "
            "same slice over and over without ever escalating",
        ),
        "training_operator_quota_denials_total": (
            ("job_namespace",),
            "Admission attempts a namespace quota blocked "
            "(core/admission.py): the tenant's admitted usage plus the "
            "gang's demand exceeded its --namespace-quota. A sustained "
            "rate from one namespace is that tenant queueing on itself, "
            "not on cluster capacity",
        ),
        "training_operator_admission_pump_skipped_total": (
            ("reason",),
            "Admission pump triggers the admissibility index elided "
            "(core/admission.py, EngineOptions.admission_index): "
            "reason=no-capacity-delta is the capacity-epoch short-"
            "circuit (nothing decide-relevant changed since the last "
            "scan — a provable fixpoint); reason=band-watermark is a "
            "whole waiting band (or a single new arrival) pruned "
            "because the free pool cannot cover even its smallest "
            "demand. A near-zero rate with the index ON means the "
            "index is pruning too little and pumps are paying the "
            "full scan anyway",
        ),
        "training_operator_admission_index_fallback_total": (
            ("policy",),
            "Indexed admission pumps that fell back to a full waiting-"
            "set scan because the active policy cannot honor the band "
            "prune (drf's share-resorted passes) or the pool declares "
            "namespace quotas (quota verdicts need every gang "
            "scanned). The no-op short-circuit still applies; a "
            "sustained rate on a policy expected to prune means the "
            "index is configured but not helping",
        ),
        "training_operator_watch_cache_events_served_total": (
            ("resource",),
            "Watch deltas APPLIED to this replica's shared watch-cache "
            "store (cluster/watchcache.py), by resource. Under "
            "shard-scoped caching (--shards > 1) only deltas of owned "
            "shards are applied, so the per-replica rate must fall ~1/N "
            "as replicas are added — the fleet-scale gate's "
            "watch-traffic number",
        ),
        "training_operator_watch_cache_events_filtered_total": (
            ("resource",),
            "Watch deltas DROPPED at the cache boundary: the object's "
            "owning-job key lies outside this replica's owned shards "
            "(or outside the namespace scope). On a balanced N-replica "
            "scoped fleet filtered/(served+filtered) ≈ (N-1)/N; near "
            "zero with --shards > 1 means scoping is not engaged and "
            "every replica is paying fleet-wide watch load",
        ),
        "training_operator_autoscaler_resizes_total": (
            ("direction", "reason"),
            "Elastic resizes the gang autoscaler APPLIED through the "
            "spec-resize path (core/autoscaler.py), by direction "
            "(grow|shrink) and reason (free-capacity = watermark+hold "
            "surplus; placement-quality = gavel generation headroom; "
            "queue-pressure = checkpoint-coordinated shrink for waiting "
            "gangs). A sustained alternation of grow and shrink on one "
            "fleet is autoscaler flapping — widen the hysteresis knobs",
        ),
        "training_operator_autoscaler_blocked_shrinks_total": (
            ("cause",),
            "Shrink decisions the autoscaler WANTED but did not apply, "
            "by binding constraint: no-fresh-checkpoint (waiting on the "
            "record_checkpoint lease rider), cooldown (disruption churn "
            "window), dwell (min time between resizes), at-min (every "
            "elastic job at its minSlices floor). A sustained "
            "no-fresh-checkpoint rate means workloads checkpoint too "
            "rarely for elasticity to act",
        ),
        "training_operator_apiserver_requests_total": (
            ("verb", "resource", "code"),
            "Apiserver requests issued through the cluster seam "
            "(cluster/accounting.py), labeled by verb (get/list/create/"
            "update/delete), resource (pods/services/jobs/status/events/"
            "leases/podgroups), and outcome code (200, 404, 409, 410, "
            "500, or the exception class for anything else). The write "
            "verbs are the apiserver-load number the watch-cache/"
            "status-coalescing work must drive down",
        ),
        "training_restore_total": (
            ("path", "cause"),
            "Restore-ladder outcomes (train/restore.py; workload-reported "
            "via the restore-outcome lease rider when observed by the "
            "operator, recorded directly in-process otherwise), by winning "
            "path (peer|storage|none) and cause (ok on the happy paths; "
            "peer-unreachable / stale-snapshot / checksum-mismatch / "
            "partial-snapshot / no-peers when the peer path degraded). A "
            "sustained storage share with peer restore enabled means the "
            "fast path is not winning — check the degradation causes",
        ),
        "training_checkpoint_persist_bytes_total": (
            ("kind",),
            "Bytes the background persist worker actually wrote to the "
            "checkpoint store, by persist kind (full = every shard "
            "rewritten; delta = only changed shards + the step manifest, "
            "EngineOptions.delta_persist). delta/full per-persist ratio "
            "is the bytes-proportional-to-change number; a delta rate "
            "near the full rate means nearly every shard changes every "
            "step and delta persists are pure overhead (delta-ineffective "
            "alert, docs/monitoring/README.md)",
        ),
        "training_checkpoint_delta_shards_skipped_total": (
            ("kind",),
            "Shards a persist carried forward BY REFERENCE instead of "
            "rewriting (per-shard checksum unchanged since the last "
            "durable step), by persist kind. Always 0 for kind=full "
            "(a full rewrites everything); for kind=delta this is the "
            "savings counter — skipped/(skipped+written) is the fraction "
            "of the tree that sat still between durable steps",
        ),
        "training_restore_bytes_total": (
            ("source",),
            "Payload bytes the restore ladder moved, by winning path "
            "(source=peer|peer-sharded; storage/none restores don't "
            "report wire bytes). With have-list transfer "
            "(restore_with_fallback(have=True)) a warm restore moves "
            "only changed shards, so bytes-per-restore here against "
            "training_restore_total's rate is the "
            "recovery-bytes-proportional-to-change number",
        ),
    }
    # Gauges with label sets: name -> (label names, help). Values live in
    # _labeled_gauges keyed by the label-value tuple, in label-name order.
    _LABELED_GAUGES = {
        "training_operator_heartbeat_age_seconds": (
            ("job_namespace", "framework", "job_name"),
            "Seconds since the operator last observed a heartbeat renewal "
            "from the job's slowest replica (gang liveness; only exported "
            "for jobs with runPolicy.progressDeadlineSeconds set). Crossing "
            "the deadline drives a ProgressStall gang restart",
        ),
        "training_workload_tokens_per_sec": (
            ("job_namespace", "framework", "job_name"),
            "Training throughput the workload last reported through its "
            "heartbeat (runtime.heartbeat.record_progress(tokens_per_sec=), "
            "observed by the liveness check as a lease annotation; max over "
            "the gang's replicas, so a global-throughput reporter yields "
            "the job number directly). Only exported for jobs with "
            "runPolicy.progressDeadlineSeconds set AND a reporting "
            "workload; the series is dropped on terminal/delete. The "
            "utilization signal for "
            "autoscaling: sustained low values beside full capacity mean "
            "the gang holds chips it cannot feed",
        ),
        "training_operator_workqueue_depth": (
            ("framework",),
            "Items waiting in the controller's immediate workqueue "
            "(client-go workqueue_depth analog; sampled on every worker "
            "get). Sustained depth means the workers cannot keep up with "
            "the event rate — scale --workers or raise --qps",
        ),
        "training_operator_owned_jobs": (
            ("shard",),
            "Jobs (all kinds) living in each shard THIS replica owns "
            "(core/sharding.py; updated on claim and on every resync). "
            "Summed across the fleet it must equal the live job count — "
            "a persistent shortfall is an orphaned shard (no live owner)",
        ),
        "training_operator_admission_queue_depth": (
            ("band",),
            "Gangs waiting in each admission priority band "
            "(core/admission.py; only exported with "
            "--enable-gang-admission). Sustained depth in a high band "
            "beside free capacity is an admission bug; depth in low "
            "bands under contention is the design working",
        ),
        "training_operator_admission_dominant_share": (
            ("job_namespace",),
            "Each tenant's dominant share of the admission pool: max "
            "over pool resources of admittedUsage/capacity (the DRF "
            "coordinate, core/policies.py). Under --admission-policy "
            "drf the ratio between two busy tenants' shares must track "
            "their --tenant-weight ratio — a sustained skew beyond it "
            "is the fairness-skew alert (docs/monitoring/README.md)",
        ),
        "training_operator_busy_workers": (
            ("framework",),
            "Sync workers currently inside a reconcile (client-go "
            "busy_workers parity). Pinned at the --workers pool size "
            "while workqueue_depth grows = the pool is saturated; "
            "persistently 0 with depth growing = workers wedged or "
            "quiesced (lost leadership)",
        ),
        "training_checkpoint_last_durable_step": (
            ("job_namespace", "framework", "job_name"),
            "Newest checkpoint step the job's workload reported DURABLE "
            "(record_checkpoint fired from the persist-finalized "
            "durability callback; min over the gang's reporting replicas "
            "— the step every rank has committed). The autoscaler's "
            "checkpoint-gated shrink keys on the same annotation, so "
            "this gauge IS the shrink gate's view: a value frozen while "
            "progress-step advances means persists are failing or the "
            "durability callback is not wired (alert: recovery taxonomy "
            "§13, docs/design/failure_modes.md)",
        ),
    }
    _HISTOGRAM_BUCKETS = (0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600)
    # Reconciles are ms-scale; startup/restart are seconds-scale.
    _BUCKETS_BY_NAME = {
        "training_operator_reconcile_duration_seconds": (
            0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 5,
        ),
        # Queue waits are ms-scale when healthy and explode toward the
        # resync period when the workers fall behind.
        "training_operator_queue_wait_seconds": (
            0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30, 60,
        ),
        # How long coalesced status churn sat dirty before its flush
        # landed: bounded by status_flush_interval when healthy, so the
        # buckets cluster around sub-second values; a tail past the
        # interval means flush requeues are starving behind queue wait.
        "training_operator_status_write_flush_latency_seconds": (
            0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30,
        ),
        # One autoscaler tick: observe + decide + apply. ms-scale when
        # healthy (a handful of lease reads); a tail past a second means
        # the observation fan-out is too wide for the tick interval.
        "training_operator_autoscaler_decision_latency_seconds": (
            0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 5,
        ),
    }
    # Histograms with arbitrary label sets (the (namespace, framework)
    # histograms above predate this): name -> (label names, buckets).
    _LABELED_HISTOGRAMS = {
        # Background persist duration: snapshot enqueued -> orbax finalize
        # (the durability edge). Sub-second locally; object storage pushes
        # toward the tail. The snapshot stall the TRAINING thread pays is
        # deliberately not in here — it's the bench's snapshot_stall number.
        "training_checkpoint_persist_seconds": (
            (), (0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 15, 60, 300),
        ),
        # Restore-ladder duration by winning path + cause (same label
        # vocabulary as training_restore_total). peer must sit left of
        # storage or the fast path is not paying for itself.
        "training_restore_seconds": (
            ("path", "cause"), (0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 15, 60, 300),
        ),
        # One policy-pump pass inside the admission arbiter's lock (the
        # per-tick hot path the fleet simulator columns at 100k objects).
        # Tens-of-microseconds when healthy at bench scale; the tail
        # grows with admitted+waiting set size, so ms-scale buckets.
        "training_operator_admission_pump_seconds": (
            (), (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.1, 0.5),
        ),
        # The PURE decide() inside one autoscaler tick — the planning
        # cost alone, distinct from the whole observe+decide+apply tick
        # (training_operator_autoscaler_decision_latency_seconds).
        "training_operator_autoscaler_decide_seconds": (
            (), (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.1, 0.5),
        ),
    }

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[Tuple[str, str], int]] = {
            name: defaultdict(int) for name, _ in self._COUNTERS
        }
        self._labeled_counters: Dict[str, Dict[Tuple[str, ...], int]] = {
            name: defaultdict(int) for name in self._LABELED_COUNTERS
        }
        self._terminal_seen: Set[Tuple[str, str, str]] = set()
        self._labeled_gauges: Dict[str, Dict[Tuple[str, ...], float]] = {
            name: {} for name in self._LABELED_GAUGES
        }

        def series(name: str):
            bounds = self._BUCKETS_BY_NAME.get(name, self._HISTOGRAM_BUCKETS)
            return defaultdict(lambda: _Histogram(bounds))

        self._histograms: Dict[str, Dict[Tuple[str, str], _Histogram]] = {
            name: series(name)
            for name in (
                "training_operator_job_startup_seconds",
                "training_operator_job_restart_seconds",
                # Per-sync latency (the reference logs "Finished syncing
                # tfjob %q (%v)", controller.go:306; here a histogram).
                "training_operator_reconcile_duration_seconds",
                # Enqueue -> worker-pop wait (client-go
                # workqueue_queue_duration_seconds analog). No namespace
                # dimension (a queue serves every namespace): series are
                # keyed ("", framework).
                "training_operator_queue_wait_seconds",
                # Dirty-buffer age at flush (write coalescing).
                "training_operator_status_write_flush_latency_seconds",
                # Gang queue wait: enqueue -> admission (core/admission.py).
                # The default seconds-to-minutes buckets fit: healthy
                # waits are sub-minute, contention pushes toward the
                # aging bound.
                "training_operator_admission_wait_seconds",
                # One autoscaler observe+decide+apply tick
                # (core/autoscaler.py).
                "training_operator_autoscaler_decision_latency_seconds",
            )
        }
        self._labeled_histograms: Dict[str, Dict[Tuple[str, ...], _Histogram]] = {
            name: defaultdict(lambda bounds=bounds: _Histogram(bounds))
            for name, (_, bounds) in self._LABELED_HISTOGRAMS.items()
        }
        # Unlabeled gauges: leader flag etc. (legacy tf_operator_is_leader,
        # cmd/tf-operator.v1/app/server.go:66-70).
        self._gauges: Dict[str, float] = {}

    def _inc(self, name: str, namespace: str, framework: str) -> None:
        with self._lock:
            self._counters[name][(namespace, framework)] += 1

    def created_inc(self, namespace: str, framework: str) -> None:
        self._inc("training_operator_jobs_created_total", namespace, framework)

    def deleted_inc(self, namespace: str, framework: str) -> None:
        self._inc("training_operator_jobs_deleted_total", namespace, framework)

    def restarted_inc(self, namespace: str, framework: str) -> None:
        self._inc("training_operator_jobs_restarted_total", namespace, framework)

    def _inc_labeled(self, name: str, *label_values: str) -> None:
        with self._lock:
            self._labeled_counters[name][tuple(label_values)] += 1

    def _add_labeled(self, name: str, amount: int, *label_values: str) -> None:
        """Add-by-N for byte-scale counters (_inc_labeled adds exactly 1)."""
        with self._lock:
            self._labeled_counters[name][tuple(label_values)] += int(amount)

    def labeled_counter_value(self, name: str, *label_values: str) -> int:
        with self._lock:
            return self._labeled_counters[name][tuple(label_values)]

    def restarted_by_cause_inc(self, namespace: str, framework: str, cause: str) -> None:
        """Restart-cause breakdown (ApplicationFailure vs
        InfrastructureDisruption) beside the legacy cause-blind
        jobs_restarted_total, which keeps its reference-parity meaning."""
        self._inc_labeled(
            "training_operator_jobs_restarted_by_cause_total",
            namespace, framework, cause,
        )

    def expectation_timeout_inc(self, namespace: str, framework: str, kind: str) -> None:
        self._inc_labeled(
            "training_operator_expectation_timeouts_total",
            namespace, framework, kind,
        )

    def force_delete_inc(self, namespace: str, framework: str, cause: str) -> None:
        """One grace-period-0 escalation of a stuck-Terminating pod."""
        self._inc_labeled(
            "training_operator_force_deletes_total",
            namespace, framework, cause,
        )

    def sync_error_inc(self, namespace: str, framework: str, exception: str) -> None:
        """One sync that raised out of the reconcile and was requeued
        rate-limited — the signal that was previously swallowed silently."""
        self._inc_labeled(
            "training_operator_sync_errors_total", namespace, framework, exception,
        )

    def status_coalesced_inc(self, namespace: str, framework: str) -> None:
        """One status write absorbed by the coalescing buffer (no
        apiserver request issued this sync; a scheduled flush carries
        the churn later)."""
        self._inc_labeled(
            "training_operator_status_writes_coalesced_total",
            namespace, framework,
        )

    def observe_status_flush_latency(self, namespace: str, framework: str,
                                     seconds: float) -> None:
        """One coalesced buffer flushed: `seconds` is how long the oldest
        deferred churn sat dirty before landing on the apiserver."""
        with self._lock:
            self._histograms[
                "training_operator_status_write_flush_latency_seconds"
            ][(namespace, framework)].observe(seconds)

    def gang_restart_inc(self, namespace: str, framework: str, scope: str,
                         cause: str) -> None:
        """One counted gang restart, labeled with its restart-domain
        scope (slice|world) beside the existing cause breakdown."""
        self._inc_labeled(
            "training_operator_gang_restarts_total",
            namespace, framework, scope, cause,
        )

    def slice_restart_inc(self, namespace: str, framework: str,
                          slice_index: str) -> None:
        """One slice-scoped counted restart, labeled by slice index."""
        self._inc_labeled(
            "training_operator_slice_restarts_total",
            namespace, framework, slice_index,
        )

    def gang_preemption_inc(self, cause: str, band: str) -> None:
        """One gang preempted by the admission layer (exactly one counted
        disruption restart; band = the victim's priority band)."""
        self._inc_labeled(
            "training_operator_gang_preemptions_total", cause, band,
        )

    def quota_denial_inc(self, namespace: str) -> None:
        """One admission attempt blocked by the namespace's quota."""
        self._inc_labeled(
            "training_operator_quota_denials_total", namespace,
        )

    def admission_pump_skipped_inc(self, reason: str) -> None:
        """One pump trigger (or one whole band within a pump) the
        admissibility index elided — counted, never silent."""
        self._inc_labeled(
            "training_operator_admission_pump_skipped_total", reason,
        )

    def admission_index_fallback_inc(self, policy: str) -> None:
        """One indexed pump that ran decide over the FULL waiting set
        (the policy or a quota'd pool cannot honor the band prune)."""
        self._inc_labeled(
            "training_operator_admission_index_fallback_total", policy,
        )

    def observe_admission_wait(self, namespace: str, framework: str,
                               seconds: float) -> None:
        """One gang admitted: `seconds` is its enqueue -> admission wait."""
        with self._lock:
            self._histograms["training_operator_admission_wait_seconds"][
                (namespace, framework)
            ].observe(seconds)

    def set_admission_queue_depths(self, depths: Dict[str, float]) -> None:
        """Replace the admission queue-depth gauge wholesale (bands that
        emptied drop their series rather than freezing at a stale depth)."""
        with self._lock:
            self._labeled_gauges["training_operator_admission_queue_depth"] = {
                (band,): float(depth) for band, depth in depths.items()
            }

    def admission_queue_depth_value(self, band: str) -> Optional[float]:
        with self._lock:
            return self._labeled_gauges[
                "training_operator_admission_queue_depth"
            ].get((band,))

    def set_admission_dominant_shares(self, shares: Dict[str, float]) -> None:
        """Replace the per-tenant dominant-share gauge wholesale (a
        tenant whose last gang released drops its series rather than
        freezing at a stale share)."""
        with self._lock:
            self._labeled_gauges[
                "training_operator_admission_dominant_share"
            ] = {(ns,): float(share) for ns, share in shares.items()}

    def admission_dominant_share_value(self, namespace: str) -> Optional[float]:
        with self._lock:
            return self._labeled_gauges[
                "training_operator_admission_dominant_share"
            ].get((namespace,))

    def autoscaler_resize_inc(self, direction: str, reason: str) -> None:
        """One elastic resize the gang autoscaler applied."""
        self._inc_labeled(
            "training_operator_autoscaler_resizes_total", direction, reason,
        )

    def autoscaler_blocked_shrink_inc(self, cause: str) -> None:
        """One shrink decision blocked by its binding constraint."""
        self._inc_labeled(
            "training_operator_autoscaler_blocked_shrinks_total", cause,
        )

    def observe_autoscaler_decision_latency(self, seconds: float) -> None:
        """One autoscaler tick's observe+decide+apply duration."""
        with self._lock:
            self._histograms[
                "training_operator_autoscaler_decision_latency_seconds"
            ][("", "autoscaler")].observe(seconds)

    def apiserver_request_inc(self, verb: str, resource: str, code: str) -> None:
        """One apiserver request completed (any verb, any outcome)."""
        self._inc_labeled(
            "training_operator_apiserver_requests_total", verb, resource, code,
        )

    def watch_cache_served_inc(self, resource: str) -> None:
        """One watch delta applied to the shared watch-cache store."""
        self._inc_labeled(
            "training_operator_watch_cache_events_served_total", resource,
        )

    def watch_cache_filtered_inc(self, resource: str) -> None:
        """One watch delta dropped at the cache's shard/namespace scope."""
        self._inc_labeled(
            "training_operator_watch_cache_events_filtered_total", resource,
        )

    def watch_cache_totals(self) -> Tuple[int, int]:
        """(served, filtered) summed over resources — the per-replica
        watch-traffic number the fleet-scale benchmark gates on."""
        with self._lock:
            served = sum(self._labeled_counters[
                "training_operator_watch_cache_events_served_total"].values())
            filtered = sum(self._labeled_counters[
                "training_operator_watch_cache_events_filtered_total"].values())
        return served, filtered

    def shard_handoff_inc(self, cause: str) -> None:
        """One shard ownership transition at this replica (cause = claim|
        steal|rebalance|lost|shutdown)."""
        self._inc_labeled("training_operator_shard_handoffs_total", cause)

    def set_owned_jobs(self, shard: str, count: float) -> None:
        with self._lock:
            self._labeled_gauges["training_operator_owned_jobs"][
                (shard,)
            ] = float(count)

    def clear_owned_jobs(self, shard: str) -> None:
        """Drop a released shard's series — a stale gauge would read as a
        double owner beside the new holder's."""
        with self._lock:
            self._labeled_gauges["training_operator_owned_jobs"].pop(
                (shard,), None
            )

    def owned_jobs_value(self, shard: str) -> Optional[float]:
        with self._lock:
            return self._labeled_gauges["training_operator_owned_jobs"].get(
                (shard,)
            )

    def busy_workers_inc(self, framework: str) -> None:
        with self._lock:
            gauges = self._labeled_gauges["training_operator_busy_workers"]
            gauges[(framework,)] = gauges.get((framework,), 0.0) + 1.0

    def busy_workers_dec(self, framework: str) -> None:
        with self._lock:
            gauges = self._labeled_gauges["training_operator_busy_workers"]
            gauges[(framework,)] = max(0.0, gauges.get((framework,), 0.0) - 1.0)

    def busy_workers_value(self, framework: str) -> float:
        with self._lock:
            return self._labeled_gauges["training_operator_busy_workers"].get(
                (framework,), 0.0
            )

    def fanout_batch_inc(self, framework: str, resource: str) -> None:
        """One slow-start fan-out wave issued (resource = pods|services)."""
        self._inc_labeled(
            "training_operator_fanout_batches_total", framework, resource,
        )

    def fanout_abort_inc(self, framework: str, resource: str) -> None:
        """One fan-out aborted on its first write error."""
        self._inc_labeled(
            "training_operator_fanout_batch_aborts_total", framework, resource,
        )

    def set_workqueue_depth(self, framework: str, depth: int) -> None:
        with self._lock:
            self._labeled_gauges["training_operator_workqueue_depth"][
                (framework,)
            ] = float(depth)

    def workqueue_depth_value(self, framework: str) -> Optional[float]:
        with self._lock:
            return self._labeled_gauges["training_operator_workqueue_depth"].get(
                (framework,)
            )

    def observe_queue_wait(self, framework: str, seconds: float) -> None:
        """One item's enqueue -> worker-pop wait."""
        with self._lock:
            self._histograms["training_operator_queue_wait_seconds"][
                ("", framework)
            ].observe(seconds)

    def set_heartbeat_age(self, namespace: str, framework: str,
                          job_name: str, seconds: float) -> None:
        """Worst observed heartbeat staleness of one liveness-enabled job
        (updated on every liveness check)."""
        with self._lock:
            self._labeled_gauges["training_operator_heartbeat_age_seconds"][
                (namespace, framework, job_name)
            ] = seconds

    def heartbeat_age_value(self, namespace: str, framework: str,
                            job_name: str) -> Optional[float]:
        with self._lock:
            return self._labeled_gauges[
                "training_operator_heartbeat_age_seconds"
            ].get((namespace, framework, job_name))

    def clear_heartbeat_age(self, namespace: str, framework: str,
                            job_name: str) -> None:
        """Drop a deleted job's series so churn doesn't grow the gauge map
        (same leak class as the terminal-dedup set)."""
        with self._lock:
            self._labeled_gauges["training_operator_heartbeat_age_seconds"].pop(
                (namespace, framework, job_name), None
            )

    def set_workload_tokens_per_sec(self, namespace: str, framework: str,
                                    job_name: str, tps: float) -> None:
        """Latest workload-reported training throughput of one job
        (lease-annotation payload surfaced by the liveness check)."""
        with self._lock:
            self._labeled_gauges["training_workload_tokens_per_sec"][
                (namespace, framework, job_name)
            ] = tps

    def workload_tokens_per_sec_value(self, namespace: str, framework: str,
                                      job_name: str) -> Optional[float]:
        with self._lock:
            return self._labeled_gauges[
                "training_workload_tokens_per_sec"
            ].get((namespace, framework, job_name))

    def clear_workload_tokens_per_sec(self, namespace: str, framework: str,
                                      job_name: str) -> None:
        """Drop a deleted job's series (same leak class as heartbeat age)."""
        with self._lock:
            self._labeled_gauges["training_workload_tokens_per_sec"].pop(
                (namespace, framework, job_name), None
            )

    def observe_checkpoint_persist(self, seconds: float) -> None:
        """One background persist finalized (snapshot enqueue -> orbax
        commit) — observed from the workload's persist worker."""
        with self._lock:
            self._labeled_histograms["training_checkpoint_persist_seconds"][
                ()
            ].observe(seconds)

    def observe_restore(self, path: str, cause: str, seconds: float) -> None:
        """One restore-ladder run: which leg won (path), why anything
        degraded (cause), and how long restart-to-state-restored took."""
        self._inc_labeled("training_restore_total", path, cause)
        with self._lock:
            self._labeled_histograms["training_restore_seconds"][
                (path, cause)
            ].observe(seconds)

    def observe_checkpoint_persist_bytes(self, kind: str, nbytes: int,
                                         shards_skipped: int) -> None:
        """One persist's byte accounting: what hit the store (payloads +
        manifest) and how many shards were carried forward by reference
        (kind = full|delta, train/checkpoint.py delta persists)."""
        self._add_labeled(
            "training_checkpoint_persist_bytes_total", nbytes, kind)
        if shards_skipped:
            self._add_labeled(
                "training_checkpoint_delta_shards_skipped_total",
                shards_skipped, kind)

    def set_delta_chain_depth(self, depth: int) -> None:
        """Manifest-chain depth of the newest persist (0 = full). Bounded
        by delta_full_every; a runaway value means the periodic-full
        forcing is broken (runaway-chain-depth alert)."""
        self.set_gauge("training_checkpoint_delta_chain_depth", float(depth))

    def observe_restore_bytes(self, source: str, nbytes: int) -> None:
        """Wire bytes one restore moved, by winning path (peer rungs only
        — the storage path doesn't meter bytes)."""
        self._add_labeled("training_restore_bytes_total", nbytes, source)

    def observe_admission_pump(self, seconds: float) -> None:
        """One policy-pump pass (wall time under the arbiter's lock)."""
        with self._lock:
            self._labeled_histograms[
                "training_operator_admission_pump_seconds"][()].observe(seconds)

    def observe_autoscaler_decide(self, seconds: float) -> None:
        """One pure decide() evaluation inside an autoscaler tick."""
        with self._lock:
            self._labeled_histograms[
                "training_operator_autoscaler_decide_seconds"][()].observe(
                    seconds)

    def labeled_histogram_stats(
            self, name: str, *label_values: str) -> Tuple[int, float]:
        """(count, sum-of-observations) of one labeled-histogram series —
        the per-call hot-path columns the fleet simulator reports."""
        with self._lock:
            series = self._labeled_histograms[name]
            key = tuple(label_values)
            if key not in series:
                return 0, 0.0
            hist = series[key]
            return hist.count, hist.total

    def labeled_histogram_count(self, name: str, *label_values: str) -> int:
        with self._lock:
            series = self._labeled_histograms[name]
            key = tuple(label_values)
            return series[key].count if key in series else 0

    def set_checkpoint_last_durable_step(self, namespace: str, framework: str,
                                         job_name: str, step: float) -> None:
        """Newest durable checkpoint step of one job (min over reporting
        replicas — the lease-rider payload the liveness check surfaces)."""
        with self._lock:
            self._labeled_gauges["training_checkpoint_last_durable_step"][
                (namespace, framework, job_name)
            ] = step

    def checkpoint_last_durable_step_value(self, namespace: str, framework: str,
                                           job_name: str) -> Optional[float]:
        with self._lock:
            return self._labeled_gauges[
                "training_checkpoint_last_durable_step"
            ].get((namespace, framework, job_name))

    def clear_checkpoint_last_durable_step(self, namespace: str, framework: str,
                                           job_name: str) -> None:
        """Drop a deleted job's series (same leak class as heartbeat age)."""
        with self._lock:
            self._labeled_gauges["training_checkpoint_last_durable_step"].pop(
                (namespace, framework, job_name), None
            )

    def successful_inc_once(self, namespace: str, framework: str, job_key: str) -> None:
        """`job_key` should be the job UID (unique per incarnation): a
        ns/name key would dedup a deleted-and-recreated job against its
        predecessor and undercount the new instance's completion."""
        with self._lock:
            if ("successful", framework, job_key) in self._terminal_seen:
                return
            self._terminal_seen.add(("successful", framework, job_key))
            self._counters["training_operator_jobs_successful_total"][(namespace, framework)] += 1

    def failed_inc_once(self, namespace: str, framework: str, job_key: str) -> None:
        with self._lock:
            if ("failed", framework, job_key) in self._terminal_seen:
                return
            self._terminal_seen.add(("failed", framework, job_key))
            self._counters["training_operator_jobs_failed_total"][(namespace, framework)] += 1

    def forget_terminal(self, framework: str, job_key: str) -> None:
        """Prune the dedup entries of a deleted job so churn doesn't grow
        the set forever (same leak class as the engine's gang cache)."""
        with self._lock:
            self._terminal_seen.discard(("successful", framework, job_key))
            self._terminal_seen.discard(("failed", framework, job_key))

    def observe_startup(self, namespace: str, framework: str, seconds: float) -> None:
        with self._lock:
            self._histograms["training_operator_job_startup_seconds"][(namespace, framework)].observe(seconds)

    def observe_reconcile(self, namespace: str, framework: str, seconds: float) -> None:
        with self._lock:
            self._histograms["training_operator_reconcile_duration_seconds"][(namespace, framework)].observe(seconds)

    def observe_restart(self, namespace: str, framework: str, seconds: float) -> None:
        with self._lock:
            self._histograms["training_operator_job_restart_seconds"][(namespace, framework)].observe(seconds)

    def histogram_values(self, name: str, namespace: str, framework: str):
        """Recent raw observations (bounded window) — test/debug hook; the
        exposition path uses the streaming aggregates."""
        with self._lock:
            return list(self._histograms[name][(namespace, framework)].recent)

    def histogram_quantile(self, name: str, namespace: str, framework: str,
                           q: float) -> Optional[float]:
        """Nearest-bucket upper-bound quantile from the STREAMING bucket
        counts — unlike histogram_values, not biased by the bounded
        recent-window (a long run's early observations stay counted).
        Returns None with no observations; a quantile landing in the
        +Inf bucket reports the largest recent raw value as a best-effort
        cap."""
        import math

        with self._lock:
            hist = self._histograms[name].get((namespace, framework))
            if hist is None or hist.count == 0:
                return None
            rank = max(1, math.ceil(q * hist.count))
            running = 0
            for bound, count in zip(hist.bounds, hist.counts):
                running += count
                if running >= rank:
                    return float(bound)
            return float(max(hist.recent)) if hist.recent else float(
                hist.bounds[-1]
            )

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def gauge_value(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0.0)

    def counter_value(self, name: str, namespace: str, framework: str) -> int:
        with self._lock:
            return self._counters[name][(namespace, framework)]

    def render(self) -> str:
        """Prometheus text exposition format. EVERY label value goes
        through escape_label_value: exception names, namespaces, and
        outcome codes are caller-controlled strings, and an unescaped
        `"` or `\\` in one series used to invalidate the whole page."""
        esc = escape_label_value
        lines: List[str] = []
        with self._lock:
            for name, help_text in self._COUNTERS:
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} counter")
                for (ns, fw), value in sorted(self._counters[name].items()):
                    lines.append(f'{name}{{job_namespace="{esc(ns)}",framework="{esc(fw)}"}} {value}')
            for name, (label_names, help_text) in self._LABELED_COUNTERS.items():
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} counter")
                for values, count in sorted(self._labeled_counters[name].items()):
                    label = ",".join(
                        f'{ln}="{esc(lv)}"' for ln, lv in zip(label_names, values)
                    )
                    lines.append(f"{name}{{{label}}} {count}")
            for name, series in self._histograms.items():
                lines.append(f"# HELP {name} {name.replace('_', ' ')}")
                lines.append(f"# TYPE {name} histogram")
                for (ns, fw), hist in sorted(series.items()):
                    label = f'job_namespace="{esc(ns)}",framework="{esc(fw)}"'
                    for bound, cum in zip(hist.bounds, hist.cumulative()):
                        lines.append(f'{name}_bucket{{{label},le="{bound}"}} {cum}')
                    lines.append(f'{name}_bucket{{{label},le="+Inf"}} {hist.count}')
                    lines.append(f"{name}_sum{{{label}}} {hist.total}")
                    lines.append(f"{name}_count{{{label}}} {hist.count}")
            for name, (label_names, _) in self._LABELED_HISTOGRAMS.items():
                lines.append(f"# HELP {name} {name.replace('_', ' ')}")
                lines.append(f"# TYPE {name} histogram")
                for values, hist in sorted(self._labeled_histograms[name].items()):
                    label = ",".join(
                        f'{ln}="{esc(lv)}"' for ln, lv in zip(label_names, values)
                    )
                    sep = "," if label else ""
                    for bound, cum in zip(hist.bounds, hist.cumulative()):
                        lines.append(
                            f'{name}_bucket{{{label}{sep}le="{bound}"}} {cum}'
                        )
                    lines.append(f'{name}_bucket{{{label}{sep}le="+Inf"}} {hist.count}')
                    lines.append(f"{name}_sum{{{label}}} {hist.total}")
                    lines.append(f"{name}_count{{{label}}} {hist.count}")
            for name, (label_names, help_text) in self._LABELED_GAUGES.items():
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} gauge")
                for values, gauge in sorted(self._labeled_gauges[name].items()):
                    label = ",".join(
                        f'{ln}="{esc(lv)}"' for ln, lv in zip(label_names, values)
                    )
                    lines.append(f"{name}{{{label}}} {gauge:g}")
            for name, value in sorted(self._gauges.items()):
                lines.append(f"# HELP {name} {name.replace('_', ' ')}")
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {value:g}")
        return "\n".join(lines) + "\n"


# Process-wide registry, like the reference's promauto default registry.
METRICS = Metrics()
