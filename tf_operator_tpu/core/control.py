"""Pod/Service control: creation/deletion with controller ownership.

Reference parity: kubeflow/common controller.v1/control
(RealPodControl/RealServiceControl and their fakes, embedded via
common.JobController at tfjob_controller.go:87-104; fakes swapped in by
tests at controller_test.go:63-64).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from ..api.common import JobObject
from ..api.k8s import Event, Pod, Service, new_owner_reference
from ..cluster.base import Cluster
from . import constants

_log = logging.getLogger(__name__)


def owner_ref_for(job: JobObject):
    return new_owner_reference(job.api_version, job.kind, job.name, job.metadata.uid)


def record_event_best_effort(cluster: Cluster, event: Event) -> None:
    """Record an event, swallowing (and logging) any failure.

    Events are observability, never control flow: a recorder failure — a
    throttled or flapping apiserver, an injected chaos fault — must not
    abort the reconcile that produced it. The reference gets this for free
    from client-go's EventRecorder (an async broadcaster that drops on
    error); a direct synchronous call here would turn event loss into job
    loss. Every controller/engine event goes through this one helper so no
    call site can reintroduce the coupling.
    """
    try:
        cluster.record_event(event)
    except Exception as exc:  # noqa: BLE001 — by design: log and move on
        _log.warning(
            "dropping event %s/%s for %s: %s",
            event.type, event.reason, event.involved_object, exc,
        )


class TokenBucket:
    """Client-side write throttling — the reference's --qps/--burst client
    rate limits (options.go:73-83, defaults QPS 5 / burst 10 against the
    apiserver). qps <= 0 disables (unlimited)."""

    def __init__(self, qps: float = 0.0, burst: int = 0, clock=time.monotonic):
        self.qps = qps
        # Reference defaults are QPS 5 / burst 10: with qps set but burst
        # unset, default to 2x qps rather than a burst-less bucket that
        # would serialize every batch of writes.
        if qps > 0 and burst <= 0:
            burst = max(1, int(2 * qps))
        self.burst = max(1, burst) if qps > 0 else 0
        self._tokens = float(self.burst)
        self._last = clock()
        self._clock = clock
        self._lock = threading.Lock()

    def acquire(self) -> None:
        """Block until a token is available (no-op when disabled)."""
        if self.qps <= 0:
            return
        while True:
            with self._lock:
                now = self._clock()
                self._tokens = min(
                    float(self.burst), self._tokens + (now - self._last) * self.qps
                )
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(min(wait, 0.1))


class PodControl:
    def create_pod(self, namespace: str, pod: Pod, job: JobObject) -> None:
        raise NotImplementedError

    def delete_pod(self, namespace: str, name: str, job: JobObject) -> None:
        raise NotImplementedError


class ServiceControl:
    def create_service(self, namespace: str, service: Service, job: JobObject) -> None:
        raise NotImplementedError

    def delete_service(self, namespace: str, name: str, job: JobObject) -> None:
        raise NotImplementedError


class RealPodControl(PodControl):
    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def create_pod(self, namespace: str, pod: Pod, job: JobObject) -> None:
        pod.metadata.namespace = namespace
        pod.metadata.owner_references.append(owner_ref_for(job))
        self.cluster.create_pod(pod)
        record_event_best_effort(
            self.cluster,
            Event(
                type="Normal",
                reason=constants.REASON_SUCCESSFUL_CREATE_POD,
                message=f"Created pod: {pod.metadata.name}",
                involved_object=f"{job.kind}/{job.key()}",
            )
        )

    def delete_pod(self, namespace: str, name: str, job: JobObject) -> None:
        self.cluster.delete_pod(namespace, name)
        record_event_best_effort(
            self.cluster,
            Event(
                type="Normal",
                reason=constants.REASON_SUCCESSFUL_DELETE_POD,
                message=f"Deleted pod: {name}",
                involved_object=f"{job.kind}/{job.key()}",
            )
        )


class RealServiceControl(ServiceControl):
    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def create_service(self, namespace: str, service: Service, job: JobObject) -> None:
        service.metadata.namespace = namespace
        service.metadata.owner_references.append(owner_ref_for(job))
        self.cluster.create_service(service)
        record_event_best_effort(
            self.cluster,
            Event(
                type="Normal",
                reason=constants.REASON_SUCCESSFUL_CREATE_SERVICE,
                message=f"Created service: {service.metadata.name}",
                involved_object=f"{job.kind}/{job.key()}",
            )
        )

    def delete_service(self, namespace: str, name: str, job: JobObject) -> None:
        self.cluster.delete_service(namespace, name)
        record_event_best_effort(
            self.cluster,
            Event(
                type="Normal",
                reason=constants.REASON_SUCCESSFUL_DELETE_SERVICE,
                message=f"Deleted service: {name}",
                involved_object=f"{job.kind}/{job.key()}",
            )
        )


class FakePodControl(PodControl):
    """Records intents without touching a cluster (reference
    control.FakePodControl used throughout controller tests)."""

    def __init__(self):
        self.pods_created: List[Pod] = []
        self.pods_deleted: List[str] = []
        self.create_error: Optional[Exception] = None

    def create_pod(self, namespace: str, pod: Pod, job: JobObject) -> None:
        if self.create_error is not None:
            raise self.create_error
        pod.metadata.namespace = namespace
        pod.metadata.owner_references.append(owner_ref_for(job))
        self.pods_created.append(pod)

    def delete_pod(self, namespace: str, name: str, job: JobObject) -> None:
        self.pods_deleted.append(f"{namespace}/{name}")


class FakeServiceControl(ServiceControl):
    def __init__(self):
        self.services_created: List[Service] = []
        self.services_deleted: List[str] = []

    def create_service(self, namespace: str, service: Service, job: JobObject) -> None:
        service.metadata.namespace = namespace
        service.metadata.owner_references.append(owner_ref_for(job))
        self.services_created.append(service)

    def delete_service(self, namespace: str, name: str, job: JobObject) -> None:
        self.services_deleted.append(f"{namespace}/{name}")
