"""Pod/Service control: creation/deletion with controller ownership.

Reference parity: kubeflow/common controller.v1/control
(RealPodControl/RealServiceControl and their fakes, embedded via
common.JobController at tfjob_controller.go:87-104; fakes swapped in by
tests at controller_test.go:63-64).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Tuple

from ..api.common import JobObject
from ..api.k8s import Event, Pod, Service, new_owner_reference
from ..cluster.base import Cluster
from . import constants

_log = logging.getLogger(__name__)

# Upper bound on in-flight writes of one slow-start fan-out: batches double
# 1 -> 2 -> 4 -> ... and saturate here, so a 128-replica gang never opens
# 128 concurrent apiserver connections from one sync.
SLOW_START_MAX_PARALLELISM = 16


def slow_start_batch(
    count: int,
    fn: Callable[[int], None],
    *,
    parallel: bool = True,
    initial_batch_size: int = 1,
    max_parallelism: int = SLOW_START_MAX_PARALLELISM,
    on_batch: Optional[Callable[[int], None]] = None,
    pool: Optional[ThreadPoolExecutor] = None,
) -> Tuple[int, Optional[Exception]]:
    """Issue ``fn(0) .. fn(count-1)`` in slow-start batches — the upstream
    controller-manager ``slowStartBatch`` idiom (kube-controller-manager
    pkg/controller/replicaset): batch sizes double from
    ``initial_batch_size`` (1 -> 2 -> 4 -> ...), each batch runs
    concurrently on a bounded pool, and the FIRST batch containing an
    error aborts the remainder. A broken pod template therefore costs one
    apiserver call, not N; a healthy template reaches full parallelism
    within log2(N) waves.

    Returns ``(successes, first_error)`` — successes is the exact number
    of ``fn`` calls that returned cleanly (the caller rolls back
    expectations for the ``count - successes`` never-confirmed writes).

    ``parallel=False`` degrades to a strictly-ordered sequential loop that
    stops at the first error: the determinism fallback for cluster seams
    whose fault schedules key on ``(method, per-method call index)``
    (the chaos proxy) or that are not thread-safe (the process tier) —
    call order then equals work-list order, byte-for-byte reproducible.

    ``on_batch`` (optional) fires once per wave with the wave size, before
    the wave runs — the instrumentation hook for batch-size counters.

    ``pool`` (optional) is a caller-owned long-lived executor. Passing one
    keeps worker threads — and with them per-thread keep-alive apiserver
    connections (KubeCluster's ``self._local``) — warm across fan-outs;
    without it a throwaway pool is built per call. A shared pool is never
    shut down here.
    """
    if count <= 0:
        return 0, None
    # A one-write batch gains nothing from a pool; skip the executor
    # machinery (single failed-replica recreates hit this every sync).
    if not parallel or max_parallelism <= 1 or count == 1:
        if on_batch is not None:
            on_batch(count)
        for i in range(count):
            try:
                fn(i)
            except Exception as exc:  # noqa: BLE001 — reported, not hidden
                return i, exc
        return count, None

    own_pool = pool is None
    if own_pool:
        pool = ThreadPoolExecutor(max_workers=max_parallelism)
    successes = 0
    index = 0
    batch = max(1, initial_batch_size)
    try:
        while index < count:
            size = min(batch, count - index, max_parallelism)
            if on_batch is not None:
                on_batch(size)
            futures = []
            submit_error: Optional[Exception] = None
            for j in range(size):
                try:
                    futures.append(pool.submit(fn, index + j))
                except Exception as exc:  # noqa: BLE001 — pool shut under us
                    # A failed submit (a shared pool closed by a racing
                    # controller shutdown) is the wave's error, NOT an
                    # escape from the (successes, first_error) contract:
                    # the already-submitted part of the wave still runs
                    # and must be counted, or the caller's expectation
                    # rollback would roll back writes that landed.
                    submit_error = exc
                    break
            first_error: Optional[Exception] = None
            for future in futures:
                exc = future.exception()
                if exc is None:
                    successes += 1
                elif first_error is None:
                    first_error = exc  # keep the earliest-indexed error
            if first_error is None:
                first_error = submit_error
            if first_error is not None:
                return successes, first_error
            index += size
            batch *= 2
        return successes, None
    finally:
        if own_pool:
            pool.shutdown(wait=True)


def owner_ref_for(job: JobObject):
    return new_owner_reference(job.api_version, job.kind, job.name, job.metadata.uid)


def record_event_best_effort(cluster: Cluster, event: Event) -> None:
    """Record an event, swallowing (and logging) any failure.

    Events are observability, never control flow: a recorder failure — a
    throttled or flapping apiserver, an injected chaos fault — must not
    abort the reconcile that produced it. The reference gets this for free
    from client-go's EventRecorder (an async broadcaster that drops on
    error); a direct synchronous call here would turn event loss into job
    loss. Every controller/engine event goes through this one helper so no
    call site can reintroduce the coupling.
    """
    try:
        cluster.record_event(event)
    except Exception as exc:  # noqa: BLE001 — by design: log and move on
        _log.warning(
            "dropping event %s/%s for %s: %s",
            event.type, event.reason, event.involved_object, exc,
        )


class TokenBucket:
    """Client-side write throttling — the reference's --qps/--burst client
    rate limits (options.go:73-83, defaults QPS 5 / burst 10 against the
    apiserver). qps <= 0 disables (unlimited).

    FIFO-fair under contention: waiters are served in arrival order via a
    queue of per-waiter events, and each released token wakes exactly the
    next waiter in line — no thundering-herd re-race on every refill.
    Parallel fan-out (slow_start_batch) makes N threads contending for
    this one budget the common case; the old spin-under-one-lock acquire
    let an unlucky thread starve arbitrarily long behind later arrivals.
    """

    def __init__(self, qps: float = 0.0, burst: int = 0, clock=time.monotonic):
        self.qps = qps
        # Reference defaults are QPS 5 / burst 10: with qps set but burst
        # unset, default to 2x qps rather than a burst-less bucket that
        # would serialize every batch of writes.
        if qps > 0 and burst <= 0:
            burst = max(1, int(2 * qps))
        self.burst = max(1, burst) if qps > 0 else 0
        self._tokens = float(self.burst)
        self._last = clock()
        self._clock = clock
        self._lock = threading.Lock()
        # FIFO ticket line: each waiting thread parks on its own Event;
        # only the head of the line polls the refill clock.
        self._waiters: deque = deque()

    def _refill_locked(self) -> None:
        now = self._clock()
        self._tokens = min(
            float(self.burst), self._tokens + (now - self._last) * self.qps
        )
        self._last = now

    def acquire(self) -> None:
        """Block until a token is available (no-op when disabled). Tokens
        are granted strictly in arrival order."""
        if self.qps <= 0:
            return
        me = threading.Event()
        with self._lock:
            self._refill_locked()
            if not self._waiters and self._tokens >= 1.0:
                self._tokens -= 1.0
                return  # uncontended fast path
            self._waiters.append(me)
            if self._waiters[0] is me:
                me.set()  # head of the line: poll for refill below
        try:
            while True:
                # Non-head waiters sleep here until the departing head
                # hands them the baton (one targeted set(), no broadcast).
                me.wait(0.05)
                with self._lock:
                    if self._waiters[0] is not me:
                        continue
                    self._refill_locked()
                    if self._tokens >= 1.0:
                        self._tokens -= 1.0
                        self._waiters.popleft()
                        if self._waiters:
                            self._waiters[0].set()
                        return
                    wait = (1.0 - self._tokens) / self.qps
                # Head-only refill poll, bounded so injected test clocks
                # that jump forward are observed promptly.
                time.sleep(min(wait, 0.05))
        except BaseException:
            # A thread unwinding mid-wait (KeyboardInterrupt, injected
            # timeout) must not leave its dead Event in the line: once it
            # reached the head, every later acquire would spin on it
            # forever. Dequeue and hand the baton on.
            with self._lock:
                try:
                    self._waiters.remove(me)
                except ValueError:
                    pass
                if self._waiters:
                    self._waiters[0].set()
            raise


class PodControl:
    """``quiet=True`` suppresses the per-object SuccessfulCreate/Delete
    event — the engine's batched fan-out paths pass it under write
    coalescing and record ONE aggregated event per batch instead of
    gang-size of them (the client-go EventAggregator idea, applied at
    the batch boundary where the aggregate is already known)."""

    def create_pod(self, namespace: str, pod: Pod, job: JobObject,
                   quiet: bool = False) -> None:
        raise NotImplementedError

    def delete_pod(self, namespace: str, name: str, job: JobObject,
                   quiet: bool = False) -> None:
        raise NotImplementedError


class ServiceControl:
    def create_service(self, namespace: str, service: Service, job: JobObject,
                       quiet: bool = False) -> None:
        raise NotImplementedError

    def delete_service(self, namespace: str, name: str, job: JobObject,
                       quiet: bool = False) -> None:
        raise NotImplementedError


class RealPodControl(PodControl):
    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def create_pod(self, namespace: str, pod: Pod, job: JobObject,
                   quiet: bool = False) -> None:
        pod.metadata.namespace = namespace
        pod.metadata.owner_references.append(owner_ref_for(job))
        self.cluster.create_pod(pod)
        if quiet:
            return
        record_event_best_effort(
            self.cluster,
            Event(
                type="Normal",
                reason=constants.REASON_SUCCESSFUL_CREATE_POD,
                message=f"Created pod: {pod.metadata.name}",
                involved_object=f"{job.kind}/{job.key()}",
            )
        )

    def delete_pod(self, namespace: str, name: str, job: JobObject,
                   quiet: bool = False) -> None:
        self.cluster.delete_pod(namespace, name)
        if quiet:
            return
        record_event_best_effort(
            self.cluster,
            Event(
                type="Normal",
                reason=constants.REASON_SUCCESSFUL_DELETE_POD,
                message=f"Deleted pod: {name}",
                involved_object=f"{job.kind}/{job.key()}",
            )
        )


class RealServiceControl(ServiceControl):
    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def create_service(self, namespace: str, service: Service, job: JobObject,
                       quiet: bool = False) -> None:
        service.metadata.namespace = namespace
        service.metadata.owner_references.append(owner_ref_for(job))
        self.cluster.create_service(service)
        if quiet:
            return
        record_event_best_effort(
            self.cluster,
            Event(
                type="Normal",
                reason=constants.REASON_SUCCESSFUL_CREATE_SERVICE,
                message=f"Created service: {service.metadata.name}",
                involved_object=f"{job.kind}/{job.key()}",
            )
        )

    def delete_service(self, namespace: str, name: str, job: JobObject,
                       quiet: bool = False) -> None:
        self.cluster.delete_service(namespace, name)
        if quiet:
            return
        record_event_best_effort(
            self.cluster,
            Event(
                type="Normal",
                reason=constants.REASON_SUCCESSFUL_DELETE_SERVICE,
                message=f"Deleted service: {name}",
                involved_object=f"{job.kind}/{job.key()}",
            )
        )


class FakePodControl(PodControl):
    """Records intents without touching a cluster (reference
    control.FakePodControl used throughout controller tests)."""

    def __init__(self):
        self.pods_created: List[Pod] = []
        self.pods_deleted: List[str] = []
        self.create_error: Optional[Exception] = None

    def create_pod(self, namespace: str, pod: Pod, job: JobObject,
                   quiet: bool = False) -> None:
        if self.create_error is not None:
            raise self.create_error
        pod.metadata.namespace = namespace
        pod.metadata.owner_references.append(owner_ref_for(job))
        self.pods_created.append(pod)

    def delete_pod(self, namespace: str, name: str, job: JobObject,
                   quiet: bool = False) -> None:
        self.pods_deleted.append(f"{namespace}/{name}")


class FakeServiceControl(ServiceControl):
    def __init__(self):
        self.services_created: List[Service] = []
        self.services_deleted: List[str] = []

    def create_service(self, namespace: str, service: Service, job: JobObject,
                       quiet: bool = False) -> None:
        service.metadata.namespace = namespace
        service.metadata.owner_references.append(owner_ref_for(job))
        self.services_created.append(service)

    def delete_service(self, namespace: str, name: str, job: JobObject,
                       quiet: bool = False) -> None:
        self.services_deleted.append(f"{namespace}/{name}")
