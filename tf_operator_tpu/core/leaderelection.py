"""Apiserver-backed leader election over a coordination.k8s.io/v1 Lease.

The reference elects through an EndpointsLock RunOrDie loop
(cmd/tf-operator.v1/app/server.go:168-196): replicas race to write a
holder identity into a shared API object, the winner renews, the rest
retry and take over when the lease expires. Same protocol here, on the
modern Lease resource, built on the Cluster seam's optimistic-concurrency
writes — so the identical lock runs against the real apiserver
(KubeCluster), the HTTP stub, and the in-memory cluster in tests.

Cross-process safety comes from the backend, not this class: every
acquire/renew/steal is a full-object update carrying the resourceVersion
we read, and a concurrent writer's bump turns our write into a Conflict
(= we lost the race, return False and retry next tick).

Two client-go behaviors are deliberately reproduced:

- **Expiry is measured on the local clock from the moment a renewTime
  change is OBSERVED**, never by comparing the remote timestamp against
  local now — otherwise a standby with a skewed clock would "see" a
  freshly renewed lease as expired and steal it while the leader still
  reconciles (dual leaders).
- **A renewing leader survives transient apiserver errors** inside a
  renew-deadline window (0.8 × lease duration from the last successful
  write): one 500/timeout must not halt reconciling while the live lease
  still blocks every standby. Past the deadline it abdicates, by which
  time standbys' own observation timers are about to free the lease.
"""

from __future__ import annotations

import calendar
import logging
import time
from typing import Optional, Tuple

from ..cluster.base import Cluster, Conflict, NotFound

log = logging.getLogger(__name__)

# Fraction of the lease duration a holder keeps claiming leadership while
# renew attempts fail (client-go's RenewDeadline is similarly < LeaseDuration
# so leadership lapses before any standby's steal timer can fire).
_RENEW_DEADLINE_FRACTION = 0.8


def _format_microtime(epoch: float) -> str:
    """RFC3339 with microseconds — the wire format of Lease spec.renewTime
    (metav1.MicroTime)."""
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(epoch)) + (
        ".%06dZ" % int((epoch % 1) * 1e6)
    )


def _parse_microtime(value) -> Optional[float]:
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    whole, _, frac = str(value).rstrip("Z").partition(".")
    try:
        base = calendar.timegm(time.strptime(whole, "%Y-%m-%dT%H:%M:%S"))
    except ValueError:
        return None
    return base + (float("0." + frac) if frac else 0.0)


def _pod_namespace() -> str:
    """The namespace this operator pod runs in — where election RBAC is
    granted (downward-API env, else the service-account mount, else
    'default' for out-of-cluster runs)."""
    import os

    ns = os.environ.get("POD_NAMESPACE")
    if ns:
        return ns
    sa_ns = "/var/run/secrets/kubernetes.io/serviceaccount/namespace"
    if os.path.exists(sa_ns):
        with open(sa_ns) as f:
            return f.read().strip() or "default"
    return "default"


class ClusterLeaseLock:
    """The lock OperatorManager's elect loop drives: try_acquire each tick,
    release on shutdown. Holder identity should be unique per replica
    (reference uses hostname = pod name)."""

    def __init__(
        self,
        cluster: Cluster,
        namespace: Optional[str] = None,
        name: str = "tf-operator-tpu-lock",
        clock=time.time,
        mono=None,
        labels=None,
    ):
        self.cluster = cluster
        self.namespace = namespace or _pod_namespace()
        self.name = name
        # Labels stamped onto the lease's metadata on create AND merged on
        # every renew (the caller may mutate the dict between rounds —
        # the shard coordinator advertises its adopted ring epoch this
        # way). Lease labels are what lets membership discovery be a
        # label-selected LIST instead of a namespace-wide scan.
        self.labels = labels if labels is not None else {}
        self._clock = clock
        # Local observation/deadline timers run on the MONOTONIC clock: a
        # wall-clock NTP step would otherwise age a freshly renewed lease
        # past its duration and let a standby steal it (the same split-brain
        # the renewTime-observation design exists to prevent). Wall clock is
        # only for the wire-format renewTime. Tests injecting a fake clock
        # get it for both, keeping time fully controlled.
        self._mono = mono if mono is not None else (
            time.monotonic if clock is time.time else clock
        )
        # (holder, renewTime-raw) last seen + the LOCAL time we saw it
        # change: the basis for skew-free expiry.
        self._observed: Optional[Tuple[str, str]] = None
        self._observed_at: float = 0.0
        # Local deadline until which we keep claiming leadership across
        # transient renew errors (0 = not holding).
        self._renew_ok_until: float = 0.0
        # Holder identity read at the top of the last try_acquire/observe
        # round (None = lease absent). Advisory: the shard coordinator
        # uses it to classify a successful claim as fresh-claim vs
        # expiry-steal; election decisions never do.
        self.last_holder_seen: Optional[str] = None

    # ----------------------------------------------------------------- api
    def try_acquire(self, identity: str, duration: float) -> bool:
        """One election round. True iff `identity` holds the lease after the
        call: fresh create, own renewal, steal of an expired lease — or a
        still-inside-deadline hold across a transient apiserver error."""
        now = self._clock()
        local = self._mono()
        try:
            lease = self.cluster.get_lease(self.namespace, self.name)
        except NotFound:
            self.last_holder_seen = None
            return self._create(identity, duration, now, local)
        except Exception:
            log.warning("lease get failed", exc_info=True)
            return self._survives_error(local)

        spec = lease.setdefault("spec", {})
        holder = spec.get("holderIdentity")
        self.last_holder_seen = holder or None
        renew_raw = str(spec.get("renewTime"))
        # A foreign/malformed lease can carry an explicit null or garbage
        # leaseDurationSeconds; arithmetic on it must never escape an
        # election round (the exception would kill the elect thread while
        # _is_leader stays latched — dual leaders).
        try:
            held_duration = float(spec.get("leaseDurationSeconds"))
        except (TypeError, ValueError):
            held_duration = duration

        if holder and holder != identity:
            # Skew-safe expiry: restart the local timer whenever the remote
            # record changes; only a lease that has sat UNCHANGED for its
            # full duration on OUR clock is stealable.
            if self._observed != (holder, renew_raw):
                self._observed = (holder, renew_raw)
                self._observed_at = local
            if local < self._observed_at + held_duration:
                self._renew_ok_until = 0.0
                return False
        if holder != identity:
            # Steal/first-claim: count the transition like client-go does.
            spec["leaseTransitions"] = int(spec.get("leaseTransitions") or 0) + 1
            spec["acquireTime"] = _format_microtime(now)
        spec["holderIdentity"] = identity
        spec["renewTime"] = _format_microtime(now)
        spec["leaseDurationSeconds"] = int(duration)
        if self.labels:
            lease.setdefault("metadata", {}).setdefault(
                "labels", {}).update(self.labels)
        try:
            self.cluster.update_lease(lease)
        except Conflict:
            # Someone else wrote concurrently — the unambiguous "you are not
            # the holder" signal. Abdicate immediately (safe direction: an
            # extra standby tick beats dual leaders).
            self._renew_ok_until = 0.0
            return False
        except NotFound:
            # The lease was DELETED between our read and write (operator
            # GC, namespace cleanup, an admin's kubectl). Riding the
            # renew-deadline here is split-brain bait: with no live lease
            # blocking them, every standby's next round CREATES and wins
            # while we still claim leadership. Race the create instead —
            # either we win it cleanly or the Conflict demotes us now.
            return self._create(identity, duration, now, local)
        except Exception:
            log.warning("lease update failed", exc_info=True)
            return self._survives_error(local)
        self._observed = (identity, spec["renewTime"])
        self._observed_at = local
        self._renew_ok_until = local + duration * _RENEW_DEADLINE_FRACTION
        return True

    def _survives_error(self, local: float) -> bool:
        """Transient-error policy: keep leading inside the renew deadline,
        abdicate after (the live lease still blocks standbys meanwhile)."""
        return local < self._renew_ok_until

    def observe(self) -> Optional[str]:
        """Read-only observation round: refresh the local expiry timer
        (same skew-safe rule as try_acquire — the timer restarts whenever
        the remote record CHANGES) without writing anything. The shard
        coordinator runs this on foreign shards every tick, so by the
        time a membership change targets one here, its lease has already
        been sitting on our observation clock — a dead owner's shard is
        stealable on the first claiming tick instead of one full duration
        later. Returns the observed holder (None = absent/unreadable)."""
        local = self._mono()
        try:
            lease = self.cluster.get_lease(self.namespace, self.name)
        except NotFound:
            self.last_holder_seen = None
            self._observed = None
            return None
        except Exception:  # noqa: BLE001 — observation is best-effort
            return self.last_holder_seen
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity") or None
        renew_raw = str(spec.get("renewTime"))
        if holder and self._observed != (holder, renew_raw):
            self._observed = (holder, renew_raw)
            self._observed_at = local
        self.last_holder_seen = holder
        return holder

    def release(self, identity: str) -> None:
        """Voluntary handoff on clean shutdown (reference ReleaseOnCancel):
        clear the holder so a standby wins the very next tick instead of
        waiting out the lease duration.

        MUST NOT raise, whatever the apiserver answers: release runs on
        the shutdown path of a possibly-crashing replica, and a 404 (the
        lease was GC'd), a 409 (a rival stole it between our read and
        write — release-after-steal), or any transient 5xx must not wedge
        the exit. The failure directions are all safe: an unreleased
        lease merely costs standbys one expiry wait."""
        self._renew_ok_until = 0.0
        try:
            lease = self.cluster.get_lease(self.namespace, self.name)
        except NotFound:
            return  # already gone: nothing to hand off
        except Exception:
            log.debug("lease read failed at release", exc_info=True)
            return
        spec = lease.setdefault("spec", {})
        if spec.get("holderIdentity") != identity:
            # Stolen (or never ours): clearing the CURRENT holder's claim
            # would hand a live lease to nobody — leave it alone.
            return
        spec["holderIdentity"] = ""
        spec["renewTime"] = None
        try:
            self.cluster.update_lease(lease)
        except (Conflict, NotFound):
            # Conflict: a rival wrote between our read and write — it is
            # the holder's lease now, not ours to clear. NotFound: deleted
            # under us. Both mean "no handoff needed from us".
            log.debug("lease release superseded", exc_info=True)
        except Exception:
            log.debug("lease release failed", exc_info=True)

    @property
    def holder(self) -> Optional[str]:
        """Advisory view of the current holder (observability/tests). Uses
        the remote timestamps directly — election decisions never do."""
        try:
            lease = self.cluster.get_lease(self.namespace, self.name)
        except Exception:
            return None
        spec = lease.get("spec", {})
        renew = _parse_microtime(spec.get("renewTime"))
        duration = spec.get("leaseDurationSeconds", 0)
        if renew is None or self._clock() >= renew + duration:
            return None
        return spec.get("holderIdentity") or None

    # ------------------------------------------------------------ internals
    def _create(self, identity: str, duration: float, now: float,
                local: Optional[float] = None) -> bool:
        local = self._mono() if local is None else local
        meta = {"namespace": self.namespace, "name": self.name}
        if self.labels:
            meta["labels"] = dict(self.labels)
        lease = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": meta,
            "spec": {
                "holderIdentity": identity,
                "leaseDurationSeconds": int(duration),
                "acquireTime": _format_microtime(now),
                "renewTime": _format_microtime(now),
                "leaseTransitions": 0,
            },
        }
        try:
            self.cluster.create_lease(lease)
        except Conflict:
            return False  # another replica created it first
        except Exception:
            log.warning("lease create failed", exc_info=True)
            return self._survives_error(local)
        self._observed = (identity, lease["spec"]["renewTime"])
        self._observed_at = local
        self._renew_ok_until = local + duration * _RENEW_DEADLINE_FRACTION
        return True
