"""Apiserver-backed leader election over a coordination.k8s.io/v1 Lease.

The reference elects through an EndpointsLock RunOrDie loop
(cmd/tf-operator.v1/app/server.go:168-196): replicas race to write a
holder identity into a shared API object, the winner renews, the rest
retry and take over when the lease expires. Same protocol here, on the
modern Lease resource, built on the Cluster seam's optimistic-concurrency
writes — so the identical lock runs against the real apiserver
(KubeCluster), the HTTP stub, and the in-memory cluster in tests.

Cross-process safety comes from the backend, not this class: every
acquire/renew/steal is a full-object update carrying the resourceVersion
we read, and a concurrent writer's bump turns our write into a Conflict
(= we lost the race, return False and retry next tick).

Two client-go behaviors are deliberately reproduced:

- **Expiry is measured on the local clock from the moment a renewTime
  change is OBSERVED**, never by comparing the remote timestamp against
  local now — otherwise a standby with a skewed clock would "see" a
  freshly renewed lease as expired and steal it while the leader still
  reconciles (dual leaders).
- **A renewing leader survives transient apiserver errors** inside a
  renew-deadline window (0.8 × lease duration from the last successful
  write): one 500/timeout must not halt reconciling while the live lease
  still blocks every standby. Past the deadline it abdicates, by which
  time standbys' own observation timers are about to free the lease.
"""

from __future__ import annotations

import calendar
import logging
import time
from typing import Optional, Tuple

from ..cluster.base import Cluster, Conflict, NotFound

log = logging.getLogger(__name__)

# Fraction of the lease duration a holder keeps claiming leadership while
# renew attempts fail (client-go's RenewDeadline is similarly < LeaseDuration
# so leadership lapses before any standby's steal timer can fire).
_RENEW_DEADLINE_FRACTION = 0.8


def _format_microtime(epoch: float) -> str:
    """RFC3339 with microseconds — the wire format of Lease spec.renewTime
    (metav1.MicroTime)."""
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(epoch)) + (
        ".%06dZ" % int((epoch % 1) * 1e6)
    )


def _parse_microtime(value) -> Optional[float]:
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    whole, _, frac = str(value).rstrip("Z").partition(".")
    try:
        base = calendar.timegm(time.strptime(whole, "%Y-%m-%dT%H:%M:%S"))
    except ValueError:
        return None
    return base + (float("0." + frac) if frac else 0.0)


def _pod_namespace() -> str:
    """The namespace this operator pod runs in — where election RBAC is
    granted (downward-API env, else the service-account mount, else
    'default' for out-of-cluster runs)."""
    import os

    ns = os.environ.get("POD_NAMESPACE")
    if ns:
        return ns
    sa_ns = "/var/run/secrets/kubernetes.io/serviceaccount/namespace"
    if os.path.exists(sa_ns):
        with open(sa_ns) as f:
            return f.read().strip() or "default"
    return "default"


class ClusterLeaseLock:
    """The lock OperatorManager's elect loop drives: try_acquire each tick,
    release on shutdown. Holder identity should be unique per replica
    (reference uses hostname = pod name)."""

    def __init__(
        self,
        cluster: Cluster,
        namespace: Optional[str] = None,
        name: str = "tf-operator-tpu-lock",
        clock=time.time,
        mono=None,
    ):
        self.cluster = cluster
        self.namespace = namespace or _pod_namespace()
        self.name = name
        self._clock = clock
        # Local observation/deadline timers run on the MONOTONIC clock: a
        # wall-clock NTP step would otherwise age a freshly renewed lease
        # past its duration and let a standby steal it (the same split-brain
        # the renewTime-observation design exists to prevent). Wall clock is
        # only for the wire-format renewTime. Tests injecting a fake clock
        # get it for both, keeping time fully controlled.
        self._mono = mono if mono is not None else (
            time.monotonic if clock is time.time else clock
        )
        # (holder, renewTime-raw) last seen + the LOCAL time we saw it
        # change: the basis for skew-free expiry.
        self._observed: Optional[Tuple[str, str]] = None
        self._observed_at: float = 0.0
        # Local deadline until which we keep claiming leadership across
        # transient renew errors (0 = not holding).
        self._renew_ok_until: float = 0.0

    # ----------------------------------------------------------------- api
    def try_acquire(self, identity: str, duration: float) -> bool:
        """One election round. True iff `identity` holds the lease after the
        call: fresh create, own renewal, steal of an expired lease — or a
        still-inside-deadline hold across a transient apiserver error."""
        now = self._clock()
        local = self._mono()
        try:
            lease = self.cluster.get_lease(self.namespace, self.name)
        except NotFound:
            return self._create(identity, duration, now, local)
        except Exception:
            log.warning("lease get failed", exc_info=True)
            return self._survives_error(local)

        spec = lease.setdefault("spec", {})
        holder = spec.get("holderIdentity")
        renew_raw = str(spec.get("renewTime"))
        # A foreign/malformed lease can carry an explicit null or garbage
        # leaseDurationSeconds; arithmetic on it must never escape an
        # election round (the exception would kill the elect thread while
        # _is_leader stays latched — dual leaders).
        try:
            held_duration = float(spec.get("leaseDurationSeconds"))
        except (TypeError, ValueError):
            held_duration = duration

        if holder and holder != identity:
            # Skew-safe expiry: restart the local timer whenever the remote
            # record changes; only a lease that has sat UNCHANGED for its
            # full duration on OUR clock is stealable.
            if self._observed != (holder, renew_raw):
                self._observed = (holder, renew_raw)
                self._observed_at = local
            if local < self._observed_at + held_duration:
                self._renew_ok_until = 0.0
                return False
        if holder != identity:
            # Steal/first-claim: count the transition like client-go does.
            spec["leaseTransitions"] = int(spec.get("leaseTransitions") or 0) + 1
            spec["acquireTime"] = _format_microtime(now)
        spec["holderIdentity"] = identity
        spec["renewTime"] = _format_microtime(now)
        spec["leaseDurationSeconds"] = int(duration)
        try:
            self.cluster.update_lease(lease)
        except Conflict:
            # Someone else wrote concurrently — the unambiguous "you are not
            # the holder" signal. Abdicate immediately (safe direction: an
            # extra standby tick beats dual leaders).
            self._renew_ok_until = 0.0
            return False
        except Exception:
            log.warning("lease update failed", exc_info=True)
            return self._survives_error(local)
        self._observed = (identity, spec["renewTime"])
        self._observed_at = local
        self._renew_ok_until = local + duration * _RENEW_DEADLINE_FRACTION
        return True

    def _survives_error(self, local: float) -> bool:
        """Transient-error policy: keep leading inside the renew deadline,
        abdicate after (the live lease still blocks standbys meanwhile)."""
        return local < self._renew_ok_until

    def release(self, identity: str) -> None:
        """Voluntary handoff on clean shutdown (reference ReleaseOnCancel):
        clear the holder so a standby wins the very next tick instead of
        waiting out the lease duration."""
        self._renew_ok_until = 0.0
        try:
            lease = self.cluster.get_lease(self.namespace, self.name)
        except Exception:
            return
        spec = lease.setdefault("spec", {})
        if spec.get("holderIdentity") != identity:
            return
        spec["holderIdentity"] = ""
        spec["renewTime"] = None
        try:
            self.cluster.update_lease(lease)
        except Exception:
            log.debug("lease release failed", exc_info=True)

    @property
    def holder(self) -> Optional[str]:
        """Advisory view of the current holder (observability/tests). Uses
        the remote timestamps directly — election decisions never do."""
        try:
            lease = self.cluster.get_lease(self.namespace, self.name)
        except Exception:
            return None
        spec = lease.get("spec", {})
        renew = _parse_microtime(spec.get("renewTime"))
        duration = spec.get("leaseDurationSeconds", 0)
        if renew is None or self._clock() >= renew + duration:
            return None
        return spec.get("holderIdentity") or None

    # ------------------------------------------------------------ internals
    def _create(self, identity: str, duration: float, now: float,
                local: Optional[float] = None) -> bool:
        local = self._mono() if local is None else local
        lease = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"namespace": self.namespace, "name": self.name},
            "spec": {
                "holderIdentity": identity,
                "leaseDurationSeconds": int(duration),
                "acquireTime": _format_microtime(now),
                "renewTime": _format_microtime(now),
                "leaseTransitions": 0,
            },
        }
        try:
            self.cluster.create_lease(lease)
        except Conflict:
            return False  # another replica created it first
        except Exception:
            log.warning("lease create failed", exc_info=True)
            return self._survives_error(local)
        self._observed = (identity, lease["spec"]["renewTime"])
        self._observed_at = local
        self._renew_ok_until = local + duration * _RENEW_DEADLINE_FRACTION
        return True
