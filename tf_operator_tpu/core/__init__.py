"""Core reconciler engine — the re-owned kubeflow/common layer (SURVEY.md §2.9)."""

from .expectations import ControllerExpectations
from .job_controller import FrameworkHooks, JobController, gen_general_name
from .workqueue import WorkQueue

__all__ = [
    "ControllerExpectations",
    "FrameworkHooks",
    "JobController",
    "WorkQueue",
    "gen_general_name",
]
