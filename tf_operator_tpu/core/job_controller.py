"""The reconciler engine: ReconcileJobs / ReconcilePods / ReconcileServices.

Re-owns the kubeflow/common v0.3.4 `JobController` the reference embeds in
every framework reconciler (SURVEY.md §2.9 — "the single biggest hidden
component"): run-policy enforcement (CleanPodPolicy / TTL / BackoffLimit /
ActiveDeadline), pod-slice bookkeeping, per-index headless services, gang
(pod-group) creation, expectations-guarded create/delete, and status
write-back. Framework specifics (env injection, status semantics, master
roles) enter through the `FrameworkHooks` interface, folding the reference's
per-framework ReconcilePods override into one engine with policy hooks
(SURVEY.md §7 anti-goals).
"""

from __future__ import annotations

import copy
import hashlib
import logging
import threading
import time
from fractions import Fraction
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..api import common as capi
from ..api.common import JobObject, JobStatus, ReplicaSpec
from ..api.k8s import (
    POD_FAILED,
    POD_PENDING,
    POD_RUNNING,
    POD_SUCCEEDED,
    Event,
    Pod,
    Service,
    ServicePort,
    ServiceSpec,
    to_dict,
)
from ..cluster.base import Cluster
from . import constants
from .control import (
    PodControl,
    ServiceControl,
    record_event_best_effort,
    slow_start_batch,
)
from .expectations import ControllerExpectations

log = logging.getLogger(__name__)


def disruption_backoff_seconds(
    uid: str,
    streak: int,
    base: float = constants.DISRUPTION_BACKOFF_BASE_SECONDS,
    cap: float = constants.DISRUPTION_BACKOFF_MAX_SECONDS,
) -> float:
    """Jittered exponential restart backoff for consecutive disruptions.

    streak 1 (first disruption since the job last ran) restarts
    immediately — a preempted slice should re-queue for capacity at once.
    From streak 2 on: base * 2^(streak-2), capped, scaled by a jitter
    factor in [0.5, 1.0) derived from a hash of (uid, streak). The jitter
    is deterministic per (job incarnation, streak) so a seeded chaos run
    replays the same schedule byte-for-byte, while distinct jobs preempted
    by one maintenance event never thundering-herd the scheduler in
    lockstep.
    """
    if streak <= 1:
        return 0.0
    delay = min(cap, base * (2 ** (streak - 2)))
    digest = hashlib.sha256(f"{uid}:{streak}".encode()).digest()
    fraction = int.from_bytes(digest[:8], "big") / 2**64
    return delay * (0.5 + 0.5 * fraction)


@dataclass
class _HeartbeatState:
    """Per-pod liveness bookkeeping, all on the CONTROLLER's clock (the
    leaderelection skew rule: staleness is measured from the moment a
    renewal is *observed* locally, never remote timestamp vs. local now —
    a worker with a skewed clock must not read as stalled, and a skewed
    operator must not excuse a dead one)."""

    running_since: float  # local time we first saw this pod Running
    raw: Optional[str] = None  # last-seen (holder, renewTime) fingerprint
    observed_at: float = 0.0  # local time `raw` last changed
    seen: bool = False  # a renewal has been observed to HAPPEN
    baselined: bool = False  # first lease read recorded (content ignored)
    # Identity of the observed pod, kept so the prune pass can tell a
    # RESTARTED rank (same index, fresh uid — lease inherited, rebaselined)
    # from a SHRUNK-AWAY one (index now outside the declared world — the
    # lease must be GC'd with the observation, or its last tokens-per-sec
    # annotation outlives the worker until terminal lease GC and a later
    # regrow's pod at this index inherits the stale number).
    pod_name: str = ""
    rtype: str = ""
    index: int = -1
    # Fast-recovery riders observed on this pod's lease (peer_restore):
    # the shard-server address this rank advertised (survivor discovery
    # for recreated pods' TPU_PEER_RESTORE_ADDRS), and the last
    # restore-outcome string already reported through on_restore_observed
    # (dedup — the annotation persists across syncs but each restore
    # must count once).
    peer_addr: Optional[str] = None
    restore_raw: Optional[str] = None


def gen_general_name(job_name: str, rtype: str, index) -> str:
    """"<job>-<rtype lower>-<index>" (reference kubeflow/common
    GenGeneralName, used at tensorflow.go:158, pytorch.go:92-95)."""
    return f"{job_name}-{rtype.lower()}-{index}".replace("/", "-")


def replica_labels(job: JobObject, rtype: str, index) -> Dict[str, str]:
    return {
        constants.LABEL_GROUP_NAME: constants.GROUP_NAME,
        constants.LABEL_JOB_NAME: job.name,
        constants.LABEL_REPLICA_TYPE: rtype.lower(),
        constants.LABEL_REPLICA_INDEX: str(index),
    }


def job_selector(job: JobObject) -> Dict[str, str]:
    return {
        constants.LABEL_GROUP_NAME: constants.GROUP_NAME,
        constants.LABEL_JOB_NAME: job.name,
    }


def gang_owner_ref(job: JobObject) -> dict:
    """ownerReference dict for PodGroup metadata (plain dicts, not typed):
    cascading GC on a real cluster + the UID discriminator for the
    stale-group sweep."""
    return {
        "apiVersion": job.api_version,
        "kind": job.kind,
        "name": job.name,
        "uid": job.metadata.uid,
        "controller": True,
    }


# Kubernetes resource.Quantity arithmetic (the subset PodGroup minResources
# aggregation needs). Exact rational arithmetic throughout: float sums of
# large memory asks (hundreds of Gi across a big gang) accumulate binary
# error that turns an integral byte total fractional and renders it as a
# legal-but-bizarre milli-byte string ("1610612736000m").
_QUANTITY_SUFFIXES = {
    "Ki": Fraction(2**10), "Mi": Fraction(2**20), "Gi": Fraction(2**30),
    "Ti": Fraction(2**40), "Pi": Fraction(2**50), "Ei": Fraction(2**60),
    "n": Fraction(1, 10**9), "u": Fraction(1, 10**6), "m": Fraction(1, 1000),
    "k": Fraction(10**3), "K": Fraction(10**3), "M": Fraction(10**6),
    "G": Fraction(10**9), "T": Fraction(10**12), "P": Fraction(10**15),
    "E": Fraction(10**18),
}

_BINARY_SUFFIXES = (
    ("Ei", 2**60), ("Pi", 2**50), ("Ti", 2**40),
    ("Gi", 2**30), ("Mi", 2**20), ("Ki", 2**10),
)


def _to_fraction(value) -> Fraction:
    if isinstance(value, float):
        return Fraction(str(value))  # exact decimal reading, not the binary repr
    return Fraction(value)


def parse_quantity(value) -> Fraction:
    s = str(value).strip()
    for suffix in ("Ki", "Mi", "Gi", "Ti", "Pi", "Ei"):
        if s.endswith(suffix):
            return Fraction(s[:-2]) * _QUANTITY_SUFFIXES[suffix]
    if s and s[-1] in _QUANTITY_SUFFIXES:
        return Fraction(s[:-1]) * _QUANTITY_SUFFIXES[s[-1]]
    return Fraction(s)


def format_quantity(value, binary: bool = True) -> str:
    value = _to_fraction(value)
    if value.denominator == 1:
        n = value.numerator
        # Memory-style totals come back out in binary suffixes (8Gi, not
        # 8589934592) so schedulers and humans can read them — but only
        # when the inputs used binary suffixes; an aggregated cpu of 1024
        # must not render as "1Ki" on a scheduler dashboard.
        if binary:
            for suffix, mult in _BINARY_SUFFIXES:
                if n >= mult and n % mult == 0:
                    return f"{n // mult}{suffix}"
        return str(n)
    milli = value * 1000
    if milli.denominator == 1:
        return f"{milli.numerator}m"  # fractional cpu-style -> milli
    nano = round(value * 10**9)
    return f"{nano}n"


def aggregate_min_resources(replicas: Dict[str, ReplicaSpec]) -> Dict[str, str]:
    """Sum per-replica container requests (falling back to limits) across
    the whole topology — the reference kubeflow/common SyncPodGroup fills
    PodGroup.spec.minResources the same way so the gang scheduler can
    reserve capacity for the entire job at once."""
    totals: Dict[str, Fraction] = {}
    binary: Dict[str, bool] = {}
    for spec in replicas.values():
        n = spec.replicas or 0
        for container in spec.template.spec.containers:
            resources = container.resources or {}
            requests = resources.get("requests") or resources.get("limits") or {}
            for name, value in requests.items():
                totals[name] = totals.get(name, Fraction(0)) + n * parse_quantity(value)
                if str(value).strip().endswith(("Ki", "Mi", "Gi", "Ti", "Pi", "Ei")):
                    binary[name] = True

    def memory_like(name: str) -> bool:
        # Byte-denominated resources render in binary suffixes even when
        # requested as bare byte counts; cpu/pod-count style resources
        # never do (an aggregated cpu of 1024 must not print "1Ki").
        return (
            name in ("memory", "ephemeral-storage")
            or name.startswith("hugepages-")
        )

    # Exact zeros are dropped, not rendered: a type with 0 replicas in this
    # aggregation (e.g. a slice gang that receives no auxiliary pod under
    # round-robin spread) contributes no reservation, and a literal "0"
    # entry only adds scheduler noise.
    return {
        name: format_quantity(v, binary=binary.get(name, memory_like(name)))
        for name, v in sorted(totals.items())
        if v != 0
    }


def get_container_exit_code(pod: Pod, container_name: str) -> int:
    """Exit code of the framework container, EXIT_CODE_UNSET if not
    terminated (reference tfjob_controller.go:707-715)."""
    exit_code = constants.EXIT_CODE_UNSET
    for status in pod.status.container_statuses:
        if status.name == container_name and status.state.terminated is not None:
            exit_code = status.state.terminated.exit_code
    return exit_code


def get_pod_slices(pods: List[Pod], replicas: int) -> List[List[Pod]]:
    """Bucket pods by their replica-index label. Slice count is
    max(replicas, max_index+1): empty buckets are pods to create, buckets at
    index >= replicas are pods to delete (reference GetPodSlices, semantics
    documented at tfjob_controller.go:672-681)."""
    size = replicas
    indexed: List[tuple] = []
    for pod in pods:
        try:
            index = int(pod.metadata.labels.get(constants.LABEL_REPLICA_INDEX, ""))
        except ValueError:
            continue
        if index < 0:
            continue
        size = max(size, index + 1)
        indexed.append((index, pod))
    slices: List[List[Pod]] = [[] for _ in range(size)]
    for index, pod in indexed:
        slices[index].append(pod)
    return slices


def filter_pods_for_replica_type(pods: List[Pod], rtype: str) -> List[Pod]:
    rt = rtype.lower()
    return [p for p in pods if p.metadata.labels.get(constants.LABEL_REPLICA_TYPE) == rt]


def update_job_replica_statuses(job_status: JobStatus, rtype: str, pod: Pod) -> None:
    """Roll one pod's phase into the per-type counters (reference
    status.go:253-262)."""
    status = job_status.replica_statuses.setdefault(rtype, capi.ReplicaStatus())
    if pod.status.phase == POD_RUNNING:
        status.active += 1
    elif pod.status.phase == POD_SUCCEEDED:
        status.succeeded += 1
    elif pod.status.phase == POD_FAILED:
        status.failed += 1


@dataclass(frozen=True)
class SliceTopology:
    """Slice-indexed restart domains of one multislice job (TF-Replicator's
    multi-level topology applied to recovery, docs/design/failure_modes.md
    §12): `num_slices` domains of `hosts_per_slice` world pods each. A
    retryable loss inside one domain restarts that domain alone;
    `coordinator_slice` (slice 0, hosting the worker-0 jax.distributed
    coordinator every other slice re-rendezvouses through) and the
    `min_slices` quorum escalate to a whole-world restart. None from the
    hook (single-slice jobs, kinds without slice semantics) keeps every
    restart path byte-identical to the flat model."""

    num_slices: int
    hosts_per_slice: int
    min_slices: Optional[int] = None
    coordinator_slice: int = 0


class FrameworkHooks:
    """Per-framework policy plugged into the engine (the reference's
    common.ControllerInterface, tfjob_controller.go:206-595)."""

    kind: str = ""
    default_container_name: str = ""
    default_port_name: str = ""
    default_port: int = 0

    def set_cluster_spec(self, job: JobObject, template, rtype: str, index: int) -> None:
        """Inject the framework's rendezvous env into the pod template
        (TF_CONFIG / MASTER_ADDR / DMLC_* / JAX coordinator — SURVEY.md §2.5)."""
        raise NotImplementedError

    def update_job_status(
        self,
        job: JobObject,
        replicas: Dict[str, ReplicaSpec],
        job_status: JobStatus,
        pods: List[Pod],
    ) -> None:
        """Framework-specific condition semantics (chief/master vs worker-0,
        scheduler-completion, …). `pods` is the engine's already-fetched pod
        list so hooks never re-list on the hot path."""
        raise NotImplementedError

    def is_master_role(self, replicas: Dict[str, ReplicaSpec], rtype: str, index: int) -> bool:
        return False

    def replica_order(self, replicas: Dict[str, ReplicaSpec]) -> List[str]:
        """Iteration order over replica types; frameworks with precedence
        semantics (TF: Chief,Eval,Master,PS,Worker) override."""
        return sorted(replicas.keys())

    def gang_group_name(self, job: JobObject, rtype: str, index: int) -> str:
        """Gang (pod group) a replica belongs to. Default: one gang per job
        (the reference's PodGroup-per-job). The JAX controller groups per
        pod-slice: a slice is all-or-nothing, but one free slice of a
        multislice job may start while others queue."""
        return job.name

    def slice_topology(self, job: JobObject, replicas: Dict[str, ReplicaSpec]):
        """The job's slice-indexed restart domains (SliceTopology), or
        None for kinds/jobs without slice semantics — None keeps every
        restart path the flat whole-world model, byte-identical."""
        return None

    def replica_slice_index(
        self, job: JobObject, topo: SliceTopology,
        replicas: Dict[str, ReplicaSpec], rtype: str, index: int,
    ) -> int:
        """Which slice domain a replica belongs to. Default mirrors the
        gang-group placement every slice-aware kind already uses: world
        members (restart_peers_on_failure types) are slice-shaped —
        rank // hosts_per_slice — while out-of-world auxiliaries spread
        round-robin (index % num_slices, the JAX gang_group_name rule)."""
        if self.restart_peers_on_failure(rtype):
            return min(
                index // max(1, topo.hosts_per_slice), topo.num_slices - 1
            )
        return index % max(1, topo.num_slices)

    def stale_world_pods(
        self, job: JobObject, replicas: Dict[str, ReplicaSpec], pods: List[Pod]
    ) -> List[Pod]:
        """Pods whose rendezvous env no longer matches the spec (elastic
        resize). The engine deletes them all in one sync (batched — restart
        MTTR, SURVEY.md §7 hard parts) and recreates next sync. Default: no
        framework opts in."""
        return []

    def restart_peers_on_failure(self, rtype: str) -> bool:
        """True if a retryable failure of ONE replica must restart the
        job's pods as a whole gang. SPMD worlds need this: a lost process
        invalidates every peer's collectives, and a partially-restarted
        gang leaves survivors wedged on a coordinator that will never
        re-admit the newcomer. Default keeps the reference's GPU-era
        per-replica restart (tfjob_controller.go:717-736), which is right
        for PS/allreduce frameworks that re-admit members."""
        return False

    def gang_groups(self, job: JobObject, replicas: Dict[str, ReplicaSpec], run_policy) -> List[dict]:
        """PodGroup specs to ensure when gang scheduling is on."""
        total = sum(spec.replicas or 0 for spec in replicas.values())
        min_member = total
        sp = run_policy.scheduling_policy
        if sp is not None and sp.min_available is not None:
            min_member = sp.min_available
        # minResources: the user's schedulingPolicy value verbatim when set,
        # else the summed per-replica requests (kubeflow/common SyncPodGroup).
        min_resources = (
            dict(sp.min_resources) if sp is not None and sp.min_resources
            else aggregate_min_resources(replicas)
        )
        return [
            {
                "apiVersion": "scheduling.volcano.sh/v1beta1",
                "kind": "PodGroup",
                "metadata": {
                    "name": job.name,
                    "namespace": job.namespace,
                    # Label + ownerReference stamp: lets the engine
                    # enumerate THIS job's groups and converge away stale
                    # ones (scale-down) — the UID keeps a same-name job of
                    # another kind from being swept by our sweep.
                    "labels": job_selector(job),
                    "ownerReferences": [gang_owner_ref(job)],
                },
                "spec": {
                    "minMember": min_member,
                    "minResources": min_resources,
                    "queue": sp.queue if sp else "",
                    "priorityClassName": sp.priority_class if sp else "",
                },
            }
        ]


@dataclass
class EngineOptions:
    enable_gang_scheduling: bool = False
    gang_scheduler_name: str = constants.GANG_SCHEDULER_NAME_DEFAULT
    # Client-side write throttling (reference --qps/--burst; 0 = unlimited).
    qps: float = 0.0
    burst: int = 0
    # Slow-start parallel fan-out for replica create/delete batches
    # (upstream slowStartBatch). Effective parallelism is ANDed with the
    # cluster seam's supports_concurrent_writes capability: a seam that
    # keys fault schedules on call order (chaos) or is not thread-safe
    # (process tier) serializes regardless of this flag, so turning it
    # off is only needed to measure the serial baseline.
    parallel_fanout: bool = True
    fanout_max_parallelism: int = 16
    # Sync-worker pool size (client-go MaxConcurrentReconciles): N threads
    # per controller pulling from the one WorkQueue, whose dirty/processing
    # sets already guarantee a key is never handed to two workers at once
    # — cross-JOB concurrency with per-job serialization. Like
    # parallel_fanout, the requested count is ANDed with the cluster
    # seam's capability (supports_concurrent_syncs) by
    # resolve_sync_workers: the chaos/crash/process fault tiers pin the
    # pool to 1 so every seeded schedule stays byte-reproducible.
    sync_workers: int = 4
    # Write coalescing (apiserver write-pressure collapse): status writes
    # go out as single-request patches (patch_job_status), pure
    # replica-count churn is buffered per job behind a rate-limited flush
    # (status_flush_interval), and batched create/delete fan-outs record
    # ONE aggregated event instead of gang-size of them. ANDed with the
    # cluster seam's supports_write_coalescing by
    # resolve_write_coalescing — the chaos/crash/process fault tiers pin
    # it off so their (method, call-index)-keyed schedules replay
    # byte-identically. Counted writes (restart ledgers, handled-uid
    # stamps, terminal/suspension conditions) are NEVER deferred: the
    # count-before-teardown protocol needs them durable, synchronous and
    # in order regardless of this flag.
    write_coalescing: bool = True
    status_flush_interval: float = 1.0
    # Fast-recovery peer restore (--enable-peer-restore): heartbeat-
    # enabled replicas are told to run a snapshot shard server
    # (TPU_SHARD_SERVER) and recreated pods receive the survivor
    # addresses the liveness checks observed on heartbeat leases
    # (TPU_PEER_RESTORE_ADDRS), so a restoring rank can fetch host-
    # resident shards instead of paying the storage round-trip. Default
    # OFF: no pod env changes, no new annotations consumed — every
    # PR 1-15 seeded tier replays byte-identically.
    peer_restore: bool = False
    # Sharded peer restore (--enable-sharded-restore, requires
    # peer_restore): recreated pods additionally receive
    # TPU_SHARDED_RESTORE=1 so the restore ladder plans a scatter-gather
    # across ALL advertised survivors (train/restore.py sharded=True)
    # instead of pulling the whole tree from one. Default OFF: no env
    # deltas, every PR 1-17 seeded tier replays byte-identically.
    sharded_restore: bool = False
    # Checkpoint-free elastic warm start (--enable-warm-start, requires
    # peer_restore): when a stale-world resize GROWS the gang, the
    # recreated/new ranks get TPU_WARM_START=1 while the grow settles, so
    # they restore from surviving peers' live host snapshots with zero
    # storage reads (train/restore.py warm_start=True). The flag is
    # per-(job, world-uid) engine state, cleared once the grown world is
    # fully present; a controller crash simply loses it and the ranks run
    # the ordinary ladder — warm start is an optimization contract, never
    # a correctness gate. Default OFF.
    warm_start: bool = False
    # Delta checkpoint persists (--enable-delta-persist, requires
    # peer_restore to matter but is independent): heartbeat-enabled
    # replicas get TPU_DELTA_PERSIST=1 so the workload's
    # CheckpointManager writes only changed shards plus a step manifest
    # (train/checkpoint.py delta persists) and advertises its have-list
    # on peer restores (train/restore.py have=True) — persist and
    # recovery bytes become O(changed shards). Pure workload-side
    # contract: the controller only injects the env var. Default OFF: no
    # env deltas, no delta/ layout written, every PR 1-19 seeded tier
    # replays byte-identically.
    delta_persist: bool = False
    # Incremental admissibility index (--enable-admission-index): the
    # shared AdmissionController maintains per-band minimum-demand
    # watermarks, a capacity epoch / dirty bit, and incremental
    # PolicyState structures so a pump touches only gangs that could
    # NEWLY fit instead of re-scanning the whole waiting set. Pure
    # pruning filter over the decide() seam — schedule-equivalent by
    # contract (byte-equal decision logs; see
    # docs/design/gang_admission.md "Admissibility index"). Default OFF
    # so every seeded tier replays the historical full-scan path
    # byte-identically. Unlike gang admission itself (below), this is a
    # legitimate options field: it parameterizes HOW the one arbiter
    # the manager builds pumps, not WHETHER it exists.
    admission_index: bool = False
    # Capacity-aware gang admission (core/admission.py,
    # --enable-gang-admission) has NO EngineOptions field on purpose:
    # the switch is the `admission` object itself — the operator manager
    # builds ONE AdmissionController when the flag is on and passes it
    # to every engine; None (the default) keeps reconcile_job's gate a
    # single check and the PR 1-8 behavior byte-identical. A boolean
    # here would be a second source of truth that could disagree with
    # the arbiter's presence.


def resolve_write_coalescing(options: EngineOptions, cluster) -> bool:
    """Effective write-coalescing verdict for one engine over one cluster
    seam: the requested EngineOptions.write_coalescing ANDed with the
    seam's supports_write_coalescing capability. Single-sourced like
    resolve_sync_workers so the engine, the operator manager, and the
    regression tests cannot drift on the gating rule."""
    return bool(getattr(options, "write_coalescing", False)) and bool(
        getattr(cluster, "supports_write_coalescing", False)
    )


def resolve_sync_workers(options: EngineOptions, cluster) -> int:
    """Effective sync-worker count for one controller over one cluster
    seam: the requested EngineOptions.sync_workers, forced to 1 when the
    seam does not declare supports_concurrent_syncs. Single-sourced so
    the operator manager, benchmarks, and regression tests cannot drift
    on the gating rule (the mirror of _batch_write's AND with
    supports_concurrent_writes)."""
    requested = max(1, int(getattr(options, "sync_workers", 1) or 1))
    if requested > 1 and not getattr(cluster, "supports_concurrent_syncs", False):
        return 1
    return requested


class JobController:
    """The engine. One instance per framework controller."""

    def __init__(
        self,
        hooks: FrameworkHooks,
        cluster: Cluster,
        pod_control: PodControl,
        service_control: ServiceControl,
        expectations: Optional[ControllerExpectations] = None,
        options: Optional[EngineOptions] = None,
        requeue: Optional[Callable[[str, float], None]] = None,
        clock=time.time,
        on_job_restarting: Optional[Callable[[JobObject, str, str], None]] = None,
        on_gang_restart: Optional[Callable[[JobObject, str, Optional[int], str], None]] = None,
        on_heartbeat_age: Optional[Callable[[JobObject, float], None]] = None,
        on_workload_throughput: Optional[Callable[[JobObject, float], None]] = None,
        on_durable_checkpoint: Optional[Callable[[JobObject, Optional[int]], None]] = None,
        on_restore_observed: Optional[
            Callable[[JobObject, str, str, float, Optional[int]], None]] = None,
        on_force_delete: Optional[Callable[[JobObject, str], None]] = None,
        on_fanout_batch: Optional[Callable[[str, int], None]] = None,
        on_fanout_abort: Optional[Callable[[str], None]] = None,
        on_status_coalesced: Optional[Callable[[JobObject], None]] = None,
        on_status_flush: Optional[Callable[[JobObject, float], None]] = None,
        tracer=None,
        admission=None,
    ):
        self.hooks = hooks
        self.cluster = cluster
        self.pod_control = pod_control
        self.service_control = service_control
        self.expectations = expectations or ControllerExpectations()
        self.options = options or EngineOptions()
        self.requeue = requeue or (lambda key, after: None)
        self.clock = clock
        # (job, rtype, cause) — cause is a RESTART_CAUSE_* constant so the
        # controller's metrics can label restarts by what actually happened.
        self.on_job_restarting = on_job_restarting or (lambda job, rtype, cause: None)
        # (job, scope, slice index or None, cause) — fires once per COUNTED
        # gang restart, labeling its restart-domain scope (slice|world);
        # the controller exports it as gang_restarts_total{scope,cause}
        # and slice_restarts_total{slice}.
        self.on_gang_restart = on_gang_restart or (
            lambda job, scope, slice_index, cause: None
        )
        # (job, worst staleness seconds) — fires on every liveness check of
        # a deadline-opted-in job; the controller exports it as the
        # heartbeat_age_seconds gauge.
        self.on_heartbeat_age = on_heartbeat_age or (lambda job, age: None)
        # (job, tokens/sec or None) — fires when a liveness check observes
        # a workload-reported throughput annotation on any heartbeat lease
        # (record_progress(tokens_per_sec=)); the controller exports the
        # freshest gang-wide value as training_workload_tokens_per_sec —
        # the utilization signal the autoscaler consumes. None means "this
        # job reports no more" (terminal): the series is dropped, not
        # zeroed — a 0.0 would both invent a series for never-reporting
        # jobs and trip low-throughput alerts on every finished job.
        self.on_workload_throughput = on_workload_throughput or (
            lambda job, tps: None
        )
        # (job, step or None) — fires when a liveness check observes the
        # checkpoint-step lease rider (record_checkpoint, which the
        # snapshot-then-persist workload fires only from its durability
        # callback): the MIN over the gang's reporting replicas — the
        # step every rank has committed, the same aggregation the
        # autoscaler's shrink gate uses. Exported as the
        # training_checkpoint_last_durable_step gauge; None drops the
        # series (terminal), mirroring on_workload_throughput.
        self.on_durable_checkpoint = on_durable_checkpoint or (
            lambda job, step: None
        )
        # (job, path, cause, seconds, bytes or None) — fires once per NEW
        # restore-outcome lease rider value observed on any replica
        # (record_restore): which restore-ladder leg won, why, and the
        # wire bytes it moved when the peer path metered them (the
        # optional 4th rider field). Exported as
        # training_restore_total/seconds{path,cause} and
        # training_restore_bytes_total{source}.
        self.on_restore_observed = on_restore_observed or (
            lambda job, path, cause, seconds, bytes_moved=None: None
        )
        # (job, cause) — fires once per grace-period-0 escalation of a
        # stuck-Terminating pod; the controller exports it as the
        # cause-labeled force_deletes_total counter.
        self.on_force_delete = on_force_delete or (lambda job, cause: None)
        # (resource, wave size) once per slow-start wave issued, and
        # (resource,) once per fan-out aborted by a write error — the
        # controller exports them as the fanout batch/abort counters.
        self.on_fanout_batch = on_fanout_batch or (lambda resource, size: None)
        self.on_fanout_abort = on_fanout_abort or (lambda resource: None)
        # (job,) once per status write absorbed by the coalescing buffer,
        # and (job, dirty age seconds) once per flush of a previously
        # dirty buffer — exported as status_writes_coalesced_total and
        # the status_write_flush_latency_seconds histogram.
        self.on_status_coalesced = on_status_coalesced or (lambda job: None)
        self.on_status_flush = on_status_flush or (lambda job, age: None)
        # Write coalescing, resolved once against the seam's capability
        # (the chaos/crash/process tiers pin it off; see EngineOptions).
        self._coalescing = resolve_write_coalescing(self.options, cluster)
        # (job key, uid) -> clock() of the last status flush that reached
        # the apiserver, and -> clock() when the oldest still-unflushed
        # coalesced churn was deferred. Guarded by _status_lock (writes
        # happen on sync workers; forget_job prunes from the watch
        # thread). Pruned via forget_job, like every per-job cache here.
        self._status_last_flush: Dict[tuple, float] = {}
        self._status_dirty_since: Dict[tuple, float] = {}
        self._status_lock = threading.Lock()
        # (job key, uid) -> {pod uid: _HeartbeatState}: the liveness
        # observation cache. In-memory by design — an operator restart (or
        # leader failover) restarts every staleness clock from its own
        # first observation, which is the safe direction: a new leader can
        # only be LATE declaring a stall, never declare one spuriously
        # from state it did not observe. Guarded like _gang_declared.
        self._hb_obs: Dict[tuple, Dict[str, _HeartbeatState]] = {}
        self._hb_lock = threading.Lock()
        # (job key, uid) whose heartbeat leases were already GC'd at
        # terminal: _handle_terminal_job runs on EVERY resync of a
        # finished job, and re-issuing N NotFound lease DELETEs each time
        # would burn the QPS budget (the _suspend_job 'settled' rule).
        # In-memory: an operator restart redoes the GC exactly once.
        self._hb_gc_done: set = set()
        # (job key, job uid, pod uid) already force-deleted (stuck-
        # terminating escalation): gates the event/metric/delete to once
        # per pod per operator incarnation — a force delete accepted but
        # leaving the object behind (foreign finalizer) must not re-fire
        # every sync. In-memory: a restart re-escalates exactly once.
        # Guarded by _hb_lock; pruned via forget_job.
        self._force_deleted: set = set()
        # Long-lived fan-out executor, built lazily on the first parallel
        # batch: reusing threads keeps KubeCluster's per-thread keep-alive
        # connections warm across fan-outs instead of renegotiating TLS
        # every wave. Never used on seams that serialize (chaos/process).
        self._fanout_pool = None
        self._fanout_pool_lock = threading.Lock()
        # Lifecycle tracer (core/tracing.py): spans for gang restarts,
        # liveness checks, force-delete escalations and fan-out waves nest
        # under the controller's sync span. Defaults to the shared
        # disabled instance so engines driven directly (tests, benches)
        # pay one attribute load per call and record nothing.
        if tracer is None:
            from .tracing import NOOP_TRACER

            tracer = NOOP_TRACER
        self.tracer = tracer
        # Gang admission arbiter (core/admission.py), shared across every
        # framework controller of one operator. None (the default, and
        # whenever --enable-gang-admission is off) keeps reconcile_job's
        # admission gate a single None-check — the PR 1-8 seeded tiers
        # replay byte-identically because this path does not exist for
        # them.
        self._admission = admission
        # (job key, uid) -> last-declared gang-group names: gates the stale
        # sweep's uncached LIST to declared-set changes (and once per
        # operator lifetime per job, since this cache is in-memory).
        # Pruned via forget_job when the job vanishes, so a long-lived
        # operator with job churn doesn't accumulate entries forever.
        # Lock: inserts happen on worker threads, prunes on the watch
        # thread delivering DELETED — unsynchronized iteration would race.
        self._gang_declared: Dict[tuple, set] = {}
        self._gang_declared_lock = threading.Lock()
        # (job key, uid) gangs mid-grow under EngineOptions.warm_start,
        # mapped to the survivor address snapshot {pod name: peer addr}
        # captured when the grow was flagged: a stale-world resize that
        # GROWS the declared world adds the key; _build_pod injects
        # TPU_WARM_START=1 while it is present; the liveness sweep clears
        # it once every declared replica is back. The snapshot matters
        # because the teardown itself empties the live observation cache
        # (no pod is Running mid-restart), yet the replaced ranks' shard
        # servers keep serving through their termination grace — without
        # it the grown world would have no peers to warm-start from.
        # In-memory on purpose — a controller crash loses the flag and the
        # recreated ranks run the ordinary restore ladder (warm start is
        # an optimization contract, never a correctness gate). Guarded by
        # _hb_lock; pruned via forget_job.
        self._warm_start_pending: Dict[tuple, Dict[str, str]] = {}

    def close(self) -> None:
        """Release process-lifetime resources (the fan-out thread pool).
        Safe to call repeatedly; the pool is lazily recreated if the
        engine is driven again (OperatorManager supports stop->start
        cycles). In-flight batch submits racing a close see a
        RuntimeError from the shut pool, which rides the normal batch
        error path (rollback + rate-limited requeue)."""
        with self._fanout_pool_lock:
            pool, self._fanout_pool = self._fanout_pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def forget_job(self, key: str) -> None:
        """Drop per-job in-memory bookkeeping after the job is gone
        (called from the controller's deletion/NotFound cleanup)."""
        if self._admission is not None:
            # A deleted job must free its admission (capacity AND quota)
            # immediately: a leaked admitted/Inqueue entry would pin the
            # tenant's quota forever — the PodGroup-leak failure mode,
            # at the admission layer.
            self._admission.release(f"{self.hooks.kind}:{key}")
        with self._gang_declared_lock:
            for cache_key in [k for k in self._gang_declared if k[0] == key]:
                self._gang_declared.pop(cache_key, None)
        with self._hb_lock:
            for cache_key in [k for k in self._hb_obs if k[0] == key]:
                self._hb_obs.pop(cache_key, None)
            for cache_key in [k for k in self._hb_gc_done if k[0] == key]:
                self._hb_gc_done.discard(cache_key)
            for cache_key in [k for k in self._force_deleted if k[0] == key]:
                self._force_deleted.discard(cache_key)
            for cache_key in [k for k in self._warm_start_pending if k[0] == key]:
                self._warm_start_pending.pop(cache_key, None)
        with self._status_lock:
            for cache_key in [k for k in self._status_last_flush if k[0] == key]:
                self._status_last_flush.pop(cache_key, None)
            for cache_key in [k for k in self._status_dirty_since if k[0] == key]:
                self._status_dirty_since.pop(cache_key, None)

    # ------------------------------------------------------------- listing
    def get_pods_for_job(self, job: JobObject) -> List[Pod]:
        """Label-selected pods with full claim semantics (reference
        ControllerRefManager, tfjob_controller.go:249-332); see
        _claim_objects for the protocol."""
        # Selector-match OR owned-by-job: a pod we own whose job-name label
        # was mutated away must still be seen here (or it could never be
        # released), without paying a full operator-scope copy of EVERY
        # job's pods per sync — at 100 jobs x 3 workers that copy was 95%
        # of reconcile latency.
        pods = self.cluster.list_pods(
            namespace=job.namespace,
            labels=job_selector(job),
            owner_uid=job.metadata.uid,
        )
        return self._claim_objects(
            job, pods, self.cluster.get_pod, self.cluster.update_pod
        )

    def get_services_for_job(self, job: JobObject) -> List[Service]:
        """Services are claimed through the identical protocol (the
        reference runs them through the same ControllerRefManager,
        tfjob_controller.go:290-332)."""
        services = self.cluster.list_services(
            namespace=job.namespace,
            labels=job_selector(job),
            owner_uid=job.metadata.uid,
        )
        return self._claim_objects(
            job, services, self.cluster.get_service, self.cluster.update_service
        )

    def _claim_objects(self, job: JobObject, objects, get_live, update) -> list:
        """The ControllerRefManager claim protocol, single-sourced for pods
        and services:

        - owned (controllerRef UID matches) + labels still match -> keep;
        - owned but labels no longer match -> RELEASE: re-read live (the
          list may be cache-served; never patch an object we haven't
          re-read), confirm its UID, then strip our controllerRef;
        - orphan + labels match -> ADOPT, gated on an uncached job GET
          proving the job still exists with the same UID (an operator
          holding a stale cached job must not stamp refs for a deleted/
          recreated one) and on the job not being mid-deletion; the
          recheck's verdict is invariant for the sync, so it runs at most
          once per call (reference canAdoptOnce), not once per orphan;
        - owned by someone else -> ignore.

        Adoption/release write failures are narrowed to NotFound/Conflict
        (the object moved under us — skip this sync, the watch re-enqueues);
        real API errors propagate to the rate-limited queue.

        No-op write dedup: a release whose live re-read shows our ref
        already gone, and an adoption Conflict whose live object already
        carries our controllerRef + labels, skip the UPDATE entirely —
        the desired state is already true and re-writing it is pure
        apiserver write pressure (each skip shows up as one fewer
        update in the accounting counters)."""
        from ..cluster.base import Conflict, NotFound
        from .control import owner_ref_for

        selector = job_selector(job)
        can_adopt: Optional[bool] = None
        out = []
        for obj in objects:
            ref = obj.metadata.controller_ref()
            matches = all(
                obj.metadata.labels.get(k) == v for k, v in selector.items()
            )
            if ref is not None and ref.uid == job.metadata.uid:
                if not matches:
                    self._release_object(job, obj, get_live, update)
                    continue
                out.append(obj)
                continue
            if ref is not None:
                continue  # owned by another controller
            if not matches or job.metadata.deletion_timestamp is not None:
                continue
            if can_adopt is None:
                # get_job_uncached bypasses the informer cache — a cached
                # read would defeat the recheck exactly when it matters (job
                # deleted and recreated before the watch delivers events).
                try:
                    live = self.cluster.get_job_uncached(
                        job.kind, job.namespace, job.name
                    )
                    can_adopt = (
                        (live.get("metadata") or {}).get("uid") == job.metadata.uid
                    )
                except NotFound:
                    can_adopt = False
            if not can_adopt:
                continue
            obj.metadata.owner_references.append(owner_ref_for(job))
            try:
                obj = update(obj)
            except NotFound:
                continue
            except Conflict:
                # The object moved under us. If the LIVE object already
                # carries our controllerRef with matching labels (a prior
                # adoption landed but its response was lost, or another
                # worker won the race to the same verdict), the desired
                # state is already true — keep it without burning another
                # UPDATE on a no-op re-adopt next sync. One extra GET,
                # paid only on the conflict path.
                try:
                    live = get_live(obj.metadata.namespace, obj.metadata.name)
                except NotFound:
                    continue
                live_ref = live.metadata.controller_ref()
                if (
                    live_ref is not None
                    and live_ref.uid == job.metadata.uid
                    and all(
                        live.metadata.labels.get(k) == v
                        for k, v in selector.items()
                    )
                ):
                    out.append(live)
                continue
            out.append(obj)
        return out

    def _release_object(self, job: JobObject, obj, get_live, update) -> None:
        """Remove our controllerRef from an object whose labels stopped
        matching (reference ReleasePods): re-read live first so a
        cache-stale view never drives the patch, confirm the UID."""
        from ..cluster.base import Conflict, NotFound

        try:
            live = get_live(obj.metadata.namespace, obj.metadata.name)
        except NotFound:
            return
        if live.metadata.uid != obj.metadata.uid:
            return
        kept = [
            r for r in live.metadata.owner_references if r.uid != job.metadata.uid
        ]
        if len(kept) == len(live.metadata.owner_references):
            # The live object already carries no ref of ours (the release
            # landed in an earlier sync whose response was lost, or the
            # listing was cache-stale): writing back an unchanged object
            # would be a pure no-op UPDATE — skip it. Each skip is one
            # apiserver write saved, visible in the accounting counters.
            return
        live.metadata.owner_references = kept
        try:
            update(live)
        except (NotFound, Conflict):
            pass  # object changed/vanished concurrently; next sync re-evaluates

    # ----------------------------------------------------------- reconcile
    def reconcile_job(self, job: JobObject) -> None:
        """One sync of one job: the reference's ReconcileJobs
        (SURVEY.md §3.2 call stack)."""
        key = job.key()
        old_status = copy.deepcopy(job.status)
        replicas = job.replica_specs()
        run_policy = job.run_policy()
        # Transient per-sync marker (not serialized): set when a retryable
        # restart is initiated, so status hooks keep the Restarting condition
        # ahead of Running/Failed for this sync. Without it, setting Running
        # for the still-healthy peers drops Restarting (they are mutually
        # exclusive), and the failed>0 check then marks the job Failed —
        # killing a job that was merely recovering from preemption.
        job.status._restarting_this_sync = False
        # Per-replica restart deletes deferred to AFTER the end-of-sync
        # status write (count-before-delete: reconcile_pods counts the
        # restart and stamps the pod handled, but the pod — the only
        # re-detectable evidence — dies only once that count is durable).
        # Transient, like _restarting_this_sync.
        job.status._deferred_deletes = []
        # Slice-granular admission verdict (set by _admission_gate_sliced):
        # None = every slice may create pods; a set limits reconcile_pods'
        # missing-slot creation to admitted slices — a queued slice's pods
        # stay unborn while its siblings run. Transient per sync.
        job.status._admitted_slices = None

        pods = self.get_pods_for_job(job)

        # Stuck-terminating escalation on the hot path reuses this claimed
        # pod list (zero extra LIST per sync); the expectations-gated path
        # runs it pre-gate in controllers/base.py with its own list. No-op
        # unless runPolicy.forceDeleteAfterSeconds is set.
        self.escalate_stuck_terminating(job, pods=pods)

        # Seed Created condition (reference sets it in onOwnerCreateFunc,
        # tfjob_controller.go:839-856; converging here keeps any path safe).
        if not job.status.conditions:
            capi.update_job_conditions(
                job.status,
                capi.JOB_CREATED,
                constants.job_reason(self.hooks.kind, constants.REASON_CREATED),
                f"{self.hooks.kind} {job.name} is created.",
                now=self.clock(),
            )

        if capi.is_finished(job.status):
            self._handle_terminal_job(job, pods, replicas, run_policy)
            self._write_status_if_changed(job, old_status)
            return

        # Suspension (RunPolicy.suspend): tear everything down WITHOUT
        # failing the job — on TPU the whole pod-slice goes back to the
        # scheduler. Resume resets startTime (fresh ActiveDeadline window,
        # training-operator semantics).
        if run_policy.suspend:
            self._suspend_job(job, pods, replicas, run_policy)
            self._write_status_if_changed(job, old_status)
            return
        suspended = capi.get_condition(job.status, capi.JOB_SUSPENDED)
        if suspended is not None and suspended.status == capi.CONDITION_TRUE:
            # Resuming: clear the suspension and start a fresh lifecycle
            # window before the normal pod reconcile below recreates.
            now = self.clock()
            suspended.status = capi.CONDITION_FALSE
            suspended.last_transition_time = now
            suspended.last_update_time = now
            job.status.start_time = None
            # Fresh lifecycle window = fresh restart budget too: kubelet
            # counters reset with the recreated pods (reference behavior),
            # so the durable ExitCode counter must reset alongside or
            # pre-suspension restarts would eat the resumed job's
            # backoffLimit. The disruption ledger and backoff window reset
            # with it — suspension released the slice, so the preemption
            # streak the old incarnation accumulated is history.
            job.status.restart_counts = {}
            job.status.disruption_counts = {}
            job.status.stall_counts = {}
            job.status.slice_restart_counts = {}
            job.status.disruption_streak = 0
            job.status.restart_backoff_until = None
            capi.update_job_conditions(
                job.status,
                capi.JOB_CREATED,
                constants.job_reason(self.hooks.kind, constants.REASON_RESUMED),
                f"{self.hooks.kind} {job.name} is resumed.",
                now=self.clock(),
            )
            record_event_best_effort(
                self.cluster,
                Event(
                    type="Normal",
                    reason=constants.job_reason(self.hooks.kind, constants.REASON_RESUMED),
                    message=f"{self.hooks.kind} {job.name} is resumed.",
                    involved_object=f"{job.kind}/{key}",
                )
            )

        # Run-policy enforcement before any pod work (library ReconcileJobs).
        failure_reason = None
        failure_message = ""
        if self._past_active_deadline(job, run_policy):
            failure_reason = constants.REASON_JOB_DEADLINE_EXCEEDED
            failure_message = f"{self.hooks.kind} {job.name} has failed because it was active longer than specified deadline"
        elif self._past_backoff_limit(job, run_policy, replicas, pods):
            failure_reason = constants.REASON_JOB_BACKOFF_EXCEEDED
            failure_message = f"{self.hooks.kind} {job.name} has failed because it has reached the specified backoff limit"
        elif self._past_disruption_limit(job, run_policy):
            failure_reason = constants.REASON_JOB_DISRUPTION_EXCEEDED
            failure_message = (
                f"{self.hooks.kind} {job.name} has failed because it was "
                "disrupted (preempted/evicted) more times than "
                "maxDisruptionRetries allows"
            )

        if failure_reason is not None:
            # Honor CleanPodPolicy even on the failure path (the reference's
            # deletePodsAndServices is the single cleanup for both): policy
            # None preserves pods for debugging.
            self._delete_pods_and_services(job, pods, run_policy)
            if job.status.completion_time is None:
                job.status.completion_time = self.clock()
            capi.update_job_conditions(
                job.status, capi.JOB_FAILED, failure_reason, failure_message, now=self.clock()
            )
            record_event_best_effort(
                self.cluster,
                Event(
                    type="Normal",
                    reason=failure_reason,
                    message=failure_message,
                    involved_object=f"{job.kind}/{key}",
                )
            )
            self._write_status_if_changed(job, old_status)
            return

        if self.options.enable_gang_scheduling:
            self._sync_pod_group(job, replicas, run_policy)

        # Capacity-aware gang admission (core/admission.py): with the
        # arbiter present, a job proceeds to pod work only once its gang
        # is admitted — queued jobs end the sync here with the JOB_QUEUED
        # condition and ZERO pods (no partial gang can ever exist), and a
        # preemption verdict runs the counted disruption teardown before
        # releasing the gang's capacity. None (the default) is one check.
        if self._admission is not None and not self._admission_gate(
            job, replicas, run_policy, pods, old_status
        ):
            return

        # Elastic resize: a membership change (slice added/removed, worker
        # scale) invalidates every live pod's injected world. Delete ALL
        # stale pods in this one sync — a gang restarts together, and batched
        # deletion keeps restart MTTR one informer round-trip instead of one
        # per pod — then recreate on the next sync once deletions land.
        #
        # Stamp-BEFORE-delete (crash consistency): the handled-uid stamp
        # marks these deletions as controller-initiated so the gang trigger
        # below never re-reads them as external node drains. A crash
        # between the deletes landing and the stamp landing would leave
        # Terminating in-range pods beside live peers — the drained-pod
        # trigger's exact signature — and charge the resize to the
        # disruption ledger. So the stamp + condition are made durable
        # FIRST; only then do pods die. A failed/crashed write deletes
        # nothing (the stale pods re-detect identically); a crash after
        # the write resumes the deletes without re-eventing. The stamp is
        # MERGED with still-present previously-handled uids (a resize
        # mid-grace-period must not un-handle a counted trigger) and
        # pruned to present pods so it stays gang-sized.
        stale = self.hooks.stale_world_pods(job, replicas, pods)
        if stale:
            if self.options.warm_start and self.options.peer_restore:
                # A resize whose declared world is LARGER than the live
                # pod set is a grow: survivors keep serving their host
                # snapshots through the teardown, so the recreated/new
                # ranks can warm-start from peers with zero storage reads.
                # Flag the (job, world) before any pod dies — _build_pod
                # injects TPU_WARM_START=1 while the flag is pending and
                # the liveness sweep clears it once the grown world is
                # fully present. A shrink (or same-size reshape) never
                # sets it: fewer survivors is the ordinary restore path.
                declared = sum(spec.replicas or 0 for spec in replicas.values())
                if declared > len(pods):
                    # Snapshot the survivors' addresses NOW, before any
                    # pod dies: the teardown drains the live observation
                    # cache, but the replaced ranks' shard servers keep
                    # serving through termination grace, so these are
                    # exactly the peers the grown world can pull from.
                    # setdefault — a re-detected grow mid-teardown must
                    # not overwrite the snapshot with the (emptier) view.
                    now = self.clock()
                    pdl = run_policy.progress_deadline_seconds
                    with self._hb_lock:
                        obs = self._hb_obs.get(
                            (job.key(), job.metadata.uid)) or {}
                        snapshot = {
                            s.pod_name: s.peer_addr for s in obs.values()
                            if s.peer_addr and s.pod_name
                            and not (pdl is not None and s.seen
                                     and now - s.observed_at >= pdl)
                        }
                        self._warm_start_pending.setdefault(
                            (job.key(), job.metadata.uid), snapshot)
            present = {p.metadata.uid for p in pods}
            already = set(job.status.gang_handled_uids or ())
            fresh = any(p.metadata.uid not in already for p in stale)
            job.status.gang_handled_uids = sorted(
                (already & present) | {p.metadata.uid for p in stale}
            )
            msg = (
                f"{self.hooks.kind} {job.name} is restarting to apply a new "
                f"replica topology ({len(stale)} stale pod(s))."
            )
            capi.update_job_conditions(
                job.status,
                capi.JOB_RESTARTING,
                constants.job_reason(self.hooks.kind, constants.REASON_RESTARTING),
                msg,
                now=self.clock(),
            )
            job.status._restarting_this_sync = True
            try:
                self._write_status_if_changed(job, old_status)
            except Exception:  # noqa: BLE001 — conflict/transient write error
                self.requeue(f"{job.kind}:{key}", 1.0)
                return
            if fresh:
                record_event_best_effort(
                    self.cluster,
                    Event(
                        type="Normal",
                        reason=constants.job_reason(self.hooks.kind, constants.REASON_RESTARTING),
                        message=msg,
                        involved_object=f"{job.kind}/{key}",
                    )
                )
                self.on_job_restarting(job, "", capi.RESTART_CAUSE_SPEC_CHANGE)
            for pod in stale:
                if pod.metadata.deletion_timestamp is None:
                    self._delete_pod(job, pod)
            return

        # Gang restart on retryable failure (SPMD worlds, restart_peers_on_
        # failure hook): one lost process takes the whole gang down in a
        # single batched sync — survivors included — so every process
        # re-runs the rendezvous and resumes from the shared checkpoint.
        gang_failure = self._find_gang_retryable_failure(
            replicas, pods, handled_uids=set(job.status.gang_handled_uids or ())
        )
        if gang_failure is not None:
            rtype, failed_pod, cause = gang_failure
            # Recreate-ALL (JobSet semantics), Succeeded pods included: the
            # restarted world initializes with the full declared membership,
            # and a kept Succeeded coordinator (worker-0 exited 0 while a
            # peer was preempted) would leave the new gang waiting on a
            # process that will never rejoin. The re-run resumes from the
            # shared checkpoint and exits cleanly again. Only WORLD MEMBERS
            # (types that opted into restart_peers_on_failure) go down with
            # the gang: out-of-world sidecars (JAXJob Evaluator) are not in
            # the SPMD rendezvous and restart individually.
            #
            # ONE restart per gang restart: backoffLimit counts world
            # restarts, not the gang-size multiple of them — every world
            # pod present is stamped handled (all are being replaced), so
            # N pods evicted together in one maintenance event count one
            # restart, not N. Count/stamp/teardown ordering — including
            # every crash window between them — lives in
            # _restart_gang_counted (count-before-teardown protocol).
            world_types = {
                rt.lower() for rt in replicas
                if self.hooks.restart_peers_on_failure(rt)
            }
            targets = [
                p for p in pods
                if p.metadata.labels.get(constants.LABEL_REPLICA_TYPE)
                in world_types
            ]
            disrupted = cause == capi.RESTART_CAUSE_DISRUPTION
            detail = (
                "was disrupted (preempted/evicted/drained)" if disrupted
                else "failed retryably"
            )
            # Slice-scoped restart domains: for a multislice job the
            # failure is first attributed to its slice, and the counted
            # teardown runs against that slice's pods ONLY — surviving
            # slices are never deleted, and the recreated slice
            # re-rendezvouses through the stable worker-0 coordinator
            # service. Losing the coordinator slice, or dropping below
            # the spec.minSlices quorum within the restart window,
            # escalates to the whole world through the same counted
            # protocol (one ledger entry, reason SliceQuorumLost).
            topo, scope, slice_idx, why = self._slice_restart_scope(
                job, replicas, pods, failed_pod, world_types
            )
            if scope == "slice":
                targets = [
                    p for p in targets
                    if self._pod_slice_index(job, topo, replicas, p)
                    == slice_idx
                ]
                reason = constants.job_reason(
                    self.hooks.kind,
                    constants.REASON_SLICE_DISRUPTION_RESTARTING if disrupted
                    else constants.REASON_SLICE_RESTARTING,
                )
                msg = (
                    f"{self.hooks.kind} {job.name} is restarting slice "
                    f"{slice_idx}: {rtype} replica "
                    f"{failed_pod.metadata.name} {detail}; the slice "
                    "restarts as one unit while the other "
                    f"{topo.num_slices - 1} slice(s) keep running."
                )
            elif why is not None:
                reason = constants.job_reason(
                    self.hooks.kind, constants.REASON_SLICE_QUORUM_LOST
                )
                msg = (
                    f"{self.hooks.kind} {job.name} is restarting the whole "
                    f"world: {rtype} replica {failed_pod.metadata.name} "
                    f"{detail} in slice {slice_idx} and {why}."
                )
            else:
                reason = constants.job_reason(
                    self.hooks.kind,
                    constants.REASON_DISRUPTION_RESTARTING if disrupted
                    else constants.REASON_RESTARTING,
                )
                msg = (
                    f"{self.hooks.kind} {job.name} is restarting the whole gang: "
                    f"{rtype} replica {failed_pod.metadata.name} {detail} "
                    "and the SPMD world restarts as one unit."
                )
            self._restart_gang_counted(
                job, pods, targets, failed_pod, rtype, cause, reason, msg,
                old_status, scope=scope, slice_index=slice_idx, topo=topo,
                escalated=why is not None,
            )
            return

        # Disruption restart backoff: after consecutive disruptions the job
        # waits out a jittered exponential window before recreating pods —
        # a reclaim loop must not hammer the scheduler with gang-sized pod
        # churn every sync. The job stays in Restarting (not Failed) for
        # the whole window; a requeue lands exactly when it closes.
        backoff_until = job.status.restart_backoff_until
        if backoff_until is not None:
            now = self.clock()
            if now < backoff_until:
                job.status._restarting_this_sync = True
                self.requeue(f"{job.kind}:{key}", backoff_until - now)
                self._write_status_if_changed(job, old_status)
                return
            job.status.restart_backoff_until = None

        # Gang liveness (opt-in, runPolicy.progressDeadlineSeconds): a
        # replica whose heartbeat renewals went stale — or that never
        # produced a first heartbeat within rendezvousDeadlineSeconds of
        # gang-up — is wedged behind a Running phase the kubelet will
        # never change. Drive the same gang-restart machine the failure
        # paths use, with its own cause + ledger.
        if run_policy.progress_deadline_seconds is None:
            stall = None
        else:
            # Traced only for opted-in jobs (a span per sync of every job
            # would be noise): the lease reads inside are attributed by
            # accounting; the verdict rides as an attr.
            with self.tracer.span("liveness.check") as live_span:
                stall = self._check_liveness(job, replicas, run_policy, pods)
                live_span.set(stalled=stall is not None)
        if stall is not None:
            # The stall branch owns its status writes: the count must be
            # DURABLE before any pod dies (see _restart_stalled_gang).
            self._restart_stalled_gang(job, replicas, pods, stall, old_status)
            return

        services = self.get_services_for_job(job)
        for rtype in self.hooks.replica_order(replicas):
            spec = replicas[rtype]
            self.reconcile_pods(job, job.status, pods, rtype, spec, replicas)
            self.reconcile_services(job, services, rtype, spec)

        self.hooks.update_job_status(job, replicas, job.status, pods)

        # The job came (back) up: close the disruption streak so the next
        # preemption restarts immediately instead of inheriting this
        # incident's backoff position (client-go crash-loop reset analog).
        if job.status.disruption_streak and capi.is_running(job.status):
            job.status.disruption_streak = 0

        # ActiveDeadline resync scheduling (reference :373-383).
        if (
            job.status.start_time is not None
            and run_policy.active_deadline_seconds is not None
        ):
            elapsed = self.clock() - job.status.start_time
            remaining = run_policy.active_deadline_seconds - elapsed
            if remaining > 0:
                self.requeue(f"{job.kind}:{key}", remaining)

        # Order is the per-replica crash-consistency protocol: the status
        # write makes the restart counts durable; only then do the counted
        # pods die. A write failure propagates (rate-limited retry) with
        # nothing deleted.
        self._write_status_if_changed(job, old_status)
        self._flush_deferred_deletes(job)

    def _flush_deferred_deletes(self, job: JobObject) -> None:
        """Phase 2 of the per-replica restart protocol (reconcile_pods):
        execute the deletes whose counts the status write just made
        durable, firing each fresh restart's event + metric now that the
        ledger the observer would check agrees. A delete failure requeues;
        the handled-uid stamp skips the re-count on retry. A crash
        anywhere in here leaves a counted, stamped, still-Failed pod the
        next controller incarnation finishes off without re-charging."""
        items = getattr(job.status, "_deferred_deletes", None) or []
        errors = False
        for item in items:
            pod = item["pod"]
            if item.get("fresh"):
                record_event_best_effort(
                    self.cluster,
                    Event(
                        type="Warning",
                        reason=item["reason"],
                        message=item["msg"],
                        involved_object=f"{job.kind}/{job.key()}",
                    ),
                )
                self.on_job_restarting(job, item["rtype"], item["cause"])
            try:
                self._delete_pod(job, pod)
            except Exception:  # noqa: BLE001 — keep deleting the rest
                log.warning(
                    "deferred restart delete of %s/%s failed; retrying",
                    pod.metadata.namespace, pod.metadata.name, exc_info=True,
                )
                errors = True
        job.status._deferred_deletes = []
        if errors:
            self.requeue(f"{job.kind}:{job.key()}", 1.0)

    def _find_gang_retryable_failure(
        self, replicas: Dict[str, ReplicaSpec], pods: List[Pod],
        handled_uids: frozenset = frozenset(),
    ) -> Optional[Tuple[str, Pod, str]]:
        """The gang restart-cause machine: (rtype, pod, cause) of the first
        replica whose loss requires a whole-gang restart — cause is a
        RESTART_CAUSE_* constant deciding which budget the restart draws
        from — else None. Triggers, in precedence order:

        1. A retryably-failed pod NOT yet terminating (fresh failure).
           Cause: classify_pod_failure — explicit disruption markers
           (DisruptionTarget condition, Preempted/Evicted reason) or a
           SIGKILL-class exit on an otherwise-healthy gang are
           InfrastructureDisruption; other retryable exits are
           ApplicationFailure, exactly as before. Non-retryable failures
           fall through to the normal status machine. A fresh failure
           whose uid is ALREADY in status.gang_handled_uids is a crash
           leftover (the count-before-teardown write landed, the process
           died before any delete) — still a trigger, so the teardown
           resumes, but _restart_gang_counted sees the stamp and never
           re-counts it.
        2. A retryably-failed pod already Terminating, returned ONLY while
           some world member is still live AND its teardown was not already
           counted (status.gang_handled_uids). The controller's own
           teardown deletes the trigger LAST, so "terminating trigger +
           live peers" normally means the deletion was external (eviction,
           node drain, kubectl delete) — but once that teardown is
           counted, the trigger can linger Terminating through its grace
           period beside the recreated world, and re-reading it as fresh
           would tear the new gang down every sync. Once every world pod
           is terminating, the restart is in flight — re-firing would
           re-burn a budget on one failure.
        3. A RUNNING/PENDING in-range world pod externally deleted
           (deletion_timestamp set before it ever failed): node drain. The
           controller's own paths that delete live world pods (gang
           teardown, stale-world resize, suspension, scale-down) either
           stamp handled_uids, take all world pods down together (no live
           peer remains), or delete only out-of-range indices — so a live
           in-range Terminating pod beside live peers can only be an
           external actor, and leaving the survivors up would hand the
           SPMD world a lone replacement it cannot re-admit. Always an
           InfrastructureDisruption.
        """
        terminating_candidate: Optional[Tuple[str, Pod, str]] = None
        drained_candidate: Optional[Tuple[str, Pod, str]] = None
        handled_candidate: Optional[Tuple[str, Pod, str]] = None
        world_types_lower = set()
        # "Otherwise-healthy gang": no world pod failed with a PERMANENT
        # exit code — a lone SIGKILL under healthy peers reads as
        # preemption; the same code amid application crashes does not.
        peers_healthy = True
        for rtype, spec in replicas.items():
            if not self.hooks.restart_peers_on_failure(rtype):
                continue
            for pod in filter_pods_for_replica_type(pods, rtype):
                if pod.status.phase != POD_FAILED:
                    continue
                code = get_container_exit_code(pod, self.hooks.default_container_name)
                if code != constants.EXIT_CODE_UNSET and not capi.is_retryable_exit_code(code):
                    peers_healthy = False
        for rtype, spec in replicas.items():
            if spec.restart_policy != capi.RESTART_POLICY_EXIT_CODE:
                continue
            if not self.hooks.restart_peers_on_failure(rtype):
                continue
            world_types_lower.add(rtype.lower())
            num_replicas = spec.replicas or 0
            for pod in filter_pods_for_replica_type(pods, rtype):
                if pod.status.phase != POD_FAILED:
                    if (
                        drained_candidate is None
                        and pod.status.phase in (POD_RUNNING, POD_PENDING)
                        and pod.metadata.deletion_timestamp is not None
                        and pod.metadata.uid not in handled_uids
                        and self._replica_index(pod) < num_replicas
                    ):
                        drained_candidate = (
                            rtype, pod, capi.RESTART_CAUSE_DISRUPTION
                        )
                    continue
                exit_code = get_container_exit_code(
                    pod, self.hooks.default_container_name
                )
                if not capi.is_retryable_exit_code(exit_code):
                    continue
                cause = capi.classify_pod_failure(
                    pod, exit_code, peers_healthy=peers_healthy
                )
                if pod.metadata.deletion_timestamp is None:
                    if pod.metadata.uid not in handled_uids:
                        return rtype, pod, cause
                    # Counted but never deleted (crash between the phase-1
                    # status write and the teardown): resume, don't refire.
                    if handled_candidate is None:
                        handled_candidate = (rtype, pod, cause)
                elif (
                    terminating_candidate is None
                    and pod.metadata.uid not in handled_uids
                ):
                    terminating_candidate = (rtype, pod, cause)
        candidate = handled_candidate or terminating_candidate or drained_candidate
        if candidate is not None and any(
            p.metadata.deletion_timestamp is None
            and p.metadata.labels.get(constants.LABEL_REPLICA_TYPE)
            in world_types_lower
            for p in pods
        ):
            return candidate
        return None

    def _teardown_gang_pods(
        self, job: JobObject, targets: List[Pod], trigger: Pod
    ) -> List[tuple]:
        """The shared gang-teardown ordering rule, single-sourced for the
        failure and stall restart paths: survivors first, the TRIGGER pod
        last and only once every survivor delete succeeded — a partial
        teardown therefore always leaves the re-detectable trigger intact
        for the retry sync. Pods already Terminating are skipped so a
        retried teardown never double-deletes. Survivor deletions fan out
        through slow_start_batch (gang teardown is half of restart MTTR),
        but unlike the CREATE batches a failed delete does NOT abort the
        wave's successors: errors are recorded per pod and the rest keep
        going — one survivor whose delete persistently fails (webhook
        denial, a wedged node) must not block the pods behind it from
        ever being deleted, or the gang restart could stall forever on
        zero progress per retry. Returns (name, exc) pairs for deletes
        that failed; the caller decides how to surface them."""
        victims = [
            pod for pod in targets
            if pod is not trigger and pod.metadata.deletion_timestamp is None
        ]
        delete_errors: List[tuple] = []
        # Event aggregation (write coalescing): one SuccessfulDeletePod
        # event for the whole teardown instead of one per member — the
        # Restarting Warning the caller records already names the
        # incident; gang-size delete-event writes are pure pressure.
        quiet = self._coalescing and len(targets) > 1

        def delete_one(i: int) -> None:
            try:
                self._delete_pod(job, victims[i], quiet=quiet)
            except Exception as exc:  # noqa: BLE001 — recorded, not aborting
                delete_errors.append((victims[i].metadata.name, exc))

        self._batch_write("pods", len(victims), delete_one)
        # list.append is atomic under the GIL, so the error count is safe
        # to read after the batch even though delete_one ran on pool
        # threads; the deleted tally derives from it.
        deleted = len(victims) - len(delete_errors)
        if not delete_errors and trigger.metadata.deletion_timestamp is None:
            try:
                self._delete_pod(job, trigger, quiet=quiet)
                deleted += 1
            except Exception as exc:  # noqa: BLE001
                delete_errors.append((trigger.metadata.name, exc))
        if quiet and deleted:
            self._record_batch_event(
                job, constants.REASON_SUCCESSFUL_DELETE_POD,
                f"Deleted {deleted} pod(s) (gang teardown, "
                f"trigger {trigger.metadata.name})",
            )
        return delete_errors

    @staticmethod
    def _replica_index(pod: Pod) -> int:
        try:
            return int(pod.metadata.labels.get(constants.LABEL_REPLICA_INDEX, ""))
        except ValueError:
            return -1

    # --------------------------------------------- slice restart domains
    def _pod_slice_index(
        self, job: JobObject, topo: SliceTopology,
        replicas: Dict[str, ReplicaSpec], pod: Pod,
    ) -> Optional[int]:
        """Slice domain of one pod (labels -> hooks.replica_slice_index),
        or None for pods without parseable replica identity."""
        rt = pod.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "")
        rtype = next((r for r in replicas if r.lower() == rt), None)
        index = self._replica_index(pod)
        if rtype is None or index < 0:
            return None
        return self.hooks.replica_slice_index(job, topo, replicas, rtype, index)

    def _impaired_slices(
        self, job: JobObject, topo: SliceTopology,
        replicas: Dict[str, ReplicaSpec], pods: List[Pod],
        world_types: set,
    ) -> set:
        """Slices that cannot currently field their full world membership:
        an in-range world pod Failed or Terminating, or fewer live world
        pods than hosts_per_slice (mid-teardown, awaiting recreation).
        This is the quorum rule's 'within the restart window' predicate —
        a slice whose counted teardown ran stays impaired until its
        recreated pods exist again."""
        live: Dict[int, int] = {}
        impaired: set = set()
        for rtype, spec in replicas.items():
            if rtype.lower() not in world_types:
                continue
            num_replicas = spec.replicas or 0
            for pod in filter_pods_for_replica_type(pods, rtype):
                index = self._replica_index(pod)
                if index < 0 or index >= num_replicas:
                    continue
                s = self.hooks.replica_slice_index(
                    job, topo, replicas, rtype, index
                )
                if (
                    pod.status.phase == POD_FAILED
                    or pod.metadata.deletion_timestamp is not None
                ):
                    impaired.add(s)
                else:
                    live[s] = live.get(s, 0) + 1
        for s in range(topo.num_slices):
            if live.get(s, 0) < topo.hosts_per_slice:
                impaired.add(s)
        return impaired

    def _slice_restart_scope(
        self, job: JobObject, replicas: Dict[str, ReplicaSpec],
        pods: List[Pod], trigger: Pod, world_types: set,
    ) -> Tuple[Optional[SliceTopology], str, Optional[int], Optional[str]]:
        """Restart-domain verdict for one gang-restart trigger:
        (topo, scope, slice index, escalation detail). scope is "world"
        for flat jobs (topo None / single slice) and for escalations —
        losing the coordinator slice, or the healthy-slice count dropping
        below spec.minSlices within the restart window — where the
        detail string says why; "slice" confines the counted teardown to
        the trigger's slice. Deterministic: a pure function of (spec,
        pod states), so a crash-resume sync recomputes the identical
        verdict from the re-detected trigger."""
        topo = self.hooks.slice_topology(job, replicas)
        if topo is None or topo.num_slices <= 1 or not world_types:
            return None, "world", None, None
        slice_idx = self._pod_slice_index(job, topo, replicas, trigger)
        if slice_idx is None:
            # Unattributable trigger (unparseable replica identity): the
            # safe scope is the whole world, but it is a PLAIN world
            # restart — labeling it SliceQuorumLost would page for a
            # coordinator/quorum loss that never happened.
            return topo, "world", None, None
        if slice_idx == topo.coordinator_slice:
            return topo, "world", slice_idx, (
                f"slice {slice_idx} hosts the worker-0 coordinator every "
                "other slice re-rendezvouses through"
            )
        if topo.min_slices is not None:
            impaired = self._impaired_slices(
                job, topo, replicas, pods, world_types
            )
            impaired.add(slice_idx)
            healthy = topo.num_slices - len(impaired)
            if healthy < topo.min_slices:
                return topo, "world", slice_idx, (
                    f"only {healthy} of {topo.num_slices} slices healthy, "
                    f"below the minSlices quorum ({topo.min_slices})"
                )
        return topo, "slice", slice_idx, None

    # -------------------------------------------------------- gang liveness
    def _gc_heartbeat_lease(self, job: JobObject, pod_name: str) -> None:
        """Best-effort delete of one pod's heartbeat lease (elastic-shrink
        pruning; the terminal path has its own batched GC). NotFound is
        the common case on repeat syncs; any other failure just leaves
        the lease to terminal GC — pruning is hygiene, never a verdict."""
        from ..cluster.base import NotFound

        try:
            self.cluster.delete_lease(
                job.namespace, constants.heartbeat_lease_name(pod_name)
            )
        except NotFound:
            pass
        except Exception:  # noqa: BLE001 — hygiene must not fail the sync
            log.debug("heartbeat lease GC failed for %s/%s", job.namespace,
                      pod_name, exc_info=True)

    def _peer_restore_addrs(
        self, job: JobObject, exclude_pod: str = "",
        progress_deadline_seconds: Optional[float] = None,
    ) -> List[str]:
        """Survivor shard-server addresses for one job, from the liveness
        observation cache (peer-address lease riders seen on live ranks).
        Sorted for deterministic env rendering; the pod being built is
        excluded — a restarted rank must not be told to restore from its
        own predecessor's dead server.

        With ``progress_deadline_seconds``, addresses whose rank's
        heartbeat lease has gone stale (an observed renewal, then nothing
        for a full deadline — the same local-clock rule the stall
        detector enforces) are filtered out: each dead address would burn
        a full retry-budget rung of the restoring rank's ladder before it
        moved on. Baselined-but-unseen ranks stay included — a rank that
        has not renewed YET (mid-rendezvous) is not evidence of death."""
        now = self.clock()
        with self._hb_lock:
            obs = self._hb_obs.get((job.key(), job.metadata.uid)) or {}
            pdl = progress_deadline_seconds
            return sorted({
                state.peer_addr for state in obs.values()
                if state.peer_addr and state.pod_name != exclude_pod
                and not (pdl is not None and state.seen
                         and now - state.observed_at >= pdl)
            })

    def _check_liveness(
        self, job: JobObject, replicas: Dict[str, ReplicaSpec], run_policy,
        pods: List[Pod],
    ) -> Optional[Tuple[str, Pod, str]]:
        """The stall detector: (rtype, pod, message) of the first replica
        past its liveness deadline, else None. Entirely opt-in — without
        progressDeadlineSeconds this is one None-check per sync and a job
        that never heartbeats can never stall-restart.

        Two deadlines, both measured on the LOCAL clock from observation
        events (the leaderelection skew rule):

        - progress: once a pod's FIRST renewal has been observed, the time
          since the last observed renewal-change may not exceed the
          deadline. A heartbeat-less job under a progress deadline alone
          is therefore never flagged (`seen` never latches).
        - rendezvous: the time from first observing the pod Running to its
          first observed heartbeat may not exceed the deadline — the bound
          that catches a gang frozen in rendezvous forever.

        Side effects: reports the worst observed staleness through
        on_heartbeat_age, and schedules an AddAfter resync for the
        earliest upcoming deadline (a heartbeat that STOPS generates no
        watch event — exactly like the ActiveDeadline resync, the check
        must wake itself).

        Cost note: one uncached get_lease per in-range Running pod per
        sync of an opted-in job. Accepted for now — the observation MUST
        be frequent for the skew rule to time renewals accurately; a
        lease informer (watch "leases" like every other resource) is the
        future path if large opted-in gangs make this the dominant sync
        cost."""
        from ..cluster.base import NotFound

        pdl = run_policy.progress_deadline_seconds
        if pdl is None:
            return None
        rdl = run_policy.rendezvous_deadline_seconds
        now = self.clock()
        cache_key = (job.key(), job.metadata.uid)
        stalled: Optional[Tuple[str, Pod, str]] = None
        worst_age = 0.0
        best_tps: Optional[float] = None
        min_ckpt: Optional[int] = None
        next_check: Optional[float] = None

        def sooner(remaining: float) -> None:
            nonlocal next_check
            next_check = remaining if next_check is None else min(next_check, remaining)

        # Lock scope: only the map of per-job dicts. The per-job dict and
        # its states are touched exclusively by this job's syncs, which
        # the workqueue serializes — so the lease reads (blocking I/O on
        # a real apiserver) run unlocked. A concurrent forget_job at worst
        # orphans the dict we hold; its updates die with the deleted job.
        with self._hb_lock:
            obs = self._hb_obs.setdefault(cache_key, {})
        present = set()
        for rtype, spec in replicas.items():
            num_replicas = spec.replicas or 0
            for pod in filter_pods_for_replica_type(pods, rtype):
                if pod.status.phase != POD_RUNNING:
                    continue
                if pod.metadata.deletion_timestamp is not None:
                    continue  # already being replaced; not ours to judge
                if self._replica_index(pod) >= num_replicas:
                    # Out-of-range (elastic shrink / scale-down): the pod
                    # is on its way out. Drop its observation AND its
                    # heartbeat lease now — the lease is keyed by pod
                    # NAME, so left alone its last tokens-per-sec
                    # annotation would linger until terminal lease GC and
                    # keep a shrunk-away rank's throughput aggregatable
                    # (and inheritable by a later regrow at this index).
                    obs.pop(pod.metadata.uid, None)
                    self._gc_heartbeat_lease(job, pod.metadata.name)
                    continue
                present.add(pod.metadata.uid)
                state = obs.get(pod.metadata.uid)
                if state is None:
                    state = obs[pod.metadata.uid] = _HeartbeatState(
                        running_since=now,
                        pod_name=pod.metadata.name,
                        rtype=rtype.lower(),
                        index=self._replica_index(pod),
                    )
                lease_name = constants.heartbeat_lease_name(
                    pod.metadata.name
                )
                try:
                    lease = self.cluster.get_lease(job.namespace, lease_name)
                except NotFound:
                    lease = None
                except Exception:
                    # Transient read failure: a liveness verdict may
                    # never ride on an apiserver blip — skip this pod's
                    # verdict this round, but SCHEDULE the re-read: the
                    # wake chain is self-sustaining, and a blip landing
                    # on a scheduled wake would otherwise cancel it
                    # permanently (no watch event ever re-arms it). The
                    # log is the only signal that distinguishes a
                    # PERSISTENT failure here (e.g. missing lease RBAC =
                    # stall protection silently off) from a healthy job.
                    log.warning(
                        "liveness lease read failed for %s/%s (stall "
                        "detection degraded until it succeeds)",
                        job.namespace, lease_name, exc_info=True,
                    )
                    sooner(min(pdl, 5.0))
                    continue
                raw = None
                if lease is not None:
                    lease_spec = lease.get("spec") or {}
                    raw = (
                        f"{lease_spec.get('holderIdentity')}"
                        f"@{lease_spec.get('renewTime')}"
                    )
                    # Workload-reported throughput rides the lease
                    # annotations (record_progress(tokens_per_sec=)). The
                    # job gauge is the MAX over replicas' latest reports: a
                    # workload reporting GLOBAL throughput (llama_train)
                    # yields the job number directly, per-replica
                    # reporters yield the fastest replica's view. Pure
                    # telemetry — no liveness verdict ever rides on it.
                    annotations = ((lease.get("metadata") or {})
                                   .get("annotations") or {})
                    tps_raw = annotations.get(constants.ANNOTATION_HEARTBEAT_TPS)
                    if tps_raw is not None:
                        try:
                            tps = float(tps_raw)
                        except (TypeError, ValueError):
                            tps = None
                        if tps is not None and tps >= 0:
                            best_tps = max(best_tps or 0.0, tps)
                    # Durable-checkpoint rider: the gang-wide value is the
                    # MIN over reporting replicas (the step EVERY rank has
                    # committed — the autoscaler's shrink-gate aggregation).
                    # A non-reporting replica simply doesn't vote; pure
                    # telemetry, no liveness verdict rides on it.
                    ckpt_raw = annotations.get(constants.ANNOTATION_HEARTBEAT_CKPT)
                    if ckpt_raw is not None:
                        try:
                            ckpt = int(float(ckpt_raw))
                        except (TypeError, ValueError):
                            ckpt = None
                        if ckpt is not None:
                            min_ckpt = ckpt if min_ckpt is None else min(min_ckpt, ckpt)
                    # Peer-restore riders, only consumed when the engine
                    # opted in (capability gating: with the flag off the
                    # annotations are ignored and nothing downstream
                    # changes).
                    if self.options.peer_restore:
                        addr = annotations.get(constants.ANNOTATION_HEARTBEAT_PEER)
                        if addr:
                            state.peer_addr = addr
                        restore_raw = annotations.get(
                            constants.ANNOTATION_HEARTBEAT_RESTORE
                        )
                        if restore_raw and restore_raw != state.restore_raw:
                            state.restore_raw = restore_raw
                            # path:cause:seconds with an optional 4th
                            # bytes field (older workloads publish 3
                            # fields; both parse — mixed-version fleets).
                            parts = restore_raw.split(":")
                            if len(parts) in (3, 4):
                                try:
                                    seconds = float(parts[2])
                                except (TypeError, ValueError):
                                    seconds = 0.0
                                bytes_moved = None
                                if len(parts) == 4:
                                    try:
                                        bytes_moved = int(parts[3])
                                    except (TypeError, ValueError):
                                        bytes_moved = None
                                self.on_restore_observed(
                                    job, parts[0], parts[1], seconds,
                                    bytes_moved,
                                )
                if not state.baselined:
                    # First read for this pod incarnation: record the
                    # lease content as a BASELINE without crediting it
                    # as a heartbeat. A recreated pod inherits its
                    # predecessor's (frozen) lease — counting that as
                    # "first heartbeat seen" would start the staleness
                    # clock at a renewal this process never made and
                    # stall-loop every restart before rendezvous.
                    # Liveness is proven only by a renewal observed to
                    # HAPPEN: a change from the baseline.
                    state.baselined = True
                    state.raw = raw
                elif raw is not None and raw != state.raw:
                    # Renewal observed: restart the staleness clock at
                    # the moment WE saw it change.
                    state.raw = raw
                    state.observed_at = now
                    state.seen = True
                if state.seen:
                    age = now - state.observed_at
                    worst_age = max(worst_age, age)
                    if age >= pdl:
                        stalled = stalled or (rtype, pod, (
                            f"replica {pod.metadata.name} last "
                            f"heartbeat {age:.0f}s ago "
                            f"(progressDeadlineSeconds={pdl})"
                        ))
                    else:
                        sooner(pdl - age)
                elif rdl is not None:
                    waited = now - state.running_since
                    worst_age = max(worst_age, waited)
                    if waited >= rdl:
                        stalled = stalled or (rtype, pod, (
                            f"replica {pod.metadata.name} produced no "
                            f"heartbeat {waited:.0f}s after gang-up "
                            f"(rendezvousDeadlineSeconds={rdl})"
                        ))
                    else:
                        sooner(rdl - waited)
                else:
                    # Baselined but unseen, rendezvous deadline unset:
                    # nothing to enforce YET — but keep the wake chain
                    # alive. The controller never watches leases, so
                    # without a scheduled re-read the first renewal
                    # after gang-up may never be observed and staleness
                    # would silently never arm (an opted-in job with
                    # zero stall protection). The gauge still reports the
                    # wait (documented semantics): an opted-in job whose
                    # heartbeats never arrive should show a growing age,
                    # not a reassuring 0.
                    worst_age = max(worst_age, now - state.running_since)
                    sooner(pdl)
        # Prune pods that vanished (restart, scale-down, terminating):
        # a recreated pod gets a fresh state under its new uid, so the
        # rendezvous clock restarts with the new incarnation. A vanished
        # rank that is OUTSIDE the current world (elastic shrink — not a
        # same-index restart, whose replacement will inherit and
        # re-baseline the lease) takes its heartbeat lease with it: the
        # gauge must only ever aggregate surviving ranks' annotations,
        # and a later regrow must start from a clean lease.
        declared = {
            rt.lower(): (spec.replicas or 0) for rt, spec in replicas.items()
        }
        for uid in [u for u in obs if u not in present]:
            state = obs.pop(uid)
            if state.pod_name and state.index >= declared.get(state.rtype, 0):
                self._gc_heartbeat_lease(job, state.pod_name)
        if self._warm_start_pending:
            # A pending warm-start grow settles once every declared
            # replica is back Running in-range: later restarts of this
            # world are ordinary failures, not the grow, and must run the
            # full restore ladder (storage arbitration included).
            total = sum(declared.values())
            if total and len(present) >= total:
                with self._hb_lock:
                    self._warm_start_pending.pop(
                        (job.key(), job.metadata.uid), None)
        self.on_heartbeat_age(job, worst_age)
        if best_tps is not None:
            self.on_workload_throughput(job, best_tps)
        if min_ckpt is not None:
            self.on_durable_checkpoint(job, min_ckpt)
        if stalled is None and next_check is not None:
            # Wake just past the earliest deadline (the +0.1 keeps a
            # same-instant wake from re-reading "age == deadline - 0").
            self.requeue(f"{job.kind}:{job.key()}", next_check + 0.1)
        return stalled

    def _restart_stalled_gang(
        self, job: JobObject, replicas: Dict[str, ReplicaSpec],
        pods: List[Pod], stall: Tuple[str, Pod, str],
        old_status: JobStatus,
    ) -> None:
        """Tear the gang down for a liveness verdict (cause ProgressStall).
        SPMD worlds (restart_peers_on_failure types) go down as one unit —
        a wedged collective holds every peer hostage, and a lone
        replacement could never rejoin; kinds without world semantics
        restart only the stalled replica. Count/teardown ordering is the
        shared count-before-teardown protocol (_restart_gang_counted) —
        this path pioneered it, because a stalled pod's evidence is the
        pod ITSELF and the teardown destroys it."""
        rtype, stalled_pod, why = stall
        world_types = {
            rt.lower() for rt in replicas
            if self.hooks.restart_peers_on_failure(rt)
        }
        scope, slice_idx, topo, escalated = "world", None, None, False
        if world_types and stalled_pod.metadata.labels.get(
            constants.LABEL_REPLICA_TYPE
        ) in world_types:
            targets = [
                p for p in pods
                if p.metadata.labels.get(constants.LABEL_REPLICA_TYPE)
                in world_types
            ]
            # Slice-scoped stall domains, same rules as the failure path:
            # a wedged collective only holds ITS slice's peers hostage
            # (per-slice ICI mesh), so the stall restart confines to the
            # stalled replica's slice unless the coordinator slice or
            # the minSlices quorum escalates it.
            topo, scope, slice_idx, esc_why = self._slice_restart_scope(
                job, replicas, pods, stalled_pod, world_types
            )
            escalated = esc_why is not None
            if scope == "slice":
                targets = [
                    p for p in targets
                    if self._pod_slice_index(job, topo, replicas, p)
                    == slice_idx
                ]
        else:
            targets = [stalled_pod]
        if scope == "slice":
            reason = constants.job_reason(
                self.hooks.kind, constants.REASON_SLICE_STALL_RESTARTING
            )
            msg = (
                f"{self.hooks.kind} {job.name} is restarting stalled slice "
                f"{slice_idx}: {why}."
            )
        elif escalated:
            reason = constants.job_reason(
                self.hooks.kind, constants.REASON_SLICE_QUORUM_LOST
            )
            msg = (
                f"{self.hooks.kind} {job.name} is restarting the whole "
                f"world for a stall in slice {slice_idx}: {why}."
            )
        else:
            reason = constants.job_reason(
                self.hooks.kind, constants.REASON_STALL_RESTARTING
            )
            msg = (
                f"{self.hooks.kind} {job.name} is restarting "
                f"{'the whole gang' if len(targets) > 1 else 'a stalled replica'}"
                f": {why}."
            )
        self._restart_gang_counted(
            job, pods, targets, stalled_pod, rtype, capi.RESTART_CAUSE_STALL,
            reason, msg, old_status, scope=scope, slice_index=slice_idx,
            topo=topo, escalated=escalated,
        )

    def _restart_gang_counted(
        self, job: JobObject, pods: List[Pod], targets: List[Pod],
        trigger: Pod, rtype: str, cause: str, reason: str, msg: str,
        old_status: JobStatus, scope: str = "world",
        slice_index: Optional[int] = None,
        topo: Optional[SliceTopology] = None, escalated: bool = False,
    ) -> List[tuple]:
        """The count-before-teardown protocol, single-sourced for the
        gang-failure, stall, and admission-preemption restart paths.
        Returns the teardown's (name, exc) delete failures — empty on a
        complete teardown; the phase-1-abort path reports the trigger as
        undeleted so callers can distinguish "nothing happened yet" from
        "done". (The failure path used to
        count at teardown COMPLETION; its crash window — trigger deleted,
        process dies before the counted status write — destroyed the only
        re-detectable evidence and lost the restart from every ledger.
        The crash tier, tests/test_crash_failover.py, holds the line.)

        Phase 1 — make the verdict durable before any pod dies. The
        handled-uid stamp covers EVERY target: controller-initiated
        deletions must not be re-read by the drained-pod trigger as a
        node drain (that would double-charge the incident to the
        disruption ledger — the counters must stay disjoint). A failed
        status write aborts the sync with nothing deleted (the trigger
        re-detects identically on retry); event + metric fire only once
        the count is durable, so a retried phase never duplicates them.

        Phase 2 — the teardown, retried (without re-counting: the stamp
        gates phase 1) until every target is down. Trigger-last matters:
        the trigger is the only member a retried sync — or a freshly
        failed-over controller — can re-DETECT, so it must outlive any
        partial teardown or the leftover healthy pods would never be
        re-judged and the world would restart mixed."""
        key = job.key()
        handled = set(job.status.gang_handled_uids or ())
        # `counted` = phase 1 runs in THIS span (a False span is a resume
        # after the count already landed). Computed ONCE and passed down:
        # the span attr and the phase-1 gate must be the same predicate,
        # because check_span_invariants' counted-exemption audits exactly
        # what the attr claims. The trace's api.* child spans make the
        # protocol auditable after the fact: invariants.py asserts the
        # counted status write precedes every teardown delete in span
        # order.
        counted = trigger.metadata.uid not in handled
        attrs = {
            "cause": cause, "rtype": rtype,
            "trigger": trigger.metadata.name, "targets": len(targets),
            "counted": counted, "scope": scope,
        }
        if slice_index is not None:
            attrs["slice"] = slice_index
        if escalated:
            attrs["escalated"] = True
        if scope == "slice" and topo is not None:
            # The slice-scope audit's self-contained evidence
            # (testing/invariants.py check_span_invariants): the exact
            # target set plus the slice geometry, so a trace alone can
            # prove the teardown never reached outside the slice.
            attrs["hosts_per_slice"] = topo.hosts_per_slice
            attrs["target_names"] = ",".join(
                sorted(p.metadata.name for p in targets)
            )
        with self.tracer.span("gang.restart", attrs=attrs):
            return self._restart_gang_counted_traced(
                job, pods, targets, trigger, rtype, cause, reason, msg,
                old_status, key, handled, counted,
                scope=scope, slice_index=slice_index,
            )

    def _restart_gang_counted_traced(
        self, job: JobObject, pods: List[Pod], targets: List[Pod],
        trigger: Pod, rtype: str, cause: str, reason: str, msg: str,
        old_status: JobStatus, key: str, handled: set, counted: bool,
        scope: str = "world", slice_index: Optional[int] = None,
    ) -> List[tuple]:
        job.status._restarting_this_sync = True
        if counted:
            present = {p.metadata.uid for p in pods}
            # Slice-scoped stamping: the stamp covers exactly the TARGET
            # set, merged with still-present previously-handled uids — so
            # a slice-2 restart (or its crash-resume) never stamps a
            # concurrently-failed slice-5 pod, whose own failure must be
            # counted by its own slice's restart. (The flat model stamped
            # every world pod, which hid exactly that suppression.)
            job.status.gang_handled_uids = sorted(
                (handled & present) | {p.metadata.uid for p in targets}
            )
            capi.update_job_conditions(
                job.status, capi.JOB_RESTARTING, reason, msg, now=self.clock()
            )
            self._count_restart(job, rtype, cause)
            if scope == "slice" and slice_index is not None:
                # Per-slice attribution (status.sliceRestartCounts): made
                # durable by the same phase-1 write as the cause ledger,
                # so the two can never disagree across a crash.
                slot = str(slice_index)
                job.status.slice_restart_counts[slot] = (
                    job.status.slice_restart_counts.get(slot, 0) + 1
                )
            try:
                self._write_status_if_changed(job, old_status)
            except Exception:  # noqa: BLE001 — conflict/transient write error
                # Nothing was deleted: the trigger re-detects identically
                # on the retry, so aborting here keeps counting exact.
                self.requeue(f"{job.kind}:{key}", 1.0)
                return [(trigger.metadata.name, None)]
            record_event_best_effort(
                self.cluster,
                Event(
                    type="Warning",
                    reason=reason,
                    message=msg,
                    involved_object=f"{job.kind}/{key}",
                ),
            )
            self.on_job_restarting(job, rtype, cause)
            self.on_gang_restart(job, scope, slice_index, cause)
            old_status = copy.deepcopy(job.status)
        delete_errors = self._teardown_gang_pods(job, targets, trigger)
        if delete_errors:
            names = ", ".join(n for n, _ in delete_errors)
            record_event_best_effort(
                self.cluster,
                Event(
                    type="Warning",
                    reason=reason,
                    message=(
                        f"{self.hooks.kind} {job.name} gang teardown is "
                        f"partial: delete failed for {names}; retrying."
                    ),
                    involved_object=f"{job.kind}/{key}",
                ),
            )
            self.requeue(f"{job.kind}:{key}", 1.0)
        self._write_status_if_changed(job, old_status)
        return delete_errors

    def _count_restart(self, job: JobObject, rtype: str, cause: str) -> None:
        """Charge one restart to the budget its cause draws from, and open
        the disruption-backoff window when a disruption streak builds."""
        if cause == capi.RESTART_CAUSE_STALL:
            # The stall ledger is deliberately budget-free: each restart
            # is rate-limited by its own deadline window, and
            # activeDeadlineSeconds stays the hard bound. Disjoint from
            # both other ledgers by construction.
            job.status.stall_counts[rtype] = (
                job.status.stall_counts.get(rtype, 0) + 1
            )
            return
        if cause == capi.RESTART_CAUSE_DISRUPTION:
            job.status.disruption_counts[rtype] = (
                job.status.disruption_counts.get(rtype, 0) + 1
            )
            job.status.disruption_streak += 1
            delay = disruption_backoff_seconds(
                job.metadata.uid, job.status.disruption_streak
            )
            if delay > 0:
                job.status.restart_backoff_until = self.clock() + delay
        else:
            job.status.restart_counts[rtype] = (
                job.status.restart_counts.get(rtype, 0) + 1
            )

    # ----------------------------------------- stuck-terminating escalation
    def escalate_stuck_terminating(
        self, job: JobObject, pods: Optional[List[Pod]] = None
    ) -> None:
        """Opt-in (runPolicy.forceDeleteAfterSeconds) dead-host recovery:
        a pod still Terminating past deletionTimestamp (k8s semantics: the
        time the graceful window EXPIRES — request time + grace) plus the
        opt-in bound is force-deleted (grace-period-0) with a Warning
        event and a cause-labeled metric — the kubelet that should have
        finished the deletion is assumed dead (reclaimed TPU host), and
        the lingering object is what blocks gang recreation of that index
        forever. With the field unset this is one None-check per sync and
        the operator NEVER force-deletes.

        Call sites: reconcile_job passes its already-fetched claimed pod
        list (the hot path pays no extra LIST); the expectations-gated
        path (controllers/base.py sync) calls with pods=None — the stuck
        pod is exactly what keeps the deletion expectation unfulfilled,
        so an escalation only behind the gate could first fire after the
        5-minute expectation expiry. The pods=None path lists and then
        keeps ONLY pods whose controllerRef is this job (never act on a
        label-colliding pod another controller owns).

        Each pod uid is escalated at most once per operator incarnation
        (self._force_deleted): a force delete that is accepted but leaves
        the object behind (a foreign finalizer) must not re-fire the
        event/metric every sync. A stuck pod generates no further watch
        events, so the wake is self-scheduled: pods inside their window
        get an AddAfter resync at the earliest upcoming deadline (the
        ActiveDeadline idiom). Write failures are per-pod best-effort —
        the requeue retries, and a force delete that did land unblocks
        the job via its DELETED event (which also satisfies the original
        deletion expectation; no new expectation is recorded here)."""
        from ..cluster.base import NotFound

        fdas = job.run_policy().force_delete_after_seconds
        if fdas is None:
            return
        now = self.clock()
        next_wake: Optional[float] = None
        retry = False
        if pods is None:
            pods = [
                p for p in self.cluster.list_pods(
                    namespace=job.namespace,
                    labels=job_selector(job),
                    owner_uid=job.metadata.uid,
                )
                if (ref := p.metadata.controller_ref()) is not None
                and ref.uid == job.metadata.uid
            ]
        for pod in pods:
            ts = pod.metadata.deletion_timestamp
            if ts is None:
                continue
            deadline = ts + fdas
            if now < deadline:
                remaining = deadline - now
                next_wake = (
                    remaining if next_wake is None
                    else min(next_wake, remaining)
                )
                continue
            dedup_key = (job.key(), job.metadata.uid, pod.metadata.uid)
            with self._hb_lock:
                if dedup_key in self._force_deleted:
                    continue  # already escalated this incarnation
            name = pod.metadata.name
            try:
                # The escalation span wraps only the grace-period-0 write,
                # so its api.delete child (and any error) reads directly
                # off the timeline; cause mirrors the metric label.
                with self.tracer.span("force_delete", attrs={
                    "pod": name,
                    "cause": constants.FORCE_DELETE_CAUSE_STUCK_TERMINATING,
                }):
                    self.cluster.delete_pod(
                        pod.metadata.namespace, name, force=True
                    )
            except NotFound:
                continue  # won the race with the kubelet after all
            except Exception:  # noqa: BLE001 — transient write failure
                log.warning(
                    "force delete of stuck-terminating pod %s/%s failed; "
                    "retrying", pod.metadata.namespace, name, exc_info=True,
                )
                retry = True
                continue
            with self._hb_lock:
                self._force_deleted.add(dedup_key)
            msg = (
                f"Pod {name} was stuck Terminating {now - ts:.0f}s past "
                f"its granted grace period (forceDeleteAfterSeconds "
                f"{fdas}s exceeded; node/kubelet presumed dead) — "
                "force-deleted with grace period 0 to unblock gang "
                "recovery."
            )
            record_event_best_effort(
                self.cluster,
                Event(
                    type="Warning",
                    reason=constants.REASON_FORCE_DELETE_POD,
                    message=msg,
                    involved_object=f"{job.kind}/{job.key()}",
                ),
            )
            self.on_force_delete(
                job, constants.FORCE_DELETE_CAUSE_STUCK_TERMINATING
            )
        if retry:
            self.requeue(f"{job.kind}:{job.key()}", 1.0)
        elif next_wake is not None:
            self.requeue(f"{job.kind}:{job.key()}", next_wake + 0.1)

    # ----------------------------------------------------- batched fan-out
    def _batch_write(self, resource: str, count: int, fn) -> tuple:
        """Issue `count` cluster writes through slow_start_batch, parallel
        only when BOTH the options allow it and the cluster seam declares
        itself safe for concurrent callers (supports_concurrent_writes).
        The serial fallback preserves work-list call order exactly, which
        is what keeps chaos fault schedules — keyed on (method, per-method
        call index) — byte-reproducible with fan-out enabled. Returns
        (successes, first_error)."""
        parallel = self.options.parallel_fanout and bool(
            getattr(self.cluster, "supports_concurrent_writes", False)
        )
        # Parallel fan-out runs `fn` on pool threads whose thread-local
        # trace stack is empty — propagate this sync's context explicitly
        # so every write stays attributed to the job (accounting's
        # record_request reads the ACTIVE thread's context). Serial
        # fan-out runs on this thread and needs nothing. Span ids of
        # parallel writes land in completion order (wall-clock), which is
        # fine: the deterministic fault tiers all serialize (their seams
        # report supports_concurrent_writes=False).
        ctx = self.tracer.current()
        if parallel and ctx is not None:
            inner_fn, tracer = fn, self.tracer

            def fn(i, _inner=inner_fn, _ctx=ctx):
                return tracer.call_in_context(_ctx, _inner, i)

        pool = None
        if parallel and count > 1 and self.options.fanout_max_parallelism > 1:
            from concurrent.futures import ThreadPoolExecutor

            with self._fanout_pool_lock:
                if self._fanout_pool is None:
                    self._fanout_pool = ThreadPoolExecutor(
                        max_workers=max(1, self.options.fanout_max_parallelism),
                        thread_name_prefix="fanout",
                    )
                pool = self._fanout_pool
        successes, err = slow_start_batch(
            count,
            fn,
            parallel=parallel,
            max_parallelism=max(1, self.options.fanout_max_parallelism),
            on_batch=lambda size: self._record_fanout_wave(resource, size),
            pool=pool,
        )
        if err is not None:
            self.on_fanout_abort(resource)
        return successes, err

    def _record_batch_event(self, job: JobObject, reason: str,
                            message: str) -> None:
        """One aggregated Normal event for a whole create/delete batch —
        the write-coalescing replacement for gang-size per-object events
        (single-sourced so the five batch paths cannot drift)."""
        record_event_best_effort(
            self.cluster,
            Event(
                type="Normal",
                reason=reason,
                message=message,
                involved_object=f"{job.kind}/{job.key()}",
            ),
        )

    @staticmethod
    def _batch_range(names: List[str], successes: int, total: int) -> str:
        """Human suffix for an aggregated batch event: the name range is
        only claimed when the WHOLE batch landed — under parallel
        fan-out a partial batch's successes are not a prefix of the work
        list, so naming `names[successes-1]` would cite an object that
        may never have been created."""
        if successes == total and names:
            return f" ({names[0]} .. {names[-1]})" if len(names) > 1 else f" ({names[0]})"
        return ""

    def _record_fanout_wave(self, resource: str, size: int) -> None:
        """One slow-start wave issued: counter + a point event on the
        active span (on_batch fires on the coordinating sync thread, so
        the event lands in the right trace)."""
        self.on_fanout_batch(resource, size)
        self.tracer.event("fanout.wave", resource=resource, size=size)

    def _create_pods_batch(
        self,
        job: JobObject,
        rtype: str,
        indices: List[int],
        spec: ReplicaSpec,
        replicas: Dict[str, ReplicaSpec],
    ) -> None:
        """Create every missing pod of one replica type in one slow-start
        fan-out. Expectations for the WHOLE batch are raised up front —
        the sync gate must block until every issued create's watch event
        lands, not just the last one's — and on a write error exactly the
        failed remainder (count - successes) is rolled back, the
        generalization of the reference createNewPod's per-pod rollback
        (tfjob_controller.go:828-833). The first error then propagates to the rate-limited
        queue with the already-created pods left standing (their events
        fulfill their share of the expectation; the retry sync re-lists
        and creates only what is still missing)."""
        key = job.key()
        pods = [
            self._build_pod(
                job, rtype, index, spec,
                self.hooks.is_master_role(replicas, rtype, index), replicas,
            )
            for index in indices
        ]
        self.expectations.expect_creations(key, "pods", len(pods))
        # Event aggregation (write coalescing): a multi-pod fan-out
        # records ONE SuccessfulCreatePod event for the whole batch
        # instead of gang-size of them — at 32 replicas the per-create
        # event stream alone used to cost as many apiserver writes as
        # the pods themselves. Single creates keep the per-pod event
        # (no pressure to collapse, and the message stays precise).
        quiet = self._coalescing and len(pods) > 1
        successes, err = self._batch_write(
            "pods", len(pods),
            lambda i: self.pod_control.create_pod(
                job.namespace, pods[i], job, quiet=quiet
            ),
        )
        if quiet and successes:
            self._record_batch_event(
                job, constants.REASON_SUCCESSFUL_CREATE_POD,
                f"Created {successes} {rtype} pod(s)" + self._batch_range(
                    [p.metadata.name for p in pods], successes, len(pods)),
            )
        if err is not None:
            for _ in range(len(pods) - successes):
                self.expectations.creation_observed(key, "pods")
            raise err

    # -------------------------------------------------------------- pods
    def reconcile_pods(
        self,
        job: JobObject,
        job_status: JobStatus,
        pods: List[Pod],
        rtype: str,
        spec: ReplicaSpec,
        replicas: Dict[str, ReplicaSpec],
    ) -> None:
        """Reference ReconcilePods with the TF exit-code override folded in
        (tfjob_controller.go:646-742)."""
        if not hasattr(job_status, "_deferred_deletes"):
            job_status._deferred_deletes = []  # direct callers (tests)
        typed_pods = filter_pods_for_replica_type(pods, rtype)
        num_replicas = spec.replicas or 0
        # Rebuilt fresh for every type the SPEC declares — never pruned
        # key-by-key. KubeCluster.patch_job_status relies on this: its
        # merge-patch cannot clear an individual sub-key of a kept map,
        # only whole top-level fields (see its docstring before adding
        # any path that removes single replicaStatuses entries).
        job_status.replica_statuses[rtype] = capi.ReplicaStatus()

        slices = get_pod_slices(typed_pods, num_replicas)
        # Missing in-range slots are COLLECTED here and created in one
        # slow-start fan-out after the scan: a 32-host gang pays log2(32)
        # batched waves instead of 32 sequential apiserver round trips
        # before its first rendezvous (docs/design/
        # control_plane_performance.md).
        to_create: List[int] = []
        for index, pod_slice in enumerate(slices):
            if len(pod_slice) > 1:
                continue  # duplicate pods for an index: wait for cache to settle
            if not pod_slice:
                if index < num_replicas:
                    to_create.append(index)
                continue

            pod = pod_slice[0]
            if index >= num_replicas:
                # Out-of-range (scale-down): delete, with the pod's
                # heartbeat lease — the terminal/suspend GC iterates only
                # the CURRENT spec's indices, so a scaled-down replica's
                # lease would otherwise be orphaned forever.
                self._delete_pod(job, pod)
                if job.run_policy().progress_deadline_seconds is not None:
                    try:
                        self.cluster.delete_lease(
                            job.namespace,
                            constants.heartbeat_lease_name(pod.metadata.name),
                        )
                    except Exception:  # noqa: BLE001 — best-effort GC
                        pass
                continue

            exit_code = get_container_exit_code(pod, self.hooks.default_container_name)
            if exit_code != constants.EXIT_CODE_UNSET:
                record_event_best_effort(
                    self.cluster,
                    Event(
                        type="Normal",
                        reason=constants.REASON_EXITED_WITH_CODE,
                        message=f"Pod: {pod.metadata.namespace}.{pod.metadata.name} exited with code {exit_code}",
                        involved_object=f"{job.kind}/{job.key()}",
                    )
                )

            retryable_failure = (
                spec.restart_policy == capi.RESTART_POLICY_EXIT_CODE
                and pod.status.phase == POD_FAILED
                and capi.is_retryable_exit_code(exit_code)
            )
            if retryable_failure and pod.metadata.deletion_timestamp is not None:
                # Teardown already in flight (the restart was counted when
                # the deletion began): don't re-delete or re-count, but keep
                # this sync in "restarting" so the status machine doesn't
                # read the terminating pod as a job failure.
                job_status._restarting_this_sync = True
            elif retryable_failure and pod.metadata.uid in (
                job_status.gang_handled_uids or ()
            ):
                # Crash leftover: the restart was counted (the phase-1
                # status write landed) but the process died before the
                # delete. Finish the delete without re-charging any budget.
                job_status._restarting_this_sync = True
                job_status._deferred_deletes.append(
                    {"pod": pod, "fresh": False}
                )
            elif retryable_failure:
                # Retryable failure: count the restart and mark the job
                # Restarting (reference :717-736), then delete the pod —
                # but only AFTER the end-of-sync status write makes the
                # count durable (count-before-delete: the failed pod is
                # the only evidence a retried or failed-over sync can
                # re-detect, and deleting it first opened a crash window
                # that silently lost the restart from the budget). Same
                # cause classification as the gang path: a preempted/
                # evicted pod restarts on the disruption budget, a
                # crashing one on backoffLimit. peers_healthy: no OTHER
                # pod of the job failed permanently this sync.
                peers_healthy = not any(
                    p is not pod
                    and p.status.phase == POD_FAILED
                    and (c := get_container_exit_code(
                        p, self.hooks.default_container_name
                    )) != constants.EXIT_CODE_UNSET
                    and not capi.is_retryable_exit_code(c)
                    for p in pods
                )
                cause = capi.classify_pod_failure(
                    pod, exit_code, peers_healthy=peers_healthy
                )
                disrupted = cause == capi.RESTART_CAUSE_DISRUPTION
                reason = constants.job_reason(
                    self.hooks.kind,
                    constants.REASON_DISRUPTION_RESTARTING if disrupted
                    else constants.REASON_RESTARTING,
                )
                detail = "was disrupted" if disrupted else "failed"
                msg = (
                    f"{self.hooks.kind} {job.name} is restarting because "
                    f"{rtype} replica(s) {detail}."
                )
                capi.update_job_conditions(
                    job_status,
                    capi.JOB_RESTARTING,
                    reason,
                    msg,
                    now=self.clock(),
                )
                job_status._restarting_this_sync = True
                # Handled stamp + durable restart accounting: the deleted
                # pod's kubelet counter dies with it, but the budget its
                # cause draws from must see the restart (checked at the
                # next sync's run-policy gate). Stamp merged and pruned to
                # present pods, like every other handled-uid writer.
                present = {p.metadata.uid for p in pods}
                job_status.gang_handled_uids = sorted(
                    (set(job_status.gang_handled_uids or ()) & present)
                    | {pod.metadata.uid}
                )
                self._count_restart(job, rtype, cause)
                job_status._deferred_deletes.append({
                    "pod": pod, "fresh": True, "rtype": rtype,
                    "cause": cause, "reason": reason, "msg": msg,
                })

            update_job_replica_statuses(job_status, rtype, pod)

        admitted_slices = getattr(job_status, "_admitted_slices", None)
        if to_create and admitted_slices is not None:
            # Slice-granular admission: only admitted slices' indices may
            # be born — a queued slice's pods stay unborn (the no-partial-
            # gang rule, applied per slice), while its admitted siblings
            # create normally.
            topo = self.hooks.slice_topology(job, replicas)
            if topo is not None:
                to_create = [
                    index for index in to_create
                    if self.hooks.replica_slice_index(
                        job, topo, replicas, rtype, index
                    ) in admitted_slices
                ]
        if to_create:
            self._create_pods_batch(job, rtype, to_create, spec, replicas)

    def _build_pod(
        self,
        job: JobObject,
        rtype: str,
        index: int,
        spec: ReplicaSpec,
        master_role: bool,
        replicas: Dict[str, ReplicaSpec],
    ) -> Pod:
        """Render one replica's Pod from the template: labels, rendezvous
        env, restart-policy mapping, gang annotations. Pure build — no
        cluster writes, no expectations — so the batch path can construct
        the whole work list deterministically before any write is issued."""
        template = copy.deepcopy(spec.template)
        labels = replica_labels(job, rtype, index)
        if master_role:
            labels[constants.LABEL_JOB_ROLE] = constants.JOB_ROLE_MASTER
        template.metadata.labels.update(labels)
        template.metadata.name = gen_general_name(job.name, rtype, index)
        template.metadata.namespace = job.namespace

        # Framework rendezvous env (TF_CONFIG etc.).
        self.hooks.set_cluster_spec(job, template, rtype, index)

        # Gang-liveness heartbeat env (opt-in via progressDeadlineSeconds):
        # tells runtime/heartbeat.py which Lease this pod renews. Injected
        # after the framework env so the contract is uniform across kinds.
        run_policy = job.run_policy()
        if run_policy.progress_deadline_seconds is not None:
            from ..bootstrap import heartbeat as hb_bootstrap

            hb_env = hb_bootstrap.gen_env(
                template.metadata.name, job.namespace,
                run_policy.progress_deadline_seconds,
            )
            if self.options.delta_persist:
                # Bytes-proportional-to-change persists: the workload's
                # CheckpointManager writes changed shards + a manifest
                # and advertises a have-list on peer restores. Workload-
                # side contract only — the controller just flips the env.
                hb_env[hb_bootstrap.ENV_DELTA_PERSIST] = "1"
            if self.options.peer_restore:
                # Fast-recovery plane: tell the workload to serve its host
                # snapshot (TPU_SHARD_SERVER) and hand this — possibly
                # recreated — pod the survivor shard-server addresses the
                # liveness checks observed on live ranks' leases, so its
                # restore ladder can try peers before storage. Addresses
                # come from the in-memory observation cache (no extra
                # apiserver reads in the build path); pods that died took
                # their observations with them, so only survivors appear.
                hb_env[hb_bootstrap.ENV_SHARD_SERVER] = "1"
                addrs = self._peer_restore_addrs(
                    job, template.metadata.name,
                    progress_deadline_seconds=(
                        run_policy.progress_deadline_seconds),
                )
                if addrs:
                    hb_env[hb_bootstrap.ENV_PEER_RESTORE_ADDRS] = ",".join(addrs)
                if self.options.sharded_restore:
                    # Scatter-gather contract: the ladder's peer rung
                    # plans across ALL advertised survivors instead of
                    # pulling the full tree from one.
                    hb_env[hb_bootstrap.ENV_SHARDED_RESTORE] = "1"
                if self.options.warm_start:
                    with self._hb_lock:
                        grow_snapshot = self._warm_start_pending.get(
                            (job.key(), job.metadata.uid))
                    if grow_snapshot is not None:
                        # This pod is (re)created by a settling elastic
                        # grow: peers hold live snapshots at least as
                        # fresh as storage, so skip the storage probe
                        # entirely (zero-read contract). The live
                        # observation cache is empty mid-restart (every
                        # pod was torn down), so fall back to the
                        # addresses snapshotted when the grow was
                        # flagged — the replaced ranks serve through
                        # their termination grace. Own-name exclusion
                        # still applies: rank N must not wait on its own
                        # predecessor's dying server.
                        hb_env[hb_bootstrap.ENV_WARM_START] = "1"
                        if hb_bootstrap.ENV_PEER_RESTORE_ADDRS not in hb_env:
                            fallback = sorted(
                                addr for name, addr in grow_snapshot.items()
                                if name != template.metadata.name)
                            if fallback:
                                hb_env[hb_bootstrap.ENV_PEER_RESTORE_ADDRS] = (
                                    ",".join(fallback))
            for container in template.spec.containers:
                if container.name != self.hooks.default_container_name:
                    continue
                for name, value in hb_env.items():
                    if container.get_env(name) is None:
                        container.set_env(name, value)

        # Restart policy mapping: ExitCode is operator-managed, so the pod
        # itself must never self-restart (reference pod.go:321-328).
        if spec.restart_policy == capi.RESTART_POLICY_EXIT_CODE:
            template.spec.restart_policy = capi.RESTART_POLICY_NEVER
        elif spec.restart_policy:
            template.spec.restart_policy = spec.restart_policy

        if self.options.enable_gang_scheduling:
            template.metadata.annotations[constants.ANNOTATION_GANG_GROUP_NAME] = (
                self.hooks.gang_group_name(job, rtype, index)
            )
            template.metadata.annotations[constants.ANNOTATION_GANG_TASK_SPEC] = rtype.lower()
            template.spec.scheduler_name = self.options.gang_scheduler_name

        return Pod(metadata=template.metadata, spec=template.spec)

    def _delete_pod(self, job: JobObject, pod: Pod, quiet: bool = False) -> None:
        key = job.key()
        self.expectations.expect_deletions(key, "pods", 1)
        try:
            self.pod_control.delete_pod(
                pod.metadata.namespace, pod.metadata.name, job, quiet=quiet
            )
        except Exception:
            self.expectations.deletion_observed(key, "pods")
            raise

    def _delete_service(self, job: JobObject, svc: Service,
                        quiet: bool = False) -> None:
        """Delete one service under the SAME expectation protocol as
        _delete_pod. Service deletions used to bypass expect_deletions
        entirely, so a slow service delete could never gate the next sync
        the way pod deletes do — a relist racing the deletion re-saw the
        dying service and skipped recreating its index, then double-created
        after the DELETED event landed. One protocol for both dependents
        closes the asymmetry (the controller's service watch observes the
        deletion exactly like the pod watch does)."""
        key = job.key()
        self.expectations.expect_deletions(key, "services", 1)
        try:
            self.service_control.delete_service(
                svc.metadata.namespace, svc.metadata.name, job, quiet=quiet
            )
        except Exception:
            self.expectations.deletion_observed(key, "services")
            raise

    def _delete_pods_and_services(self, job: JobObject, pods: List[Pod], run_policy) -> None:
        """Apply CleanPodPolicy: None keeps everything; Running deletes only
        live (running/pending) pods; All deletes all. Services go with any
        pod cleanup (kubeflow/common deletePodsAndServices semantics).
        Both teardowns fan out through slow_start_batch — gang teardown is
        the other half of restart MTTR — with the first delete error
        aborting the remainder and propagating to the rate-limited queue
        (already-deleted objects don't re-delete on the retry)."""
        policy = run_policy.clean_pod_policy or capi.CLEAN_POD_POLICY_NONE
        if policy == capi.CLEAN_POD_POLICY_NONE:
            return
        doomed = [
            pod for pod in pods
            if policy != capi.CLEAN_POD_POLICY_RUNNING
            or pod.status.phase in (POD_RUNNING, POD_PENDING)
        ]
        # Aggregated teardown events under write coalescing (the
        # _create_pods_batch rule, mirrored): one event per cleanup
        # batch, not one per object.
        quiet_pods = self._coalescing and len(doomed) > 1
        successes, err = self._batch_write(
            "pods", len(doomed),
            lambda i: self._delete_pod(job, doomed[i], quiet=quiet_pods),
        )
        if quiet_pods and successes:
            self._record_batch_event(
                job, constants.REASON_SUCCESSFUL_DELETE_POD,
                f"Deleted {successes} pod(s) (cleanup policy {policy})",
            )
        if err is not None:
            raise err
        services = self.get_services_for_job(job)
        quiet_svcs = self._coalescing and len(services) > 1
        successes, err = self._batch_write(
            "services", len(services),
            lambda i: self._delete_service(job, services[i], quiet=quiet_svcs),
        )
        if quiet_svcs and successes:
            self._record_batch_event(
                job, constants.REASON_SUCCESSFUL_DELETE_SERVICE,
                f"Deleted {successes} service(s) (cleanup policy {policy})",
            )
        if err is not None:
            raise err

    # ----------------------------------------------------------- services
    def reconcile_services(
        self, job: JobObject, services: List[Service], rtype: str, spec: ReplicaSpec
    ) -> None:
        """One headless service per replica index giving each replica a
        stable DNS identity (library ReconcileServices; DNS contract at
        tensorflow.go:153-166)."""
        rt = rtype.lower()
        typed = [
            s for s in services if s.metadata.labels.get(constants.LABEL_REPLICA_TYPE) == rt
        ]
        num_replicas = spec.replicas or 0
        by_index: Dict[int, Service] = {}
        for svc in typed:
            try:
                by_index[int(svc.metadata.labels.get(constants.LABEL_REPLICA_INDEX, ""))] = svc
            except ValueError:
                continue

        # Missing indices fan out through the same slow-start batch path
        # as pods: whole-batch expectations up front, exact rollback of
        # the failed remainder, first error to the rate-limited queue.
        missing = [i for i in range(num_replicas) if i not in by_index]
        if missing:
            services = [
                self._build_service(job, rtype, index, spec)
                for index in missing
            ]
            key = job.key()
            self.expectations.expect_creations(key, "services", len(services))
            # One aggregated SuccessfulCreateService event per multi-
            # service fan-out (the _create_pods_batch event-aggregation
            # rule, identically applied).
            quiet = self._coalescing and len(services) > 1
            successes, err = self._batch_write(
                "services", len(services),
                lambda i: self.service_control.create_service(
                    job.namespace, services[i], job, quiet=quiet
                ),
            )
            if quiet and successes:
                self._record_batch_event(
                    job, constants.REASON_SUCCESSFUL_CREATE_SERVICE,
                    f"Created {successes} {rtype} service(s)"
                    + self._batch_range(
                        [s.metadata.name for s in services],
                        successes, len(services)),
                )
            if err is not None:
                for _ in range(len(services) - successes):
                    self.expectations.creation_observed(key, "services")
                raise err

        for index, svc in sorted(by_index.items()):
            if index >= num_replicas:
                self._delete_service(job, svc)

    def _build_service(
        self, job: JobObject, rtype: str, index: int, spec: ReplicaSpec
    ) -> Service:
        """Render one replica's headless Service (pure build, no writes —
        the service analog of _build_pod)."""
        labels = replica_labels(job, rtype, index)
        service = Service(
            metadata=copy.deepcopy(spec.template.metadata),
            spec=ServiceSpec(
                cluster_ip="None",
                selector=labels,
                ports=[
                    ServicePort(
                        name=self.hooks.default_port_name,
                        port=self._port_from_spec(spec),
                    )
                ],
            ),
        )
        service.metadata.name = gen_general_name(job.name, rtype, index)
        service.metadata.namespace = job.namespace
        service.metadata.labels = dict(service.metadata.labels)
        service.metadata.labels.update(labels)
        return service

    def _port_from_spec(self, spec: ReplicaSpec) -> int:
        for container in spec.template.spec.containers:
            if container.name == self.hooks.default_container_name:
                for p in container.ports:
                    if p.name == self.hooks.default_port_name:
                        return p.container_port
        return self.hooks.default_port

    # ---------------------------------------------------------- run policy
    def _past_active_deadline(self, job: JobObject, run_policy) -> bool:
        if run_policy.active_deadline_seconds is None or job.status.start_time is None:
            return False
        return self.clock() - job.status.start_time >= run_policy.active_deadline_seconds

    def _past_backoff_limit(
        self, job: JobObject, run_policy, replicas: Dict[str, ReplicaSpec], pods: List[Pod]
    ) -> bool:
        """Total restarts across both restart mechanisms (kubeflow/common
        PastBackoffLimit, extended): kubelet container restartCounts for
        OnFailure/Always replicas, plus the job's durable
        status.restartCounts for operator-managed ExitCode restarts (whose
        recreated pods always report kubelet count 0)."""
        if run_policy.backoff_limit is None:
            return False
        restarts = sum(job.status.restart_counts.values())
        for rtype, spec in replicas.items():
            if spec.restart_policy not in (
                capi.RESTART_POLICY_ON_FAILURE,
                capi.RESTART_POLICY_ALWAYS,
            ):
                continue
            for pod in filter_pods_for_replica_type(pods, rtype):
                if pod.status.phase in (POD_RUNNING, POD_PENDING):
                    for cs in pod.status.container_statuses:
                        restarts += cs.restart_count
        if run_policy.backoff_limit == 0:
            return restarts > 0
        return restarts >= run_policy.backoff_limit

    def _past_disruption_limit(self, job: JobObject, run_policy) -> bool:
        """The disruption budget (RunPolicy.maxDisruptionRetries) mirrors
        backoffLimit's accounting over the disruption ledger. None —
        the default — is unlimited: preemption-and-resume is a normal,
        budget-free operation on TPU fleets."""
        limit = run_policy.max_disruption_retries
        if limit is None:
            return False
        disruptions = sum(job.status.disruption_counts.values())
        if limit == 0:
            return disruptions > 0
        return disruptions >= limit

    # ----------------------------------------------------------- suspension
    def _suspend_job(
        self, job: JobObject, pods: List[Pod], replicas: Dict[str, ReplicaSpec], run_policy
    ) -> None:
        """Delete every pod and service (and gang groups) of a live job
        without marking it Failed; the Suspended condition records why
        nothing is running."""
        if self._admission is not None:
            # Suspension releases the whole slice back to the scheduler —
            # the admission reservation goes with it; resume re-enters
            # through the admission gate like a fresh gang.
            self._admission.release(f"{job.kind}:{job.key()}")
        already = capi.get_condition(job.status, capi.JOB_SUSPENDED)
        settled = (
            already is not None
            and already.status == capi.CONDITION_TRUE
            and not pods
        )
        if settled:
            # Steady-state suspension: nothing to tear down — repeating the
            # deletes every resync would burn the QPS budget on NotFounds.
            return
        # Zero the per-type counters: the normal sync path rebuilds them in
        # reconcile_pods, which a suspended job never reaches — stale
        # `active` counts would report live workers on a released slice.
        for rtype in replicas:
            job.status.replica_statuses[rtype] = capi.ReplicaStatus()
        deleted_uids = []
        for pod in pods:
            if pod.metadata.deletion_timestamp is None:
                self._delete_pod(job, pod)
                deleted_uids.append(pod.metadata.uid)
        if deleted_uids:
            # Suspension teardown is controller-initiated: stamp it so a
            # quick resume doesn't misread the still-terminating pods as a
            # node-drain disruption of the fresh world. Merged (pruned to
            # present pods), same as the stale-world stamp: replacing would
            # un-handle a counted trigger still in its grace period.
            present = {p.metadata.uid for p in pods}
            job.status.gang_handled_uids = sorted(
                (set(job.status.gang_handled_uids or ()) & present)
                | set(deleted_uids)
            )
        for svc in self.get_services_for_job(job):
            self._delete_service(job, svc)
        self._delete_heartbeat_leases(job, replicas, run_policy)
        if self.options.enable_gang_scheduling:
            self._delete_gang_groups(job, replicas, run_policy)
        if already is None or already.status != capi.CONDITION_TRUE:
            msg = f"{self.hooks.kind} {job.name} is suspended."
            capi.update_job_conditions(
                job.status,
                capi.JOB_SUSPENDED,
                constants.job_reason(self.hooks.kind, constants.REASON_SUSPENDED),
                msg,
                now=self.clock(),
            )
            record_event_best_effort(
                self.cluster,
                Event(
                    type="Normal",
                    reason=constants.job_reason(self.hooks.kind, constants.REASON_SUSPENDED),
                    message=msg,
                    involved_object=f"{job.kind}/{job.key()}",
                )
            )

    # ------------------------------------------------------------ terminal
    def _handle_terminal_job(
        self, job: JobObject, pods: List[Pod], replicas: Dict[str, ReplicaSpec], run_policy
    ) -> None:
        """CleanPodPolicy + TTL GC once the job reached Succeeded/Failed."""
        if self._admission is not None:
            # A finished gang frees its capacity/quota immediately (and
            # exactly as often as it likes — release is idempotent);
            # waiting gangs are kicked by the arbiter.
            self._admission.release(f"{job.kind}:{job.key()}")
        self._delete_pods_and_services(job, pods, run_policy)
        if run_policy.progress_deadline_seconds is not None:
            gc_key = (job.key(), job.metadata.uid)
            with self._hb_lock:
                first_terminal_sync = gc_key not in self._hb_gc_done
                self._hb_gc_done.add(gc_key)
            if first_terminal_sync:
                self._delete_heartbeat_leases(job, replicas, run_policy)
                # A job that went terminal while stale must not keep
                # exporting its last (above-threshold) heartbeat age —
                # the staleness alert would page forever for a job that
                # is already Succeeded/Failed. Its throughput series is
                # DROPPED for the dual reason: a lingering last value
                # reads as live throughput, and a 0.0 would trip
                # low-throughput alerts on every finished job (and invent
                # a series for jobs that never reported).
                self.on_heartbeat_age(job, 0.0)
                self.on_workload_throughput(job, None)
                # Same reasoning for the durable-step series: a finished
                # job's last durable step is history, not a live gate.
                self.on_durable_checkpoint(job, None)

        ttl = run_policy.ttl_seconds_after_finished
        if ttl is not None:
            finished_at = job.status.completion_time or job.status.last_reconcile_time
            if finished_at is None:
                finished_at = self.clock()
            expiry = finished_at + ttl
            if self.clock() >= expiry:
                try:
                    self.cluster.delete_job(job.kind, job.namespace, job.name)
                except Exception:
                    pass
                self.expectations.delete_expectations(job.key(), "pods")
                self.expectations.delete_expectations(job.key(), "services")
            else:
                self.requeue(f"{job.kind}:{job.key()}", expiry - self.clock())

        if self.options.enable_gang_scheduling:
            self._delete_gang_groups(job, replicas, run_policy)

    def _delete_heartbeat_leases(
        self, job: JobObject, replicas: Dict[str, ReplicaSpec], run_policy
    ) -> None:
        """GC the per-pod heartbeat Leases of a finished/suspended job.
        Best-effort by design: a lease is tiny, same-name pod recreations
        overwrite it, and a terminal job must never wedge on GC — so every
        failure (including a backend predating delete_lease) is swallowed."""
        if run_policy.progress_deadline_seconds is None:
            return
        for rtype, spec in replicas.items():
            for index in range(spec.replicas or 0):
                name = constants.heartbeat_lease_name(
                    gen_general_name(job.name, rtype, index)
                )
                try:
                    self.cluster.delete_lease(job.namespace, name)
                except Exception:  # noqa: BLE001 — best-effort GC
                    pass

    def _delete_gang_groups(self, job: JobObject, replicas: Dict[str, ReplicaSpec], run_policy) -> None:
        """Tear down the gang units (terminal cleanup and suspension).
        Only NotFound is tolerated — a real API failure (RBAC, network)
        must surface, or the PodGroup leaks in the scheduler silently.
        Deletes the declared set AND anything else carrying the job's label
        stamp (groups from a pre-resize topology)."""
        from ..cluster.base import NotFound

        for group in self.hooks.gang_groups(job, replicas, run_policy):
            meta = group.get("metadata", {})
            try:
                self.cluster.delete_pod_group(
                    meta.get("namespace", job.namespace), meta["name"]
                )
            except NotFound:
                pass
        self._delete_stale_gang_groups(job, declared=set())

    def _delete_stale_gang_groups(self, job: JobObject, declared: set) -> None:
        """Delete THIS job's PodGroups not in `declared` — membership is
        decided by the ownerReference UID, not the name labels alone (a
        same-name job of a different kind shares the label stamp and must
        not have its live group swept). Groups created by an older operator
        (no stamp) are invisible here — they converge at terminal cleanup
        via the declared-name path."""
        try:
            existing = self.cluster.list_pod_groups(
                namespace=job.namespace, labels=job_selector(job)
            )
        except NotImplementedError:
            return  # backend predates group listing; declared-name path only
        from ..cluster.base import NotFound

        for group in existing:
            meta = group.get("metadata") or {}
            name = meta.get("name", "")
            owned = any(
                ref.get("uid") == job.metadata.uid and ref.get("controller")
                for ref in meta.get("ownerReferences") or []
            )
            if owned and name and name not in declared:
                try:
                    self.cluster.delete_pod_group(job.namespace, name)
                except NotFound:
                    pass

    # ----------------------------------------------------------- pod group
    def _sync_pod_group(self, job: JobObject, replicas: Dict[str, ReplicaSpec], run_policy) -> None:
        """Create the gang unit(s) (volcano PodGroup analog; reference
        SyncPodGroup via kubeflow/common when EnableGangScheduling). Groups
        come from the hooks so the JAX controller can gang per slice.

        Only NotFound triggers a create: a transient GET failure (500,
        timeout) must NOT cause a blind create — it would race a live group
        and mask the real error. Conflict on create (another worker won the
        race) is fine. Anything else propagates to the rate-limited queue.

        A gang sitting in the scheduler queue is surfaced as a Queued job
        condition (observable backpressure — no reference counterpart; the
        reference's PodGroup is fire-and-forget)."""
        from ..cluster.base import Conflict, NotFound

        queued_phases = []
        declared = set()
        for group in self.hooks.gang_groups(job, replicas, run_policy):
            meta = group.get("metadata", {})
            declared.add(meta["name"])
            try:
                live = self.cluster.get_pod_group(
                    meta.get("namespace", job.namespace), meta["name"]
                )
            except NotFound:
                try:
                    self.cluster.create_pod_group(group)
                except Conflict:
                    pass  # concurrent creator; next sync reads it back
                continue
            phase = ((live.get("status") or {}).get("phase")) or ""
            if phase in ("Pending", "Inqueue"):
                queued_phases.append((meta.get("name", job.name), phase))
        # Converge away groups the current spec no longer declares (e.g. a
        # multislice scale-down: numSlices 3 -> 2 must release slice-2's
        # reservation, or the scheduler keeps honoring a gang that no pod
        # will ever join). The sweep costs an uncached LIST, so it runs
        # only when the declared set changes (plus once per operator
        # lifetime per job — the cache is in-memory, so a restart re-checks).
        cache_key = (job.key(), job.metadata.uid)
        with self._gang_declared_lock:
            unchanged = self._gang_declared.get(cache_key) == declared
        if not unchanged:
            self._delete_stale_gang_groups(job, declared)
            with self._gang_declared_lock:
                self._gang_declared[cache_key] = declared
        if queued_phases and not capi.is_running(job.status):
            names = ", ".join(f"{n}={p}" for n, p in queued_phases)
            capi.update_job_conditions(
                job.status,
                capi.JOB_QUEUED,
                constants.job_reason(job.kind, constants.REASON_QUEUED),
                f"gang(s) waiting for scheduler capacity: {names}",
                now=self.clock(),
            )

    # ------------------------------------------------------ gang admission
    def _admission_gate(
        self, job: JobObject, replicas: Dict[str, ReplicaSpec], run_policy,
        pods: List[Pod], old_status: JobStatus,
    ) -> bool:
        """The per-sync admission decision (core/admission.py). Returns
        True when the job is admitted and the normal pod reconcile may
        proceed; False ends the sync — either QUEUED (condition + event
        written, pods held unborn, a fallback requeue armed beside the
        arbiter's kicks) or PREEMPTING (the counted disruption teardown
        ran; the admission release is acknowledged only once the counted
        write is durable, so the disruption ledger and the preemption
        ledger agree exactly-once across crashes)."""
        from .admission import gang_demand

        adm = self._admission
        key = job.key()
        item = f"{job.kind}:{key}"

        # Slice-granular admission (flagged, --admission-slice-granularity):
        # a multislice job's slices are individually admittable,
        # preemptable and backfillable demands — a capacity revocation
        # preempts one slice (slice-local counted teardown, slice-local
        # re-queue) instead of evicting the job.
        topo = self.hooks.slice_topology(job, replicas)
        if getattr(adm, "slice_granular", False):
            if topo is not None and topo.num_slices > 1:
                return self._admission_gate_sliced(
                    job, replicas, run_policy, pods, old_status, topo
                )
            # Granularity transition (elastic resize to a single slice):
            # stale '#slice-' registrations from the sliced gate must
            # not keep double-charging the pool beside this flat one.
            adm.release_stale_granularity(item, sliced=False)

        cause = adm.preemption_requested(item)
        if cause is not None:
            live = [p for p in pods if p.metadata.deletion_timestamp is None]
            if live:
                trigger = max(live, key=lambda p: p.metadata.name)
                trigger_rt = trigger.metadata.labels.get(
                    constants.LABEL_REPLICA_TYPE, ""
                )
                rtype = next(
                    (rt for rt in replicas if rt.lower() == trigger_rt),
                    next(iter(replicas), ""),
                )
                reason = constants.job_reason(
                    self.hooks.kind, constants.REASON_GANG_PREEMPTED
                )
                msg = (
                    f"{self.hooks.kind} {job.name} is preempted by gang "
                    f"admission ({cause}): the gang releases its capacity "
                    "and re-queues at the head of its priority band."
                )
                # The shared count-before-teardown protocol: the
                # disruption count is durable before any pod dies, the
                # trigger dies last, retries never re-count (the
                # handled-uid stamp), and the span-order audit holds.
                errors = self._restart_gang_counted(
                    job, pods, live, trigger, rtype,
                    capi.RESTART_CAUSE_DISRUPTION, reason, msg, old_status,
                )
                if not errors and trigger.metadata.uid in (
                    job.status.gang_handled_uids or ()
                ):
                    # Counted write landed (this sync or a crashed
                    # predecessor's) AND the teardown completed: the
                    # preemption may be acknowledged — quota released,
                    # re-queued at the head of its band, exactly one
                    # ledger entry. A PARTIAL teardown keeps the
                    # preemption pending instead: acking early would let
                    # the next sync's adoption path (has_pods) re-admit
                    # a half-torn-down gang; the teardown's own requeue
                    # resumes it off the stamp without re-counting.
                    adm.note_preempted(item, job.metadata.uid, cause)
                return False
            # Nothing left to tear down (pods already gone): acknowledge
            # and fall through to the queued path below.
            adm.note_preempted(item, job.metadata.uid, cause)

        sp = run_policy.scheduling_policy
        groups = self.hooks.gang_groups(job, replicas, run_policy)
        result = adm.try_admit(
            key=item, kind=job.kind, namespace=job.namespace, name=job.name,
            uid=job.metadata.uid,
            priority_class=(sp.priority_class if sp is not None else "") or "",
            throughput_ratios=dict(
                (sp.throughput_ratios if sp is not None else None) or {}
            ),
            demand=gang_demand(groups),
            members=sum(
                int((g.get("spec") or {}).get("minMember") or 0)
                for g in groups
            ),
            has_pods=any(
                p.metadata.deletion_timestamp is None for p in pods
            ),
            kick=lambda item=item: self.requeue(item, 0.0),
        )
        if result.admitted:
            if result.newly_admitted and capi.has_condition(
                job.status, capi.JOB_QUEUED
            ):
                # The queued -> admitted transition: the measured wait
                # becomes the admission.queue span (the trace's
                # queue-wait analog at the capacity layer) and one event.
                self.tracer.record_span(
                    "admission.queue", duration=result.waited,
                    attrs={"wait": round(result.waited, 3)},
                )
                record_event_best_effort(
                    self.cluster,
                    Event(
                        type="Normal",
                        reason=constants.job_reason(
                            job.kind, constants.REASON_GANG_ADMITTED
                        ),
                        message=(
                            f"{self.hooks.kind} {job.name} was admitted "
                            f"after waiting {result.waited:.1f}s for "
                            "capacity."
                        ),
                        involved_object=f"{job.kind}/{key}",
                    ),
                )
            self._set_group_phases(job, groups, "Running")
            return True

        # Queued: pods stay unborn. The condition is the observable
        # surface (plus the mirrored PodGroup Inqueue phase on backends
        # that model it); the fallback requeue keeps the decision fresh
        # even if every admission kick is lost.
        names = ", ".join(
            sorted((g.get("metadata") or {}).get("name", "") for g in groups)
        )
        capi.update_job_conditions(
            job.status,
            capi.JOB_QUEUED,
            constants.job_reason(job.kind, constants.REASON_QUEUED),
            f"gang admission: waiting on {result.blocked_on or 'capacity'}"
            f" ({names})",
            now=self.clock(),
        )
        if result.newly_queued:
            record_event_best_effort(
                self.cluster,
                Event(
                    type="Normal",
                    reason=constants.job_reason(
                        job.kind, constants.REASON_QUEUED
                    ),
                    message=(
                        f"{self.hooks.kind} {job.name} is queued by gang "
                        f"admission (blocked on "
                        f"{result.blocked_on or 'capacity'})."
                    ),
                    involved_object=f"{job.kind}/{key}",
                ),
            )
        self._set_group_phases(job, groups, "Inqueue")
        self._write_status_if_changed(job, old_status)
        self.requeue(item, 1.0)
        return False

    def _admission_gate_sliced(
        self, job: JobObject, replicas: Dict[str, ReplicaSpec], run_policy,
        pods: List[Pod], old_status: JobStatus, topo: SliceTopology,
    ) -> bool:
        """The per-SLICE admission decision (flagged headroom over the
        PR 9 arbiter): each slice of a multislice job registers its own
        demand under "<item>#slice-<s>" — hooks.gang_groups already
        emits one PodGroup per slice, so slice s's demand is exactly
        group s's. Verdicts compose per slice:

        - a slice with a pending preemption runs the SLICE-SCOPED counted
          disruption teardown (surviving slices' pods never deleted) and
          is acknowledged to the arbiter only once the counted write is
          durable and the teardown complete — then it re-queues at the
          head of its band, slice-local;
        - admitted slices proceed to pod work; reconcile_pods creates
          only their indices (status._admitted_slices);
        - queued slices hold their pods unborn. Zero admitted slices is
          the whole-job queued path (JOB_QUEUED condition, sync ends);
          a partial admission proceeds with a fallback requeue polling
          for the waiting slices.

        Release paths (terminal/suspend/delete) free every slice at once:
        AdmissionController.release treats "#slice-" sub-keys of the job
        key as part of it."""
        from .admission import gang_demand

        adm = self._admission
        key = job.key()
        item = f"{job.kind}:{key}"
        # Granularity transition (resize 1 -> N slices): a stale
        # plain-key registration from the flat gate must not linger
        # beside the per-slice ones.
        adm.release_stale_granularity(item, sliced=True)
        sp = run_policy.scheduling_policy
        groups = self.hooks.gang_groups(job, replicas, run_policy)
        world_types = {
            rt.lower() for rt in replicas
            if self.hooks.restart_peers_on_failure(rt)
        }

        pods_by_slice: Dict[int, List[Pod]] = {}
        for pod in pods:
            s = self._pod_slice_index(job, topo, replicas, pod)
            if s is not None:
                pods_by_slice.setdefault(s, []).append(pod)

        # Pending slice preemptions first — ONE counted slice teardown per
        # sync (its requeue resumes any others, exactly like the gang
        # teardown's own retry protocol).
        for s in range(len(groups)):
            skey = f"{item}#slice-{s}"
            cause = adm.preemption_requested(skey)
            if cause is None:
                continue
            live = [
                p for p in pods_by_slice.get(s, ())
                if p.metadata.deletion_timestamp is None
                and p.metadata.labels.get(constants.LABEL_REPLICA_TYPE)
                in world_types
            ]
            if live:
                trigger = max(live, key=lambda p: p.metadata.name)
                trigger_rt = trigger.metadata.labels.get(
                    constants.LABEL_REPLICA_TYPE, ""
                )
                rtype = next(
                    (rt for rt in replicas if rt.lower() == trigger_rt),
                    next(iter(replicas), ""),
                )
                reason = constants.job_reason(
                    self.hooks.kind, constants.REASON_GANG_PREEMPTED
                )
                msg = (
                    f"{self.hooks.kind} {job.name} slice {s} is preempted "
                    f"by gang admission ({cause}): the slice releases its "
                    "capacity and re-queues at the head of its priority "
                    "band; surviving slices keep running."
                )
                errors = self._restart_gang_counted(
                    job, pods, live, trigger, rtype,
                    capi.RESTART_CAUSE_DISRUPTION, reason, msg, old_status,
                    scope="slice", slice_index=s, topo=topo,
                )
                if not errors and trigger.metadata.uid in (
                    job.status.gang_handled_uids or ()
                ):
                    # Same ack rule as the flat gate: counted write
                    # durable AND teardown complete, else the pending
                    # marker keeps the slice's capacity charged.
                    adm.note_preempted(skey, job.metadata.uid, cause)
                return False
            adm.note_preempted(skey, job.metadata.uid, cause)

        admitted: set = set()
        blocked: List[tuple] = []
        for s, group in enumerate(groups):
            skey = f"{item}#slice-{s}"
            gspec = group.get("spec") or {}
            result = adm.try_admit(
                key=skey, kind=job.kind, namespace=job.namespace,
                name=f"{job.name}#slice-{s}", uid=job.metadata.uid,
                priority_class=(
                    sp.priority_class if sp is not None else ""
                ) or "",
                throughput_ratios=dict(
                    (sp.throughput_ratios if sp is not None else None) or {}
                ),
                demand=gang_demand([group]),
                members=int(gspec.get("minMember") or 0),
                has_pods=any(
                    p.metadata.deletion_timestamp is None
                    for p in pods_by_slice.get(s, ())
                ),
                kick=lambda item=item: self.requeue(item, 0.0),
                # Victim preference: evict higher slices first so the
                # coordinator slice (0) is only ever chosen once no
                # other slice in the band remains — the admission-side
                # mirror of the coordinator-escalation rule.
                victim_rank=s,
            )
            if result.admitted:
                admitted.add(s)
                # Announce on the measured wait, not the JOB_QUEUED
                # condition: under partial admission the job may carry
                # Running (a sibling slice) while THIS slice waited out
                # its whole queue time — the aging/starvation telemetry
                # must still see that wait.
                if result.newly_admitted and result.waited > 0.0:
                    self.tracer.record_span(
                        "admission.queue", duration=result.waited,
                        attrs={"wait": round(result.waited, 3), "slice": s},
                    )
                    record_event_best_effort(
                        self.cluster,
                        Event(
                            type="Normal",
                            reason=constants.job_reason(
                                job.kind, constants.REASON_GANG_ADMITTED
                            ),
                            message=(
                                f"{self.hooks.kind} {job.name} slice {s} "
                                f"was admitted after waiting "
                                f"{result.waited:.1f}s for capacity."
                            ),
                            involved_object=f"{job.kind}/{key}",
                        ),
                    )
            else:
                blocked.append((s, result))
                if result.newly_queued:
                    record_event_best_effort(
                        self.cluster,
                        Event(
                            type="Normal",
                            reason=constants.job_reason(
                                job.kind, constants.REASON_QUEUED
                            ),
                            message=(
                                f"{self.hooks.kind} {job.name} slice {s} is "
                                f"queued by gang admission (blocked on "
                                f"{result.blocked_on or 'capacity'})."
                            ),
                            involved_object=f"{job.kind}/{key}",
                        ),
                    )

        if not admitted:
            names = ", ".join(
                sorted(
                    (g.get("metadata") or {}).get("name", "") for g in groups
                )
            )
            blocked_on = ", ".join(
                sorted({r.blocked_on or "capacity" for _, r in blocked})
            )
            capi.update_job_conditions(
                job.status,
                capi.JOB_QUEUED,
                constants.job_reason(job.kind, constants.REASON_QUEUED),
                f"gang admission: waiting on {blocked_on or 'capacity'}"
                f" ({names})",
                now=self.clock(),
            )
            self._set_group_phases(job, groups, "Inqueue")
            self._write_status_if_changed(job, old_status)
            self.requeue(item, 1.0)
            return False

        job.status._admitted_slices = admitted
        self._set_group_phases(
            job, [groups[s] for s in sorted(admitted)], "Running"
        )
        if blocked:
            self._set_group_phases(
                job, [groups[s] for s, _ in blocked], "Inqueue"
            )
            # Fallback poll for the waiting slices (admission kicks are
            # the fast path, this keeps the verdict fresh if one is lost).
            self.requeue(item, 1.0)
        return True

    def _set_group_phases(self, job: JobObject, groups: List[dict],
                          phase: str) -> None:
        """Mirror the admission verdict onto the job's PodGroup phases so
        the existing phase-driven surfaces (the _sync_pod_group Queued
        check, dashboards reading PodGroups) agree with the arbiter.
        Best-effort and only on backends that model group status (the
        in-memory simulator); on a real cluster Volcano owns the phase."""
        if not self.options.enable_gang_scheduling:
            return
        setter = getattr(self.cluster, "set_pod_group_phase", None)
        if setter is None:
            return
        for group in groups:
            meta = group.get("metadata") or {}
            try:
                setter(meta.get("namespace", job.namespace), meta["name"], phase)
            except Exception:  # noqa: BLE001 — a mirror, never a gate
                pass

    # -------------------------------------------------------------- status
    # Status keys whose change may be COALESCED: pure bring-up/teardown
    # churn (per-type active/succeeded/failed counters flapping pod by
    # pod) plus the write timestamp itself. EVERYTHING else — conditions,
    # the three restart ledgers, the gang handled-uid stamp, start/
    # completion times, backoff windows — flushes synchronously: those
    # fields are the count-before-teardown protocol's durable evidence
    # and the API contract consumers watch, and a deferred write there
    # would open exactly the crash windows PR 3 closed. Camel-cased (the
    # to_dict wire names) because the delta is computed on serialized
    # snapshots.
    _COALESCIBLE_STATUS_KEYS = frozenset({
        "replicaStatuses", "lastReconcileTime",
    })

    def _write_status_if_changed(self, job: JobObject, old_status: JobStatus) -> None:
        """Persist job.status iff it differs from what the cluster holds.

        Legacy path (write_coalescing off — chaos/crash/process seams and
        the --disable-write-coalescing lever): one synchronous full-object
        update_job_status per changed sync, byte-identical to the
        pre-coalescing engine.

        Coalesced path (resolve_write_coalescing True): writes go out as
        single-request status patches (patch_job_status), and a delta
        confined to _COALESCIBLE_STATUS_KEYS inside the per-job rate
        window (options.status_flush_interval since the last flush) is
        BUFFERED instead of written: the cluster copy stays intentionally
        stale, a requeue is scheduled for the window's close, and the
        flush sync re-derives the status from scratch — so the buffer is
        the knowledge that the stored copy is behind, never a second
        source of truth, and a crash loses nothing but churn the next
        sync recomputes. Any non-coalescible delta (conditions, ledgers,
        stamps — the counted writes' superset) flushes immediately and in
        order, carrying every previously deferred change with it.

        Propagate write failures either way: the caller's rate-limited
        queue must retry, or a terminal condition computed here is lost
        forever (a finished job emits no further events to trigger
        another sync)."""
        old_d = to_dict(old_status)
        new_d = to_dict(job.status)
        key = (job.key(), job.metadata.uid)
        if new_d == old_d:
            # The stored copy IS current: deferred churn (if any) either
            # flushed with an intervening write or reverted — drop the
            # dirty marker, or a much later flush would report its age
            # as a bogus multi-hour flush latency.
            with self._status_lock:
                self._status_dirty_since.pop(key, None)
            return
        if self._coalescing:
            changed = {
                k for k in set(old_d) | set(new_d)
                if old_d.get(k) != new_d.get(k)
            }
            if changed <= {"lastReconcileTime"}:
                # Write-timestamp-only churn is a no-op, never a write —
                # and nothing meaningful is pending (same stale-marker
                # rule as the equal case above).
                with self._status_lock:
                    self._status_dirty_since.pop(key, None)
                return
            if changed <= self._COALESCIBLE_STATUS_KEYS:
                now = self.clock()
                with self._status_lock:
                    last = self._status_last_flush.get(key)
                    defer = (
                        last is not None
                        and now - last < self.options.status_flush_interval
                    )
                    if defer:
                        self._status_dirty_since.setdefault(key, now)
                        wake = self.options.status_flush_interval - (now - last)
                if defer:
                    self.on_status_coalesced(job)
                    # The flush ride: a watch event cannot be counted on
                    # (deferred churn generates none), so the window's
                    # close schedules its own resync, which re-derives
                    # the status and finds the stored copy behind.
                    self.requeue(f"{job.kind}:{job.key()}", wake + 0.05)
                    return
        job.status.last_reconcile_time = self.clock()
        # new_d was serialized above and only the stamp moved since:
        # patch it in place instead of re-walking the whole status tree
        # (this is the hottest write path of a large gang's bring-up).
        new_d["lastReconcileTime"] = job.status.last_reconcile_time
        if self._coalescing:
            self.cluster.patch_job_status(
                job.kind, job.namespace, job.name, new_d
            )
        else:
            self.cluster.update_job_status(
                job.kind, job.namespace, job.name, new_d
            )
        now = self.clock()
        with self._status_lock:
            self._status_last_flush[key] = now
            dirty_since = self._status_dirty_since.pop(key, None)
        if dirty_since is not None:
            self.on_status_flush(job, max(0.0, now - dirty_since))
