"""Rate-limited work queue with deduplication and delayed adds.

Mirrors the semantics the reference gets from client-go's
RateLimitingInterface (legacy run loop controller.go:193-286): an item
enqueued while queued is deduplicated; an item enqueued while being processed
is re-queued after processing ("dirty" set); failures re-add with exponential
backoff; AddAfter schedules a future enqueue (used for ActiveDeadline and TTL
resyncs, tfjob_controller.go:381, job.go:174-190).
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Set, Tuple


class WorkQueue:
    BASE_DELAY = 0.005
    MAX_DELAY = 16.0

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._cond = threading.Condition()
        # deque, not list: get() pops the head, and list.pop(0) is O(n) —
        # at 100 queued jobs every pop shifted the whole backlog, a cost
        # paid once per sync by every worker of the pool.
        self._queue: Deque[str] = deque()
        self._queued: Set[str] = set()
        self._processing: Set[str] = set()
        self._dirty: Set[str] = set()
        self._delayed: List[Tuple[float, int, str]] = []  # (when, seq, item)
        self._seq = 0
        self._failures: Dict[str, int] = {}
        self._shutdown = False
        # item -> clock() at the moment it entered the immediate queue;
        # drained in get() to measure queue wait (client-go's
        # workqueue_queue_duration_seconds analog). Delayed items start
        # their wait when they come DUE, not when scheduled — an
        # ActiveDeadline resync parked for an hour is not "waiting".
        self._added_at: Dict[str, float] = {}
        # Observer hook (set by the controller): fn(item, wait_seconds)
        # called after each successful get(), outside the queue lock.
        self.on_wait: Optional[Callable[[str, float], None]] = None

    def add(self, item: str) -> None:
        with self._cond:
            if item in self._queued:
                return
            if item in self._processing:
                self._dirty.add(item)
                return
            self._queued.add(item)
            self._added_at[item] = self._clock()
            self._queue.append(item)
            self._cond.notify()

    def add_after(self, item: str, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            self._seq += 1
            heapq.heappush(self._delayed, (self._clock() + delay, self._seq, item))
            self._cond.notify()

    def processing_items(self) -> List[str]:
        """Items currently held by workers (snapshot). The shard drain
        check uses this to answer "is any sync of shard S's jobs still in
        flight?" before a lease release — counting (depth()) cannot say
        WHICH keys are busy."""
        with self._cond:
            return list(self._processing)

    def depth(self) -> dict:
        """Queue introspection for the operator's /debugz endpoint."""
        with self._cond:
            return {
                "queued": len(self._queue),
                "processing": len(self._processing),
                "delayed": len(self._delayed),
                "failing": len(self._failures),
            }

    def add_rate_limited(self, item: str) -> None:
        with self._cond:
            failures = self._failures.get(item, 0)
            self._failures[item] = failures + 1
        self.add_after(item, min(self.BASE_DELAY * (2 ** failures), self.MAX_DELAY))

    def forget(self, item: str) -> None:
        with self._cond:
            self._failures.pop(item, None)

    def _drain_delayed_locked(self) -> Optional[float]:
        """Move due delayed items into the queue; return wait time to the next
        delayed item, or None."""
        now = self._clock()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            if item not in self._queued and item not in self._processing:
                self._queued.add(item)
                self._added_at[item] = now
                self._queue.append(item)
            elif item in self._processing:
                self._dirty.add(item)
        return (self._delayed[0][0] - now) if self._delayed else None

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        """Pop the next item, blocking up to timeout. Returns None on timeout
        or shutdown. The caller MUST call done(item) afterwards."""
        deadline = None if timeout is None else self._clock() + timeout
        item = None
        waited = 0.0
        with self._cond:
            while item is None:
                if self._shutdown:
                    return None
                next_delay = self._drain_delayed_locked()
                if self._queue:
                    item = self._queue.popleft()
                    self._queued.discard(item)
                    self._processing.add(item)
                    now = self._clock()
                    waited = now - self._added_at.pop(item, now)
                    break
                wait = next_delay
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait if wait is not None else 1.0)
        observer = self.on_wait
        if observer is not None:
            try:
                # Outside the lock: the observer writes metrics (its own
                # lock) and must never wedge or reenter the queue.
                observer(item, max(0.0, waited))
            except Exception:  # noqa: BLE001 — observability never blocks work
                pass
        return item

    def done(self, item: str) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._dirty.discard(item)
                if item not in self._queued:
                    self._queued.add(item)
                    self._added_at[item] = self._clock()
                    self._queue.append(item)
                    self._cond.notify()

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    def empty_and_idle(self) -> bool:
        """No immediate work: queue drained and nothing processing. Delayed
        items whose time has not come do NOT count — a far-future resync
        (deadline/TTL requeue) must not keep callers spinning."""
        with self._cond:
            self._drain_delayed_locked()
            return not self._queue and not self._processing
